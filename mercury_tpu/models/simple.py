"""Small debug CNN — not in the reference; used by tests and quick smokes
where a full ResNet is overkill (e.g. CPU-mesh CI). Includes BatchNorm so
the mutable-batch-stats path is exercised."""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp


class SmallCNN(nn.Module):
    num_classes: int = 10
    width: int = 16
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.compute_dtype)
        for i, w in enumerate((self.width, self.width * 2)):
            x = nn.Conv(w, (3, 3), strides=(2, 2), use_bias=False,
                        dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
            x = nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.compute_dtype, param_dtype=self.param_dtype,
                axis_name=self.bn_axis_name if train else None,
            )(x)
            x = nn.relu(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)
