"""CIFAR-style ResNet family in Flax.

Capability parity with ``pytorch_model.py:14-113``: ``BasicBlock`` (3×3-3×3,
BN after each conv, 1×1-conv shortcut on stride/width change, ``:14-36``),
``Bottleneck`` (1×1-3×3-1×1, expansion 4, ``:39-64``), and the CIFAR stem
``ResNet`` (conv3×3(3→64)+BN — no 7×7/maxpool — 4 stages of widths
64/128/256/512 at strides 1/2/2/2, global average pool, linear head,
``:67-97``). Depth configs per ``ResNet18/34/50/101/152`` (``:100-113``).

TPU-first details the reference never faced:
- activations/matmuls in ``compute_dtype`` (bfloat16 by default) with fp32
  params — MXU-friendly;
- BatchNorm can be cross-replica: pass ``bn_axis_name`` to psum batch stats
  over the data mesh axis (the reference silently lets per-worker BN stats
  drift — SURVEY.md §7 "hard parts"); ``None`` reproduces local/drifting BN.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp

ModuleDef = Any


class BasicBlock(nn.Module):
    """3×3-3×3 residual block (``pytorch_model.py:14-36``)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 1

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:  # 1×1-conv shortcut (:25-29)
            residual = self.conv(
                self.filters * self.expansion, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class Bottleneck(nn.Module):
    """1×1-3×3-1×1 bottleneck, expansion 4 (``pytorch_model.py:39-64``)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef
    expansion: int = 4

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), strides=(self.strides, self.strides))(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * self.expansion, (1, 1))(y)
        y = self.norm()(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * self.expansion, (1, 1), strides=(self.strides, self.strides)
            )(residual)
            residual = self.norm()(residual)
        return nn.relu(residual + y)


class ResNet(nn.Module):
    """CIFAR-stem ResNet (``pytorch_model.py:67-97``)."""

    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 10
    num_filters: int = 64
    compute_dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    bn_axis_name: Optional[str] = None  # "data" → cross-replica synced BN

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.compute_dtype,
            param_dtype=self.param_dtype,
            axis_name=self.bn_axis_name if train else None,
        )
        x = x.astype(self.compute_dtype)
        # CIFAR stem: 3×3 conv, stride 1, no maxpool (pytorch_model.py:72-73)
        x = conv(self.num_filters, (3, 3))(x)
        x = norm()(x)
        x = nn.relu(x)
        for i, n_blocks in enumerate(self.stage_sizes):  # strides 1/2/2/2 (:74-77)
            for j in range(n_blocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i, strides=strides, conv=conv, norm=norm
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global avg pool (≡ 4×4 avg pool, :94)
        x = nn.Dense(
            self.num_classes, dtype=self.compute_dtype, param_dtype=self.param_dtype
        )(x)
        return x.astype(jnp.float32)  # logits in fp32 for stable loss/softmax


# Depth configs (``pytorch_model.py:100-113``).
ResNet18 = partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
ResNet34 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
ResNet50 = partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck)
ResNet101 = partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck)
ResNet152 = partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck)
