"""VGG family in Flax.

Capability parity with ``pytorch_model.py:117-153``: the ``cfg`` depth tables
for VGG-11/13/16/19 (``:117-137``, conv widths with 'M' maxpools) and the
``VGG`` head (features → fc(·→128) → fc(128→classes), ``:140-153``).

Deliberate fixes over the reference (SURVEY.md "known defects — do not
replicate"): input channels are configurable and default to 3 — the
reference hardwires ``in_channels=1`` (``pytorch_model.py:119``), which
breaks on CIFAR's 3-channel input; and we return raw logits rather than the
reference's deprecated no-dim ``log_softmax`` (``:153``) — losses here take
logits.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp

# Conv width / 'M' maxpool tables (``pytorch_model.py:122-127``).
CFG = {
    "vgg11": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg13": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "vgg16": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
              512, 512, 512, "M"],
    "vgg19": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M", 512, 512, 512, 512,
              "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    """VGG feature stack + 2-layer MLP head (``pytorch_model.py:140-153``)."""

    cfg: Sequence[Union[int, str]]
    num_classes: int = 10
    hidden_dim: int = 128           # fc(·→128)→fc(128→classes) (:151-152)
    compute_dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.astype(self.compute_dtype)
        for v in self.cfg:  # _make_layers (:117-137): conv3×3+BN+ReLU / maxpool
            if v == "M":
                x = nn.max_pool(x, (2, 2), strides=(2, 2))
            else:
                x = nn.Conv(
                    int(v), (3, 3), padding=1, use_bias=False,
                    dtype=self.compute_dtype, param_dtype=self.param_dtype,
                )(x)
                x = nn.BatchNorm(
                    use_running_average=not train, momentum=0.9, epsilon=1e-5,
                    dtype=self.compute_dtype, param_dtype=self.param_dtype,
                    axis_name=self.bn_axis_name if train else None,
                )(x)
                x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))  # flatten (:148)
        x = nn.Dense(self.hidden_dim, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)


def make_vgg(name: str, **kwargs) -> VGG:
    """Build a VGG by name ('vgg11'|'vgg13'|'vgg16'|'vgg19'), mirroring the
    reference's ``VGG(vgg_name, num_classes)`` entry (``:140-143``)."""
    return VGG(cfg=CFG[name.lower()], **kwargs)
