"""BiLSTM + additive-attention sequence classifier in Flax.

Capability parity with the reference's speech/audio model: ``Attention``
(length-masked additive attention pooling over LSTM outputs,
``pytorch_model.py:156-206`` — mask built per-sequence ``:189-198``) and
``MyLSTM`` (two stacked bidirectional LSTMs, attention pooling after each,
concatenated pooled vectors, 2-layer MLP head, ``:208-241``).

TPU-first notes: recurrence runs as ``nn.RNN`` (a ``lax.scan`` under jit —
static-shape, compiler-friendly); variable lengths are handled with a mask
(no ragged shapes), exactly the masked-softmax the reference builds by hand.
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp


class AdditiveAttention(nn.Module):
    """Length-masked additive attention pooling (``pytorch_model.py:156-206``).

    ``score_t = v·tanh(W h_t)``; positions ≥ length get -inf before the
    softmax (the reference's per-sequence mask loop, ``:189-198``); output is
    the attention-weighted sum of the sequence.
    """

    attention_dim: int = 128
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, h, lengths=None):
        # h: [B, T, D]; lengths: [B] int or None (= full length)
        scores = nn.Dense(self.attention_dim, dtype=self.compute_dtype,
                          param_dtype=self.param_dtype)(h)
        scores = nn.tanh(scores)
        scores = nn.Dense(1, use_bias=False, dtype=self.compute_dtype,
                          param_dtype=self.param_dtype)(scores)[..., 0]  # [B, T]
        if lengths is not None:
            t = jnp.arange(h.shape[1])[None, :]
            mask = t < lengths[:, None]
            scores = jnp.where(mask, scores, -jnp.inf)
        weights = nn.softmax(scores, axis=-1)  # [B, T]
        return jnp.einsum("bt,btd->bd", weights, h), weights


class BiLSTMAttention(nn.Module):
    """Two stacked BiLSTMs, each attention-pooled; pooled vectors concat into
    a 2-layer MLP head (``MyLSTM``, ``pytorch_model.py:208-241``)."""

    num_classes: int
    hidden_dim: int = 128
    attention_dim: int = 128
    mlp_dim: int = 128
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    def _bilstm(self, name: str):
        fwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, param_dtype=self.param_dtype),
                     name=f"{name}_fwd")
        bwd = nn.RNN(nn.OptimizedLSTMCell(self.hidden_dim, param_dtype=self.param_dtype),
                     name=f"{name}_bwd")
        return nn.Bidirectional(fwd, bwd, name=name)

    @nn.compact
    def __call__(self, x, lengths=None, train: bool = True):
        # x: [B, T, F] float; lengths: [B] int32 or None
        x = x.astype(self.compute_dtype)
        seq_lengths = lengths
        h1 = self._bilstm("bilstm1")(x, seq_lengths=seq_lengths)   # [B, T, 2H]
        pooled1, _ = AdditiveAttention(
            self.attention_dim, self.compute_dtype, self.param_dtype, name="attn1"
        )(h1, lengths)
        h2 = self._bilstm("bilstm2")(h1, seq_lengths=seq_lengths)  # [B, T, 2H]
        pooled2, _ = AdditiveAttention(
            self.attention_dim, self.compute_dtype, self.param_dtype, name="attn2"
        )(h2, lengths)
        z = jnp.concatenate([pooled1, pooled2], axis=-1)           # [B, 4H] (:234)
        z = nn.Dense(self.mlp_dim, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(z)
        z = nn.relu(z)
        z = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(z)
        return z.astype(jnp.float32)
