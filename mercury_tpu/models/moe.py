"""Mixture-of-Experts MLP with expert parallelism.

The reference has no MoE or expert parallelism (SURVEY.md §2.5); this is a
beyond-parity extension completing the parallelism matrix
(dp/tp/pp/sp/**ep**). The layer is a Switch-style top-1-routed expert MLP:

- a gating projection scores ``num_experts`` experts per token; each token
  goes to its argmax expert, output scaled by the gate probability;
- every expert is a 2-layer GELU MLP whose weights live in stacked arrays
  ``[E, ...]`` — shard that leading axis over a mesh axis (``ep_axis``) and
  each device holds ``E/W`` experts;
- under expert parallelism the dispatch is the TPU-native all-to-all: each
  device buckets its local tokens by target expert into a fixed-capacity
  tensor (static shapes — XLA-friendly), ``lax.all_to_all`` exchanges
  expert-major slabs so every device receives exactly the tokens routed to
  *its* experts, applies them, and a second all-to-all returns the outputs
  to the tokens' home devices;
- tokens beyond an expert's capacity are dropped (output 0 for that token,
  the standard Switch overflow semantics); with enough capacity the EP
  layer is numerically identical to the dense reference path, which the
  tests pin.

A load-balancing auxiliary loss (Switch eq. 4: ``E · Σ_e f_e · p̄_e``) is
returned alongside the output.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from mercury_tpu.compat import axis_size
from jax import lax


class MoEMLP(nn.Module):
    """Top-1 (Switch) mixture-of-experts MLP over token features.

    Call with ``x: [B, T, D]`` (or ``[N, D]``); returns ``(y, aux_loss)``
    with ``y`` the same shape as ``x``.

    ``ep_axis``: mesh axis for expert parallelism — requires being inside
    ``shard_map`` with tokens sharded over the same axis and the stacked
    expert params sharded ``P(ep_axis)`` on their leading axis;
    ``num_experts`` must be divisible by the axis size. ``None`` = single
    device: same fixed-capacity bucketing (identical drop semantics, and
    O(N·capacity_factor) compute), minus the all-to-alls. The O(E·N)
    one-hot oracle is :meth:`reference`.
    """

    num_experts: int
    d_model: int
    mlp_ratio: int = 4
    capacity_factor: float = 1.25
    ep_axis: Optional[str] = None
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    def setup(self):
        e, d, h = self.num_experts, self.d_model, self.mlp_ratio * self.d_model
        if self.ep_axis is not None:
            # Inside shard_map each device holds its expert shard, so the
            # declared param shapes are per-device. Initialize params with
            # a dense twin (ep_axis=None) and shard their leading axis.
            w = axis_size(self.ep_axis)
            if e % w:
                raise ValueError(
                    f"num_experts {e} not divisible by axis size {w}"
                )
            e = e // w
        init = nn.initializers.lecun_normal()
        self.gate = nn.Dense(self.num_experts, dtype=self.compute_dtype,
                             param_dtype=self.param_dtype, name="gate")
        self.w_up = self.param("w_up", init, (e, d, h), self.param_dtype)
        self.b_up = self.param("b_up", nn.initializers.zeros, (e, h),
                               self.param_dtype)
        self.w_down = self.param("w_down", init, (e, h, d), self.param_dtype)
        self.b_down = self.param("b_down", nn.initializers.zeros, (e, d),
                                 self.param_dtype)

    def _expert_mlp(self, w_up, b_up, w_down, b_down, tokens):
        # tokens: [..., D] with a leading expert axis matching w_up's.
        h = jnp.einsum("e...d,edh->e...h", tokens,
                       w_up.astype(self.compute_dtype))
        h = nn.gelu(h + b_up.astype(self.compute_dtype)[(slice(None),)
                    + (None,) * (h.ndim - 2)])
        y = jnp.einsum("e...h,ehd->e...d", h,
                       w_down.astype(self.compute_dtype))
        return y + b_down.astype(self.compute_dtype)[(slice(None),)
                   + (None,) * (y.ndim - 2)]

    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        orig_shape = x.shape
        d = orig_shape[-1]
        tokens = x.reshape(-1, d).astype(self.compute_dtype)   # [N, D]
        n = tokens.shape[0]
        e = self.num_experts

        logits = self.gate(tokens)                              # [N, E]
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)                 # [N]
        gate_val = jnp.max(probs, axis=-1)                      # [N]
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)

        # Switch load-balancing loss: E · Σ_e (fraction routed)·(mean prob).
        frac = jnp.mean(onehot, axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        if self.ep_axis is not None:
            frac = lax.pmean(frac, self.ep_axis)
            mean_prob = lax.pmean(mean_prob, self.ep_axis)
        aux = e * jnp.sum(frac * mean_prob)

        capacity = int(math.ceil(self.capacity_factor * n / e))

        # Position of each token within its expert's bucket; overflow
        # drops. Integer cumsum: a float32 count would stop incrementing
        # exactly past 2^24 tokens.
        onehot_i = onehot.astype(jnp.int32)
        pos = jnp.sum(jnp.cumsum(onehot_i, axis=0) * onehot_i, axis=-1) - 1
        keep = (pos < capacity).astype(self.compute_dtype)      # [N]
        slot = jnp.clip(pos, 0, capacity - 1)

        # Scatter local tokens into [E, C, D] buckets.
        dispatch = jnp.zeros((e, capacity, d), self.compute_dtype)
        dispatch = dispatch.at[expert_idx, slot].add(
            tokens * keep[:, None]
        )

        if self.ep_axis is None:
            # Single-device path: same bucketing (so capacity semantics
            # match EP exactly), no exchange — each expert's MLP runs on
            # its C bucketed tokens, O(N·capacity_factor) compute. The
            # O(E·N) one-hot oracle lives in :meth:`reference`.
            out = self._expert_mlp(
                self.w_up, self.b_up, self.w_down, self.b_down, dispatch
            )                                                   # [E, C, D]
            y = out[expert_idx, slot] * (keep * gate_val)[:, None]
            return y.reshape(orig_shape).astype(x.dtype), aux

        # ---------------- expert-parallel dispatch ----------------
        w = axis_size(self.ep_axis)
        e_loc = e // w
        # Exchange expert-major slabs: [W, E_loc, C, D] — after all_to_all
        # the leading axis indexes the SOURCE device and E_loc are my
        # experts.
        dispatch = dispatch.reshape(w, e_loc, capacity, d)
        received = lax.all_to_all(dispatch, self.ep_axis, 0, 0, tiled=False)

        out = self._expert_mlp(
            self.w_up, self.b_up, self.w_down, self.b_down,
            received.transpose(1, 0, 2, 3).reshape(e_loc, w * capacity, d),
        )                                                       # [E_loc, W·C, D]
        out = out.reshape(e_loc, w, capacity, d).transpose(1, 0, 2, 3)

        # Route outputs back to the tokens' home devices.
        returned = lax.all_to_all(out, self.ep_axis, 0, 0, tiled=False)
        returned = returned.reshape(e, capacity, d)             # my tokens'
        y = returned[expert_idx, slot] * (keep * gate_val)[:, None]
        return y.reshape(orig_shape).astype(x.dtype), aux

    def reference(self, x) -> Tuple[jax.Array, jax.Array]:
        """O(E·N) one-hot oracle: every expert processes every token, the
        routed output is selected by one-hot combine. No capacity, no
        drops — the definitional top-1 semantics the bucketed paths are
        tested against (``ep_axis`` must be None)."""
        orig_shape = x.shape
        tokens = x.reshape(-1, orig_shape[-1]).astype(self.compute_dtype)
        e = self.num_experts
        probs = jax.nn.softmax(
            self.gate(tokens).astype(jnp.float32), axis=-1)
        expert_idx = jnp.argmax(probs, axis=-1)
        gate_val = jnp.max(probs, axis=-1)
        onehot = jax.nn.one_hot(expert_idx, e, dtype=jnp.float32)
        aux = e * jnp.sum(jnp.mean(onehot, axis=0) * jnp.mean(probs, axis=0))
        all_out = self._expert_mlp(
            self.w_up, self.b_up, self.w_down, self.b_down,
            jnp.broadcast_to(tokens, (e,) + tokens.shape),
        )                                                       # [E, N, D]
        y = jnp.einsum("ne,end->nd", onehot.astype(all_out.dtype), all_out)
        y = y * gate_val[:, None].astype(y.dtype)
        return y.reshape(orig_shape).astype(x.dtype), aux
