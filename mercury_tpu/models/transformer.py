"""Transformer sequence classifier in Flax.

An extension beyond the reference's model zoo (its only sequence model is
the BiLSTM+attention speech net, ``pytorch_model.py:208-241``): a standard
pre-LN Transformer encoder over ``[B, T, F]`` feature sequences, mean-pooled
into a classification head, trainable end-to-end through the Mercury
importance-sampled step like every other model in the zoo.

Long-context path: with ``sp_axis`` set and the module applied inside a
``shard_map`` whose sequence dimension is sharded over that mesh axis, every
self-attention runs sequence-parallel
(:mod:`mercury_tpu.parallel.sequence`) — by default blockwise **ring
attention** (K/V blocks stream around the ring via ``lax.ppermute`` while
each device keeps only its local sequence shard, so context length scales
with the number of devices), or Ulysses-style **all-to-all attention**
(``sp_impl="ulysses"``: reshard sequence → heads, dense attention per head
subset, reshard back; needs ``num_heads % axis_size == 0``). The LayerNorms, MLPs,
positional embeddings, and mean-pool are position-local (the pool's sum is
completed by the caller's ``psum``-friendly mean over the sharded axis —
see ``tests/test_sequence_parallel.py`` for the canonical harness).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax.numpy as jnp

from mercury_tpu.compat import axis_size
from jax import lax

from mercury_tpu.parallel.sequence import attention


class TransformerBlock(nn.Module):
    """Pre-LN encoder block: MHA (dense, ring, or ulysses — ``sp_impl``)
    + GELU MLP, residual both.

    With ``moe_experts`` set, the MLP becomes a Switch-style
    mixture-of-experts (:class:`~mercury_tpu.models.MoEMLP`); its
    load-balancing aux loss is recorded via ``self.sow("losses",
    "moe_aux", ...)`` — apply with ``mutable=["losses"]`` to collect it.
    ``moe_ep_axis`` shards the experts over a mesh axis (expert
    parallelism, inside ``shard_map``)."""

    num_heads: int
    d_model: int
    mlp_ratio: int = 4
    causal: bool = False
    sp_axis: Optional[str] = None
    sp_impl: str = "ring"
    moe_experts: Optional[int] = None
    moe_ep_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        # x: [B, T(_local), D]
        b, t, _ = x.shape
        head_dim = self.d_model // self.num_heads
        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        # Separate q/k/v projections (not one fused qkv Dense): under tensor
        # parallelism each [D, D] kernel column-splits on head boundaries,
        # so no resharding is needed before the per-head attention
        # (parallel/tensor.py; the fused layout would split mid-q/k/v).
        proj_kw = dict(features=self.d_model, dtype=self.compute_dtype,
                       param_dtype=self.param_dtype)
        q = nn.Dense(name="query", **proj_kw)(h)
        k = nn.Dense(name="key", **proj_kw)(h)
        v = nn.Dense(name="value", **proj_kw)(h)
        shape = (b, t, self.num_heads, head_dim)
        out = attention(q.reshape(shape), k.reshape(shape), v.reshape(shape),
                        causal=self.causal, sp_axis=self.sp_axis,
                        sp_impl=self.sp_impl)
        out = nn.Dense(self.d_model, dtype=self.compute_dtype,
                       param_dtype=self.param_dtype, name="proj")(
            out.reshape(b, t, self.d_model))
        x = x + out
        h = nn.LayerNorm(dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        if self.moe_experts is not None:
            from mercury_tpu.models.moe import MoEMLP

            h, aux = MoEMLP(
                num_experts=self.moe_experts, d_model=self.d_model,
                mlp_ratio=self.mlp_ratio, ep_axis=self.moe_ep_axis,
                capacity_factor=self.moe_capacity_factor,
                compute_dtype=self.compute_dtype,
                param_dtype=self.param_dtype, name="moe",
            )(h)
            self.sow("losses", "moe_aux", aux)
        else:
            h = nn.Dense(self.mlp_ratio * self.d_model, dtype=self.compute_dtype,
                         param_dtype=self.param_dtype)(h)
            h = nn.gelu(h)
            h = nn.Dense(self.d_model, dtype=self.compute_dtype,
                         param_dtype=self.param_dtype)(h)
        return x + h


class TransformerClassifier(nn.Module):
    """Encoder stack over feature sequences, mean-pooled into a linear head.

    ``sp_axis``: mesh axis the sequence dimension is sharded over
    (sequence-parallel attention per ``sp_impl`` — ``"ring"`` or
    ``"ulysses"`` — + ``psum``-completed mean pool); ``None`` = unsharded.
    """

    num_classes: int
    d_model: int = 128
    num_heads: int = 4
    num_layers: int = 2
    mlp_ratio: int = 4
    max_len: int = 2048
    causal: bool = False
    # Vision mode (ViT): with patch_size set, 4-D image input
    # [B, H, W, C] is patchified to a [B, (H/p)·(W/p), p²·C] token
    # sequence before the shared embed — so the WHOLE transformer stack
    # (and its tensor-/pipeline-parallel machinery, which shards the
    # blocks) applies unchanged to the image datasets.
    patch_size: Optional[int] = None
    sp_axis: Optional[str] = None
    sp_impl: str = "ring"
    moe_experts: Optional[int] = None
    moe_ep_axis: Optional[str] = None
    moe_capacity_factor: float = 1.25
    # Activation rematerialization: recompute each block's activations in
    # the backward pass instead of storing them (jax.checkpoint via
    # nn.remat) — trades ~1 extra forward of FLOPs for O(layers) less
    # activation memory, the standard long-context lever.
    remat: bool = False
    compute_dtype: jnp.dtype = jnp.float32
    param_dtype: jnp.dtype = jnp.float32

    # setup-style (not @nn.compact) so `embed`/`head` are individually
    # applyable: the pipeline-parallel runner (parallel/pipeline.py) reuses
    # them via `model.apply(..., method=...)` and stays definitionally
    # identical to the dense forward. Explicit `name=` keeps the param tree
    # identical to the original compact layout.
    @nn.nowrap
    # graftlint: disable=GL113 -- "inherit" is a copy-self.sp_axis sentinel, not an axis name
    def make_block(self, name=None, sp_axis="inherit") -> TransformerBlock:
        """The single source of truth for block construction — used by
        ``setup`` and by the pipeline-parallel runner
        (``parallel/pipeline.py``, on an unbound instance — hence
        ``nowrap``), so the two can never drift apart on block-affecting
        config."""
        # nn.remat lifts the whole block: its forward recomputes during
        # backprop (same params/variables tree, same numerics).
        cls = nn.remat(TransformerBlock) if self.remat else TransformerBlock
        return cls(
            num_heads=self.num_heads, d_model=self.d_model,
            mlp_ratio=self.mlp_ratio, causal=self.causal,
            sp_axis=self.sp_axis if sp_axis == "inherit" else sp_axis,
            sp_impl=self.sp_impl,
            moe_experts=self.moe_experts, moe_ep_axis=self.moe_ep_axis,
            moe_capacity_factor=self.moe_capacity_factor,
            compute_dtype=self.compute_dtype, param_dtype=self.param_dtype,
            name=name,
        )

    def setup(self):
        self.embed_proj = nn.Dense(self.d_model, dtype=self.compute_dtype,
                                   param_dtype=self.param_dtype, name="embed")
        self.pos_embed = self.param(
            "pos_embed",
            nn.initializers.normal(0.02),
            (self.max_len, self.d_model),
            self.param_dtype,
        )
        self.blocks = [
            self.make_block(name=f"block{i}") for i in range(self.num_layers)
        ]
        self.final_norm = nn.LayerNorm(dtype=self.compute_dtype,
                                       param_dtype=self.param_dtype,
                                       name="LayerNorm_0")
        self.head_proj = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                                  param_dtype=self.param_dtype, name="head")

    def embed(self, x):
        """Input projection + (globally offset) positional embedding.
        4-D image input is patchified first (``patch_size``)."""
        x = x.astype(self.compute_dtype)
        if x.ndim == 4:
            if self.patch_size is None:
                raise ValueError(
                    "4-D (image) input needs patch_size set (ViT mode)"
                )
            if self.sp_axis is not None:
                raise ValueError(
                    "sequence parallelism over raw images is unsupported: "
                    "patchify first, then shard the token sequence"
                )
            p = self.patch_size
            b, h, w, c = x.shape
            if h % p or w % p:
                raise ValueError(
                    f"image size {h}x{w} not divisible by patch_size {p}"
                )
            x = x.reshape(b, h // p, p, w // p, p, c)
            x = x.transpose(0, 1, 3, 2, 4, 5)
            x = x.reshape(b, (h // p) * (w // p), p * p * c)
        _, t, _ = x.shape
        x = self.embed_proj(x)
        pe = self.pos_embed.astype(self.compute_dtype)
        if self.sp_axis is None:
            global_len = t
            if global_len > self.max_len:
                raise ValueError(
                    f"sequence length {global_len} exceeds max_len="
                    f"{self.max_len}"
                )
            return x + pe[None, :t]
        global_len = t * axis_size(self.sp_axis)
        if global_len > self.max_len:
            raise ValueError(
                f"sequence length {global_len} exceeds max_len={self.max_len}"
            )
        if self.sp_impl == "zigzag":
            # Zigzag layout (parallel/sequence.py zigzag_order): rank i's
            # shard is global chunks (i, 2W-1-i) — the caller feeds tokens
            # permuted with zigzag_order, and the positional embedding
            # follows the same assignment (two chunk slices instead of one
            # contiguous run). Downstream this composes exactly: blocks
            # are pointwise over tokens, zigzag_ring_attention reconstructs
            # causal relations from the layout, and the head's mean pool
            # is permutation-invariant — so logits match the dense model
            # on the unpermuted sequence.
            if t % 2 != 0:
                raise ValueError(
                    f"zigzag layout needs an even local length, got {t}"
                )
            w = axis_size(self.sp_axis)
            i = lax.axis_index(self.sp_axis)
            c = t // 2
            pos = jnp.concatenate([
                lax.dynamic_slice_in_dim(pe, i * c, c, axis=0),
                lax.dynamic_slice_in_dim(pe, (2 * w - 1 - i) * c, c, axis=0),
            ], axis=0)
        else:
            # Contiguous layout: global positions for this sequence shard.
            pos = lax.dynamic_slice_in_dim(
                pe, lax.axis_index(self.sp_axis) * t, t, axis=0
            )
        return x + pos[None]

    def head(self, x):
        """Final LayerNorm + (axis-completed) mean pool + classifier."""
        x = self.final_norm(x)
        pooled = jnp.mean(x, axis=1)                       # [B, D] (local mean)
        if self.sp_axis is not None:
            # Complete the mean over the sharded sequence axis.
            pooled = lax.pmean(pooled, self.sp_axis)
        return self.head_proj(pooled).astype(jnp.float32)

    def __call__(self, x, train: bool = True):
        # x: [B, T(_local), F] float
        x = self.embed(x)
        for block in self.blocks:
            x = block(x)
        return self.head(x)
