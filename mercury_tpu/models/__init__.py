"""Flax model zoo — ResNet/VGG/MobileNetV2/BiLSTM-attention/Transformer.

``create_model`` is the factory the trainer uses (name-keyed, like the
reference's model selection global at ``pytorch_collab.py:25,255``).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from mercury_tpu.models.lstm import AdditiveAttention, BiLSTMAttention  # noqa: F401
from mercury_tpu.models.mobilenet import MobileNetV2  # noqa: F401
from mercury_tpu.models.resnet import (  # noqa: F401
    BasicBlock,
    Bottleneck,
    ResNet,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from mercury_tpu.models.moe import MoEMLP  # noqa: F401
from mercury_tpu.models.simple import SmallCNN  # noqa: F401
from mercury_tpu.models.transformer import (  # noqa: F401
    TransformerBlock,
    TransformerClassifier,
)
from mercury_tpu.models.vgg import CFG as VGG_CFG  # noqa: F401
from mercury_tpu.models.vgg import VGG, make_vgg  # noqa: F401

_RESNETS = {
    "resnet18": ResNet18,
    "resnet34": ResNet34,
    "resnet50": ResNet50,
    "resnet101": ResNet101,
    "resnet152": ResNet152,
}

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32, "float16": jnp.float16}


def create_model(
    name: str,
    num_classes: int = 10,
    compute_dtype: str = "bfloat16",
    param_dtype: str = "float32",
    bn_axis_name: Optional[str] = None,
    **kwargs,
):
    """Build a model by name.

    Names: ``resnet18/34/50/101/152``, ``vgg11/13/16/19``, ``mobilenetv2``,
    ``bilstm_attention``, ``transformer``, ``vit``. ``bn_axis_name`` enables
    cross-replica synced BatchNorm over the given mesh axis (ignored by
    models without BN).
    """
    name = name.lower()
    cd, pd = _DTYPES[compute_dtype], _DTYPES[param_dtype]
    if name in _RESNETS:
        return _RESNETS[name](
            num_classes=num_classes, compute_dtype=cd, param_dtype=pd,
            bn_axis_name=bn_axis_name, **kwargs,
        )
    if name in VGG_CFG:
        return make_vgg(
            name, num_classes=num_classes, compute_dtype=cd, param_dtype=pd,
            bn_axis_name=bn_axis_name, **kwargs,
        )
    if name in ("mobilenetv2", "mobilenet_v2"):
        return MobileNetV2(
            num_classes=num_classes, compute_dtype=cd, param_dtype=pd,
            bn_axis_name=bn_axis_name, **kwargs,
        )
    if name == "smallcnn":
        return SmallCNN(num_classes=num_classes, compute_dtype=cd, param_dtype=pd,
                        bn_axis_name=bn_axis_name, **kwargs)
    if name in ("bilstm_attention", "mylstm", "lstm"):
        return BiLSTMAttention(num_classes=num_classes, compute_dtype=cd,
                               param_dtype=pd, **kwargs)
    if name in ("transformer", "vit"):
        if name == "vit":
            # Vision transformer for the CIFAR-shaped datasets: patchified
            # image input through the SAME TransformerClassifier stack, so
            # Megatron TP shardings, pipeline staging, and MoE blocks
            # apply to image training unchanged. max_len defaults to the
            # 32×32 token count for the chosen patch size — pass max_len
            # explicitly for other image sizes.
            kwargs.setdefault("patch_size", 4)
            kwargs.setdefault("num_layers", 4)
            kwargs.setdefault("max_len", (32 // kwargs["patch_size"]) ** 2)
        return TransformerClassifier(num_classes=num_classes, compute_dtype=cd,
                                     param_dtype=pd, **kwargs)
    raise ValueError(f"unknown model {name!r}")
