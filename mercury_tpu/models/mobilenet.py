"""MobileNetV2 (CIFAR variant) in Flax.

The reference has **no** MobileNetV2 (SURVEY.md §2.3: "MobileNetV2 does not
exist in the reference"), but ``BASELINE.json`` config #4 benchmarks it, so
the model zoo adds the standard architecture (Sandler et al. 2018): inverted
residual blocks with linear bottlenecks, width 32→1280, expansion 6.

CIFAR adaptation (standard practice for 32×32 inputs): stride-1 stem and the
first two stride-2 stages reduced to stride 1, so the final feature map stays
≥4×4 on 32×32 images.
"""

from __future__ import annotations

from typing import Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp


class InvertedResidual(nn.Module):
    """Expand (1×1) → depthwise 3×3 → project (1×1), residual when shapes match."""

    filters: int
    strides: int
    expand: int
    compute_dtype: jnp.dtype
    param_dtype: jnp.dtype
    bn_axis_name: Optional[str]

    @nn.compact
    def __call__(self, x, train: bool = True):
        def bn():
            return nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.compute_dtype, param_dtype=self.param_dtype,
                axis_name=self.bn_axis_name if train else None,
            )

        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        y = x
        if self.expand != 1:
            y = nn.Conv(hidden, (1, 1), use_bias=False,
                        dtype=self.compute_dtype, param_dtype=self.param_dtype)(y)
            y = nn.relu6(bn()(y))
        y = nn.Conv(
            hidden, (3, 3), strides=(self.strides, self.strides),
            feature_group_count=hidden, use_bias=False,
            dtype=self.compute_dtype, param_dtype=self.param_dtype,
        )(y)
        y = nn.relu6(bn()(y))
        y = nn.Conv(self.filters, (1, 1), use_bias=False,
                    dtype=self.compute_dtype, param_dtype=self.param_dtype)(y)
        y = bn()(y)  # linear bottleneck — no activation
        if self.strides == 1 and in_ch == self.filters:
            y = y + x
        return y


# (expansion t, channels c, repeats n, stride s) — V2 paper Table 2, with the
# CIFAR stride adaptation in MobileNetV2.__call__.
_V2_CFG: Tuple[Tuple[int, int, int, int], ...] = (
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
)


class MobileNetV2(nn.Module):
    num_classes: int = 10
    width_mult: float = 1.0
    cifar_stem: bool = True  # stride-1 stem + first two down-stages at stride 1
    compute_dtype: jnp.dtype = jnp.bfloat16
    param_dtype: jnp.dtype = jnp.float32
    bn_axis_name: Optional[str] = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        def bn():
            return nn.BatchNorm(
                use_running_average=not train, momentum=0.9, epsilon=1e-5,
                dtype=self.compute_dtype, param_dtype=self.param_dtype,
                axis_name=self.bn_axis_name if train else None,
            )

        def c(ch):
            return max(8, int(ch * self.width_mult))

        x = x.astype(self.compute_dtype)
        stem_stride = 1 if self.cifar_stem else 2
        x = nn.Conv(c(32), (3, 3), strides=(stem_stride, stem_stride), use_bias=False,
                    dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        x = nn.relu6(bn()(x))
        downs_reduced = 0
        for t, ch, n, s in _V2_CFG:
            for i in range(n):
                stride = s if i == 0 else 1
                if self.cifar_stem and stride == 2 and downs_reduced < 2:
                    stride = 1
                    downs_reduced += 1
                x = InvertedResidual(
                    filters=c(ch), strides=stride, expand=t,
                    compute_dtype=self.compute_dtype, param_dtype=self.param_dtype,
                    bn_axis_name=self.bn_axis_name,
                )(x, train=train)
        x = nn.Conv(c(1280), (1, 1), use_bias=False,
                    dtype=self.compute_dtype, param_dtype=self.param_dtype)(x)
        x = nn.relu6(bn()(x))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=self.compute_dtype,
                     param_dtype=self.param_dtype)(x)
        return x.astype(jnp.float32)
