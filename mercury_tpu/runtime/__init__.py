"""Supervised host runtime: liveness, restarts, graceful degradation."""

from mercury_tpu.runtime.supervisor import HostSupervisor

__all__ = ["HostSupervisor"]
