"""HostSupervisor: the failure half of the control loop.

PR 6's anomaly engine *detects* trouble; this supervisor *acts on it*.
It watches the liveness of every managed host thread fleet (scorer
fleet, prefetch pipeline), restarts dead units with exponential backoff
under a restart budget, and — when the budget is exhausted — walks an
explicit degradation ladder for the importance-sampling plane instead
of taking the run down:

    level 0  ASYNC    scorer fleet refreshes the table in the background
    level 1  SYNC     the trainer thread scores chunks itself
                      (``ScorerFleet.score_once`` — no worker threads)
    level 2  FROZEN   no refresh at all; the table's in-graph staleness
                      decay keeps flattening it toward the EMA mean
    level 3  UNIFORM  the table is flattened to a constant, so the
                      inverse-CDF draw IS uniform sampling
                      (``sampler/is_active=0``)

No level transition retraces anything: the fused step program never
changes — only which host-side refresh path feeds the device table
(levels 0/1), whether it is fed at all (2), or whether its contents are
constant (3). This is the principled safe mode of arXiv:1803.00942:
when importance estimates can't be trusted, sample uniformly.

Recovery probing climbs back up: every ``probe_every`` steps a probe
callback (a trainer-thread ``score_once``) is attempted; each success
climbs one level, and the final climb into level 0 revives the worker
fleet with a fresh restart budget. Each probe *failure* at a degraded
level escalates one further level — a persistent fault therefore walks
the ladder deterministically to uniform sampling and stays there,
probing, until the fault clears.

Every transition (restart, degrade, recover) is logged, counted in the
``supervisor/*`` telemetry, and dumped as a flight record through the
anomaly engine's recorder, so the degraded-but-green run leaves a
complete post-mortem trail.

Decisions and restarts happen on the trainer thread via :meth:`tick`
(deterministic, testable). The optional monitor thread
(``poll_s > 0``, name ``mercury-supervisor``) only samples liveness
between ticks so a mid-interval death is timestamped; it never mutates
units or the ladder.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.runtime.supervisor")

__all__ = ["HostSupervisor", "LEVEL_NAMES", "BUDGET_BUCKETS"]

#: Degradation-ladder level names, index == level.
LEVEL_NAMES = ("async", "sync", "frozen", "uniform")

#: Restart-budget buckets of the graftlint Layer S model
#: (``lint/control.py`` extracts this tuple; its order is the
#: monotonicity order invariant GLS04 proves): ``fresh`` — no attempt
#: consumed; ``partial`` — some budget used; ``spent`` — all budget
#: used, exhaustion not yet handled; ``exhausted`` — the once-latch
#: fired. :meth:`HostSupervisor.summary` reports the live bucket so
#: ``/statusz`` shows the exact model-checker state.
BUDGET_BUCKETS = ("fresh", "partial", "spent", "exhausted")


class _Slo:
    """One registered service-level objective (mutable breach latch)."""

    __slots__ = ("name", "check_fn", "breached", "breaches",
                 "episode_event")

    def __init__(self, name: str,
                 check_fn: Callable[[], Optional[str]]) -> None:
        self.name = name
        self.check_fn = check_fn
        self.breached = False   # rising-edge latch: one degrade per event
        self.breaches = 0
        # Journal event id of the breach that opened the current episode;
        # the degrade it causes and the eventual release both parent to
        # it, so the whole episode is one chain in the event DAG.
        self.episode_event: Optional[str] = None


class _Unit:
    """One supervised thread fleet (mutable restart state)."""

    __slots__ = ("name", "alive_fn", "restart_fn", "escalates",
                 "restarts_used", "next_restart_t", "exhausted_handled",
                 "last_alive_t", "down_since_t", "last_fail_event")

    def __init__(self, name: str, alive_fn: Callable[[], bool],
                 restart_fn: Callable[[], None], escalates: bool) -> None:
        self.name = name
        self.alive_fn = alive_fn
        self.restart_fn = restart_fn
        self.escalates = escalates
        self.restarts_used = 0
        self.next_restart_t = 0.0
        self.exhausted_handled = False
        self.last_alive_t = time.monotonic()
        self.down_since_t: Optional[float] = None
        # Journal event id of this unit's most recent failed restart —
        # the causal parent of a later exhaustion event.
        self.last_fail_event: Optional[str] = None


class HostSupervisor:
    """Liveness + restart + degradation-ladder state machine.

    Wiring (``train/trainer.py``): units register with an ``alive``
    probe and a ``restart`` action; the sampler ladder gets a ``probe``
    (attempt one trainer-thread scoring round) and a ``revive`` (respawn
    the worker fleet) callback. The trainer calls :meth:`tick` once per
    fit iteration and merges :meth:`stats` at the log gate; it reads
    :meth:`level` to choose the refresh path. The writer's drain thread
    feeds :meth:`observe_record` (the anomaly observer path) so the
    supervisor sees every host metric record — its heartbeat of the
    metric plane itself.
    """

    def __init__(self, *, restart_budget: int = 3, backoff_s: float = 0.5,
                 probe_every: int = 200, poll_s: float = 0.0,
                 anomaly=None, journal=None, plan_provider=None) -> None:
        self._budget = max(int(restart_budget), 0)
        self._backoff_s = max(float(backoff_s), 0.0)
        self._probe_every = max(int(probe_every), 0)
        self._anomaly = anomaly
        # Auto-planner hook: a callable returning the active plan facts
        # ({"plan": name, "replans": n}) for status surfaces. Read-only —
        # the supervisor never drives a re-plan itself (that is the
        # restore_elastic path); it only reports the decision on
        # summary()/statusz next to the ladder state.
        self._plan_provider = plan_provider
        # Control-plane event journal (obs/events.py); None when off.
        # Its emit() is buffered, lock-leaf, and never blocks a tick.
        self._journal = journal
        # Event id of the most recent degrade — the causal parent of the
        # recovery probes (and their outcomes) that follow it.
        self._last_degrade_event: Optional[str] = None
        self._units: List[_Unit] = []
        self._slos: List[_Slo] = []
        self._probe_fn: Optional[Callable[[], None]] = None
        self._revive_fn: Optional[Callable[[], None]] = None
        # One lock guards all mutable supervisor state: tick() (trainer
        # thread), observe_record() (writer drain thread) and the
        # monitor thread all touch it.
        self._lock = threading.Lock()
        self._level = 0
        self._next_probe_step = 0
        self._restarts = 0
        self._degradations = 0
        self._recoveries = 0
        self._last_record_step = -1
        self._last_record_t = 0.0
        self._transitions: List[Dict[str, Any]] = []
        self._closed = False
        self._poll_s = max(float(poll_s), 0.0)
        self._thread: Optional[threading.Thread] = None
        if self._poll_s > 0.0:
            self._thread = threading.Thread(
                target=self._poll_loop, name="mercury-supervisor",
                daemon=True)
            self._thread.start()

    # ------------------------------------------------------------- wiring
    def register_unit(self, name: str, alive: Callable[[], bool],
                      restart: Callable[[], None],
                      escalates: bool = False) -> None:
        """Supervise a thread fleet. ``escalates=True`` routes its
        budget exhaustion into the degradation ladder (the scorer
        plane); False means exhaustion is terminal for that unit and
        its failure propagates to the caller (the prefetch plane —
        training cannot proceed without input)."""
        with self._lock:
            self._units.append(_Unit(name, alive, restart, escalates))

    def register_slo(self, name: str,
                     check: Callable[[], Optional[str]]) -> None:
        """Register a service-level objective. ``check`` returns a
        breach description while the SLO is violated and None while
        healthy; it is evaluated every :meth:`tick`. A breach walks the
        degradation ladder ONE level on its rising edge (latched — a
        persistent breach does not free-fall to uniform; clearing and
        re-breaching walks another level, and the recovery probe climbs
        back when the plane heals). The scorer service's backpressure +
        staleness SLOs (``slo_score_staleness_max``,
        ``scorer_queue_highwater``) enter the ladder here."""
        with self._lock:
            self._slos.append(_Slo(name, check))

    def set_ladder(self, probe: Callable[[], None],
                   revive: Callable[[], None]) -> None:
        """Install the recovery callbacks: ``probe`` attempts one
        trainer-thread scoring round (raises on failure); ``revive``
        respawns the async worker fleet for the final climb to
        level 0."""
        with self._lock:
            self._probe_fn = probe
            self._revive_fn = revive

    # ------------------------------------------------------------- queries
    def level(self) -> int:
        """Current degradation-ladder level (0..3). Lock-free read of a
        single published int — a stale read costs one iteration of the
        old refresh path, and tick() republishes every step."""
        return self._level  # graftlint: disable=GL120 -- single published int; stale read self-corrects next tick, all writes hold the lock

    def level_name(self) -> str:
        return LEVEL_NAMES[self.level()]

    def sampler_active(self) -> bool:
        """False once degraded all the way to uniform sampling."""
        return self.level() < 3

    # ---------------------------------------------------------------- tick
    def tick(self, step: int) -> None:
        """Per-iteration service (trainer thread): check unit liveness,
        restart within budget/backoff, escalate on exhaustion, and run
        the recovery probe on its cadence."""
        now = time.monotonic()
        with self._lock:
            units = list(self._units)
        for unit in units:
            if self._safe_alive(unit):
                with self._lock:
                    unit.last_alive_t = now
                    unit.down_since_t = None
                continue
            self._handle_down(unit, step, now)
        self._check_slos(step)
        self._maybe_probe(step)

    def _check_slos(self, step: int) -> None:
        with self._lock:
            slos = list(self._slos)
        for slo in slos:
            try:
                status = slo.check_fn()
            except Exception as exc:
                _log.warning("supervisor: SLO check %s raised: %s",
                             slo.name, exc)
                continue
            with self._lock:
                rising = status is not None and not slo.breached
                falling = status is None and slo.breached
                slo.breached = status is not None
                if rising:
                    slo.breaches += 1
                episode = slo.episode_event
                if falling:
                    slo.episode_event = None
            if rising:
                _log.warning("supervisor: SLO %s breached at step %d: %s",
                             slo.name, step, status)
                self._flight("supervisor_slo_breach", step, {
                    "slo": slo.name, "status": status,
                })
                breach_eid = self._journal_emit(
                    "supervisor/slo_breach", step,
                    detail={"slo": slo.name, "status": status})
                with self._lock:
                    slo.episode_event = breach_eid
                self._degrade(step, f"SLO {slo.name} breached: {status}",
                              parent=breach_eid)
            elif falling:
                self._journal_emit(
                    "supervisor/slo_release", step, parent=episode,
                    detail={"slo": slo.name})

    def request_restart(self, name: str, step: int) -> bool:
        """Synchronous restart of one unit (the pop()-failed hot path:
        the trainer cannot take another step without input, so it asks
        for the restart NOW rather than waiting for the next tick).
        Honors the budget; honors the backoff by sleeping it out (the
        pipeline is already stalled — a short deliberate wait beats a
        crash-loop against a still-broken source). Returns False when
        the budget is exhausted."""
        with self._lock:
            unit = self._find(name)
        if unit is None:
            return False
        if unit.restarts_used >= self._budget:
            self._note_exhausted(unit, step)
            return False
        wait = unit.next_restart_t - time.monotonic()
        if wait > 0:
            time.sleep(wait)
        return self._try_restart(unit, step)

    def report_failure(self, source: str, step: int,
                       exc: BaseException,
                       parent: Optional[str] = None) -> None:
        """A degraded-path action failed on the trainer thread (e.g. the
        level-1 sync refresh raised): escalate one level. ``parent``
        optionally names the journal event that caused the failure."""
        self._degrade(step, f"{source} failed: "
                            f"{type(exc).__name__}: {exc}", parent=parent)

    # ------------------------------------------------------ unit handling
    def _find(self, name: str) -> Optional[_Unit]:
        for u in self._units:  # graftlint: disable=GL120 -- lock-held helper: every caller wraps _find() in `with self._lock`; taking the non-reentrant lock here would deadlock
            if u.name == name:
                return u
        return None

    def _safe_alive(self, unit: _Unit) -> bool:
        try:
            return bool(unit.alive_fn())
        except Exception as exc:
            _log.warning("supervisor: alive probe for %s raised: %s",
                         unit.name, exc)
            return False

    def _handle_down(self, unit: _Unit, step: int, now: float) -> None:
        with self._lock:
            if unit.down_since_t is None:
                unit.down_since_t = now
            exhausted = unit.restarts_used >= self._budget
            backing_off = now < unit.next_restart_t
        if exhausted:
            self._note_exhausted(unit, step)
            return
        if backing_off:
            return
        self._try_restart(unit, step)

    def _try_restart(self, unit: _Unit, step: int) -> bool:
        with self._lock:
            unit.restarts_used += 1
            attempt = unit.restarts_used
            # Exponential backoff before the NEXT attempt may run.
            unit.next_restart_t = (time.monotonic()
                                   + self._backoff_s * (2 ** (attempt - 1)))
            self._restarts += 1
        try:
            unit.restart_fn()
        except Exception as exc:
            _log.warning("supervisor: restart %d/%d of %s FAILED: %s: %s",
                         attempt, self._budget, unit.name,
                         type(exc).__name__, exc)
            self._flight("supervisor_restart_failed", step, {
                "unit": unit.name, "attempt": attempt,
                "budget": self._budget,
                "error": f"{type(exc).__name__}: {exc}",
            })
            fail_eid = self._journal_emit(
                "supervisor/restart_failed", step,
                detail={"unit": unit.name, "attempt": attempt,
                        "budget": self._budget,
                        "error": f"{type(exc).__name__}: {exc}"})
            with self._lock:
                unit.last_fail_event = fail_eid
            return False
        with self._lock:
            unit.down_since_t = None
            unit.exhausted_handled = False
        _log.warning("supervisor: restarted %s (attempt %d/%d) at step %d",
                     unit.name, attempt, self._budget, step)
        self._flight("supervisor_restart", step, {
            "unit": unit.name, "attempt": attempt, "budget": self._budget,
        })
        self._journal_emit(
            "supervisor/restart", step,
            detail={"unit": unit.name, "attempt": attempt,
                    "budget": self._budget})
        return True

    def _note_exhausted(self, unit: _Unit, step: int) -> None:
        with self._lock:
            if unit.exhausted_handled:
                return
            unit.exhausted_handled = True
            escalates = unit.escalates
            fail_eid = unit.last_fail_event
        exhausted_eid = self._journal_emit(
            "supervisor/exhausted", step, parent=fail_eid,
            detail={"unit": unit.name, "budget": self._budget,
                    "escalates": escalates})
        if escalates:
            self._degrade(step, f"{unit.name} restart budget "
                                f"({self._budget}) exhausted",
                          parent=exhausted_eid)
        else:
            _log.warning(
                "supervisor: %s is down with its restart budget (%d) "
                "exhausted — its next failure propagates to the caller",
                unit.name, self._budget)
            self._flight("supervisor_exhausted", step, {
                "unit": unit.name, "budget": self._budget,
            })

    # ------------------------------------------------------------- ladder
    def _degrade(self, step: int, reason: str,
                 parent: Optional[str] = None) -> None:
        with self._lock:
            if self._level >= len(LEVEL_NAMES) - 1:
                return
            src = self._level
            self._level = src + 1
            dst = self._level
            self._degradations += 1
            self._transitions.append({
                "step": step, "from": LEVEL_NAMES[src],
                "to": LEVEL_NAMES[dst], "reason": reason,
            })
        _log.warning("supervisor: DEGRADE %s -> %s at step %d (%s)",
                     LEVEL_NAMES[src], LEVEL_NAMES[dst], step, reason)
        self._flight("supervisor_degrade", step, {
            "from": LEVEL_NAMES[src], "to": LEVEL_NAMES[dst],
            "reason": reason,
        })
        eid = self._journal_emit(
            "supervisor/degrade", step, parent=parent,
            detail={"from": LEVEL_NAMES[src],
                    "to": LEVEL_NAMES[dst], "reason": reason})
        with self._lock:
            self._last_degrade_event = eid

    def _recover(self, step: int, reason: str,
                 parent: Optional[str] = None) -> None:
        with self._lock:
            if self._level <= 0:
                return
            src = self._level
            self._level = src - 1
            dst = self._level
            self._recoveries += 1
            if dst == 0:
                # Back to nominal: the fleet earned a fresh budget.
                for u in self._units:
                    if u.escalates:
                        u.restarts_used = 0
                        u.exhausted_handled = False
                        u.next_restart_t = 0.0
            self._transitions.append({
                "step": step, "from": LEVEL_NAMES[src],
                "to": LEVEL_NAMES[dst], "reason": reason,
            })
        _log.warning("supervisor: RECOVER %s -> %s at step %d (%s)",
                     LEVEL_NAMES[src], LEVEL_NAMES[dst], step, reason)
        self._flight("supervisor_recover", step, {
            "from": LEVEL_NAMES[src], "to": LEVEL_NAMES[dst],
            "reason": reason,
        })
        self._journal_emit(
            "supervisor/recover", step, parent=parent,
            detail={"from": LEVEL_NAMES[src],
                    "to": LEVEL_NAMES[dst], "reason": reason})

    def _maybe_probe(self, step: int) -> None:
        with self._lock:
            # A still-breaching SLO pins the ladder: climbing back while
            # e.g. scorer staleness is over its max would oscillate
            # (recover, re-breach, degrade) without the plane having
            # healed — recovery waits for every SLO to clear.
            slo_pinned = any(s.breached for s in self._slos)
            due = (self._level > 0 and self._probe_every > 0
                   and not slo_pinned and step >= self._next_probe_step)
            if due:
                self._next_probe_step = step + self._probe_every
            probe = self._probe_fn
            revive = self._revive_fn
            level = self._level
        if not due or probe is None:
            return
        with self._lock:
            degrade_eid = self._last_degrade_event
        try:
            if level == 1 and revive is not None:
                # The last climb needs live workers, not just a working
                # score path: revive the fleet, then verify it scored.
                revive()
            probe()
        except Exception as exc:
            peid = self._journal_emit(
                "supervisor/probe_failed", step, parent=degrade_eid,
                detail={"level": level, "level_name": LEVEL_NAMES[level],
                        "error": f"{type(exc).__name__}: {exc}"})
            self.report_failure("recovery probe", step, exc, parent=peid)
            return
        peid = self._journal_emit(
            "supervisor/probe_ok", step, parent=degrade_eid,
            detail={"level": level, "level_name": LEVEL_NAMES[level]})
        self._recover(step, "recovery probe succeeded", parent=peid)

    # ------------------------------------------------- observer / monitor
    def observe_record(self, record: Dict[str, float]) -> None:
        """Writer-observer hook (drain thread): timestamp the metric
        plane's heartbeat. Never raises (the writer contract counts
        observer failures, but a supervisor that takes down telemetry
        would be absurd)."""
        try:
            with self._lock:
                self._last_record_step = int(record.get("step", -1))
                self._last_record_t = time.monotonic()
        except Exception:
            pass

    def _poll_loop(self) -> None:
        """Monitor thread: timestamp unit liveness between ticks. Reads
        the alive probes and stamps per-unit times under the lock —
        restarts and ladder moves stay on the trainer thread."""
        while not self._closed:
            now = time.monotonic()
            with self._lock:
                units = list(self._units)
            for unit in units:
                if self._safe_alive(unit):
                    with self._lock:
                        unit.last_alive_t = now
                else:
                    with self._lock:
                        if unit.down_since_t is None:
                            unit.down_since_t = now
            deadline = time.monotonic() + self._poll_s
            while not self._closed:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                time.sleep(min(left, 0.05))

    def close(self, timeout: float = 5.0) -> None:
        """Stop the monitor thread (idempotent; daemon, so a wedged
        probe never blocks exit)."""
        if self._closed:
            return
        self._closed = True
        if self._thread is not None:
            self._thread.join(timeout=timeout)

    # ----------------------------------------------------------- telemetry
    def _journal_emit(self, kind: str, step: int,
                      parent: Optional[str] = None,
                      detail: Optional[Dict[str, Any]] = None
                      ) -> Optional[str]:
        """Journal one control-plane event; returns its id (the causal
        parent for follow-on events) or None when journaling is off.
        Never raises — a full/failed journal must not affect the ladder."""
        if self._journal is None:
            return None
        try:
            return self._journal.emit(kind, step, parent=parent,
                                      detail=detail)
        except Exception as exc:  # defensive: journal never takes us down
            _log.warning("supervisor: journal emit %s failed: %s",
                         kind, exc)
            return None

    def _flight(self, kind: str, step: int, detail: Dict[str, Any]) -> None:
        if self._anomaly is None:
            return
        try:
            self._anomaly.dump_flight_record(kind, step, detail)
        except Exception as exc:  # defensive: recorder never takes us down
            _log.warning("supervisor: flight record %s failed: %s",
                         kind, exc)

    def stats(self) -> Dict[str, float]:
        """Log-gate scalars (keys registered in obs/registry.py)."""
        with self._lock:
            down = sum(1 for u in self._units
                       if u.down_since_t is not None)
            latched = sum(1 for s in self._slos if s.breached)
            return {
                "supervisor/level": float(self._level),
                "supervisor/restarts": float(self._restarts),
                "supervisor/degradations": float(self._degradations),
                "supervisor/recoveries": float(self._recoveries),
                "supervisor/units_down": float(down),
                "supervisor/slo_breaches": float(
                    sum(s.breaches for s in self._slos)),
                "supervisor/slo_latched": float(latched),
                "supervisor/probe_pinned": 1.0 if latched else 0.0,
                "sampler/is_active": 0.0 if self._level >= 3 else 1.0,
            }

    def _unit_bucket_locked(self, unit: _Unit) -> str:
        """The Layer S budget bucket this unit's concrete counters map
        to (caller holds the lock)."""
        if unit.exhausted_handled:
            return BUDGET_BUCKETS[3]
        if unit.restarts_used > 0 and unit.restarts_used >= self._budget:
            return BUDGET_BUCKETS[2]
        if unit.restarts_used > 0:
            return BUDGET_BUCKETS[1]
        return BUDGET_BUCKETS[0]

    def _model_state_locked(self) -> Dict[str, Any]:
        """The live (level, budget bucket, latch set, pin) tuple in the
        model checker's state space — ``state_id`` matches an id in the
        committed ``lint/control_plane.json`` machine, so a /statusz
        scrape names the exact state the GLS invariants were proved
        over. The bucket is the worst (highest-order) escalating
        unit's; latch slots are the model's ``slo{i}`` names in
        registration order, real SLO names ride alongside."""
        bucket = BUDGET_BUCKETS[0]
        for u in self._units:  # graftlint: disable=GL120 -- lock-held helper: every caller (model_state, summary) wraps _model_state_locked() in `with self._lock`; taking the non-reentrant lock here would deadlock
            if not u.escalates:
                continue
            b = self._unit_bucket_locked(u)
            if BUDGET_BUCKETS.index(b) > BUDGET_BUCKETS.index(bucket):
                bucket = b
        latched = [s.name for s in self._slos if s.breached]
        slots = [f"slo{i}" for i, s in enumerate(self._slos)
                 if s.breached]
        pinned = bool(latched)
        latch = "+".join(slots) if slots else "none"
        pin = "pinned" if pinned else "free"
        return {
            "level": self._level,
            "level_name": LEVEL_NAMES[self._level],
            "budget_bucket": bucket,
            "latched_slos": latched,
            "probe_pinned": pinned,
            "state_id": f"L{self._level}/{bucket}/{latch}/{pin}",
        }

    def model_state(self) -> Dict[str, Any]:
        """Public form of the model-checker state tuple."""
        with self._lock:
            return self._model_state_locked()

    def summary(self) -> Dict[str, Any]:
        """Cumulative view for flight-record context dumps."""
        plan = None
        if self._plan_provider is not None:
            try:
                plan = self._plan_provider()
            except Exception:  # never let a status read break the ladder
                plan = None
        with self._lock:
            return {
                "plan": plan,
                "level": self._level,
                "level_name": LEVEL_NAMES[self._level],
                "model_state": self._model_state_locked(),
                "restart_budget": self._budget,
                "restarts": self._restarts,
                "degradations": self._degradations,
                "recoveries": self._recoveries,
                "last_record_step": self._last_record_step,
                "transitions": list(self._transitions),
                "units": [
                    {"name": u.name, "restarts_used": u.restarts_used,
                     "down": u.down_since_t is not None}
                    for u in self._units
                ],
                "slos": [
                    {"name": s.name, "breached": s.breached,
                     "breaches": s.breaches}
                    for s in self._slos
                ],
            }
