"""mercury_tpu — a TPU-native (JAX/XLA/pjit) framework for stochastic
importance-sampled distributed SGD.

Re-implements the capabilities of the Mercury reference system (SenSys 2021,
"Mercury: Efficient On-Device Distributed DNN Training via Stochastic
Importance Sampling") as an idiomatic JAX framework:

- ``mercury_tpu.data``      — CIFAR-10/100 ingest, Dirichlet non-IID
  partitioning, index-carrying batch contract, on-device augmentation.
- ``mercury_tpu.models``    — Flax model zoo: ResNet-18/34/50/101/152 (CIFAR
  stem), VGG-11/13/16/19, MobileNetV2, BiLSTM+attention.
- ``mercury_tpu.sampling``  — the importance-sampling core: candidate scoring,
  EMA smoothing, with-replacement categorical draws, unbiased reweighting,
  and the group-wise sliding-window sampler.
- ``mercury_tpu.analysis``  — measure-then-decide: the exact variance
  probe (incl. the oracle bound) that predicts whether importance
  sampling can pay on a given (task, model) before you buy it.
- ``mercury_tpu.parallel``  — SPMD data parallelism over a ``jax.sharding.Mesh``
  with in-graph ``lax.psum`` gradient + importance-stat reduction, plus an
  explicit ``lax.ppermute`` ring allreduce.
- ``mercury_tpu.train``     — Trainer / train-step orchestration, config,
  eval, timing segments, checkpointing.
- ``mercury_tpu.utils``     — meters, pytree flatten/unflatten, stochastic
  quantization, metric logging.
"""

__version__ = "0.1.0"

from mercury_tpu.config import TrainConfig  # noqa: F401
from mercury_tpu.analysis import estimate_is_benefit  # noqa: F401
