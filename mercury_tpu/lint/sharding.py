"""graftlint Layer 3: sharding & memory auditor over the compiled plans.

Layer 2 (:mod:`mercury_tpu.lint.audit`) pins the *traced* program; this
layer AOT-**compiles** each parallelism plan (dp / zero / dp_bf16 / sp /
pp) on the CPU mesh and pins what XLA actually scheduled:

- **No implicit resharding.** Trace-level collectives whose name stack
  carries neither ``mercury_scoring`` nor ``mercury_grad_sync`` are
  counted per primitive, and the post-optimization HLO's collective ops
  (``all-reduce``/``all-gather``/``reduce-scatter``/``collective-permute``/
  ``all-to-all``) are counted per op and attributed to the named scopes
  via their preserved ``op_name`` metadata. Growth in the *unscoped*
  compiled counts is exactly a GSPMD resharding nobody asked for — the
  silent all-gather of a score table or ZeRO shard that erases the
  paper's scoring-FLOPs advantage.
- **Constraint coverage.** Every >1 MiB intermediate produced by the
  GSPMD-partitioned ``parallel/{fsdp,tensor,sequence,pipeline}.py``
  modules must be covered by an explicit ``with_sharding_constraint``
  (:func:`mercury_tpu.lint.memory.unconstrained_large_intermediates`;
  ``shard_map`` interiors are manual SPMD and exempt).
- **Monotone memory.** ``compiled.memory_analysis()`` byte counts per
  plan, ratcheted within a documented CPU-estimate tolerance
  (:data:`mercury_tpu.lint.memory.DEFAULT_TOLERANCE`).
- **bf16 scoring dataflow.** For plans that declare
  ``scoring_dtype="bfloat16"``, *no* f32 operand may reach a dot/conv
  inside the ``mercury_scoring`` scope — a dataflow analysis that walks
  each offending f32 value back through elementwise/convert chains to
  name the equation where f32 entered (strictly stronger than Layer 2's
  all-operands-f32 dot check, which a mixed bf16×f32 promotion slips
  past).
- **Axis-registry drift.** The AST rule GL113's hard-coded axis list
  (Layer 1 cannot import jax) must equal
  ``parallel/mesh.py::MESH_AXES``.

Budgets live in the committed ``lint/shard_budgets.json``; regenerate
with ``python -m mercury_tpu.lint --layer sharding --regen`` after an
intentional change. As in Layer 2, count/memory mismatches under a
*different* jax version than the budgets were recorded with demote to
warnings; the hard invariants (f32 leaks, unconstrained intermediates)
always fail loudly.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mercury_tpu.lint import golden
from mercury_tpu.lint import memory as lint_memory
from mercury_tpu.lint.audit import (
    COLLECTIVE_PRIMS,
    PLAN_NAMES,
    SCOPES,
    _BUILDERS,
    _name_stack,
    ensure_cpu_devices,
)
from mercury_tpu.lint.memory import iter_eqns_with_context, user_frame

SCHEMA = "graftlint_shard_budgets_v1"

#: Post-optimization HLO collective ops (the `-start` suffix covers the
#: async-pair form some passes emit).
_HLO_COLLECTIVE_RE = re.compile(
    r"=\s+\S+\s+"
    r"(all-reduce|all-gather|reduce-scatter|collective-permute|"
    r"all-to-all)(?:-start)?\(")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')

#: Elementwise / layout primitives the f32-origin walk looks *through*:
#: they propagate an existing f32 value rather than create one.
_F32_PASSTHROUGH = frozenset({
    "add", "sub", "mul", "div", "max", "min", "neg", "exp", "log",
    "tanh", "logistic", "rsqrt", "sqrt", "abs", "sign", "select_n",
    "broadcast_in_dim", "reshape", "transpose", "squeeze", "slice",
    "dynamic_slice", "concatenate", "pad", "rev", "gather",
    "stop_gradient", "copy", "pjit",
})


def default_shard_budgets_path() -> str:
    return os.path.join(os.path.dirname(__file__), "shard_budgets.json")


# --------------------------------------------------------------------------
# measurement
# --------------------------------------------------------------------------

@dataclass
class ShardMeasurement:
    plan: str
    config: Dict[str, Any]
    #: trace-level collective prims OUTSIDE both mercury scopes
    unscoped_trace_collectives: Dict[str, int] = field(default_factory=dict)
    #: sharding_constraint equations in the traced program
    sharding_constraints: int = 0
    #: compiled-HLO collective ops, total / per named scope / unscoped
    hlo_collectives: Dict[str, int] = field(default_factory=dict)
    hlo_scoped_collectives: Dict[str, Dict[str, int]] = field(
        default_factory=dict)
    hlo_unscoped_collectives: Dict[str, int] = field(default_factory=dict)
    #: compiled.memory_analysis() byte counts (lint/memory.py)
    memory: Dict[str, int] = field(default_factory=dict)
    #: hard-invariant violation messages (empty on a healthy plan)
    f32_scoring_leaks: List[str] = field(default_factory=list)
    unconstrained_intermediates: List[str] = field(default_factory=list)

    def config_hash(self) -> str:
        blob = json.dumps(self.config, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def as_budget(self) -> Dict[str, Any]:
        return {
            "config_hash": self.config_hash(),
            "config": self.config,
            "unscoped_trace_collectives": dict(
                sorted(self.unscoped_trace_collectives.items())),
            "sharding_constraints": self.sharding_constraints,
            "hlo_collectives": dict(sorted(self.hlo_collectives.items())),
            "hlo_scoped_collectives": {
                scope: dict(sorted(counts.items()))
                for scope, counts in sorted(
                    self.hlo_scoped_collectives.items())
            },
            "hlo_unscoped_collectives": dict(
                sorted(self.hlo_unscoped_collectives.items())),
            "memory": dict(sorted(self.memory.items())),
            "f32_scoring_leaks": len(self.f32_scoring_leaks),
            "unconstrained_intermediates":
                len(self.unconstrained_intermediates),
        }


def _count_hlo_collectives(hlo_text: str) -> Tuple[
        Dict[str, int], Dict[str, Dict[str, int]], Dict[str, int]]:
    """``(total, per_scope, unscoped)`` collective-op counts from
    post-optimization HLO. Scope attribution rides the ``op_name``
    metadata XLA preserves from jax named scopes."""
    total: Dict[str, int] = {}
    per_scope: Dict[str, Dict[str, int]] = {s: {} for s in SCOPES}
    unscoped: Dict[str, int] = {}
    for line in hlo_text.splitlines():
        m = _HLO_COLLECTIVE_RE.search(line)
        if not m:
            continue
        op = m.group(1)
        total[op] = total.get(op, 0) + 1
        om = _OP_NAME_RE.search(line)
        op_name = om.group(1) if om else ""
        hit = False
        for scope in SCOPES:
            if scope in op_name:
                sc = per_scope[scope]
                sc[op] = sc.get(op, 0) + 1
                hit = True
        if not hit:
            unscoped[op] = unscoped.get(op, 0) + 1
    return total, per_scope, unscoped


def _is_f32(var) -> bool:
    aval = getattr(var, "aval", None)
    return str(getattr(aval, "dtype", "")) == "float32"


def _f32_origin(var, producers: Dict[Any, Any], max_hops: int = 64) -> str:
    """Walk ``var`` back through its producer chain to the equation where
    f32 first appears (no f32 among that equation's inputs), for a
    readable leak message."""
    cur = var
    for _ in range(max_hops):
        eqn = producers.get(cur)
        if eqn is None:
            return "a function input / constant that is already f32"
        f32_ins = [v for v in eqn.invars
                   if hasattr(v, "count") and _is_f32(v)]
        if not f32_ins or eqn.primitive.name not in _F32_PASSTHROUGH:
            frame = user_frame(eqn)
            where = ""
            if frame:
                short = "/".join(
                    frame[0].replace(os.sep, "/").split("/")[-2:])
                where = f" at {short}:{frame[1]}"
            return f"f32 enters via `{eqn.primitive.name}`{where}"
        cur = f32_ins[0]
    return "an f32 chain deeper than the walk limit"


def f32_scoring_leaks(closed, plan: str = "?") -> List[str]:
    """Dataflow dtype check for bf16 scoring: one message per f32 operand
    reaching a dot/conv inside the ``mercury_scoring`` scope."""
    producers: Dict[Any, Any] = {}
    scoring_compute: List[Any] = []
    for eqn, _ in iter_eqns_with_context(closed):
        for v in eqn.outvars:
            if hasattr(v, "count"):
                producers[v] = eqn
        if eqn.primitive.name in ("dot_general", "conv_general_dilated") \
                and "mercury_scoring" in _name_stack(eqn):
            scoring_compute.append(eqn)

    leaks: List[str] = []
    for eqn in scoring_compute:
        for v in eqn.invars:
            if not _is_f32(v):
                continue
            aval = getattr(v, "aval", None)
            shape = list(getattr(aval, "shape", ()))
            origin = (_f32_origin(v, producers)
                      if hasattr(v, "count")
                      else "an f32 literal")
            leaks.append(
                f"plan {plan}: f32{shape} operand reaches "
                f"{eqn.primitive.name} inside mercury_scoring — {origin} "
                "(bf16 scoring region; the upcast erases the scoring "
                "FLOP savings)")
    return leaks


def measure_shard_step(step_fn, args: Tuple, plan: str,
                       config: Dict[str, Any]) -> ShardMeasurement:
    """Trace *and compile* ``step_fn(*args)`` (AOT, no execution) and
    collect the Layer 3 facts."""
    import jax

    m = ShardMeasurement(plan=plan, config=config)

    closed = jax.make_jaxpr(step_fn)(*args)
    for eqn, _ in iter_eqns_with_context(closed):
        name = eqn.primitive.name
        if name == "sharding_constraint":
            m.sharding_constraints += 1
        elif name in COLLECTIVE_PRIMS:
            stack = _name_stack(eqn)
            if not any(scope in stack for scope in SCOPES):
                m.unscoped_trace_collectives[name] = \
                    m.unscoped_trace_collectives.get(name, 0) + 1
    if str(config.get("scoring_dtype", "")) == "bfloat16":
        m.f32_scoring_leaks = f32_scoring_leaks(closed, plan)
    m.unconstrained_intermediates = \
        lint_memory.unconstrained_large_intermediates(closed)

    lower_fn = step_fn if hasattr(step_fn, "lower") else jax.jit(step_fn)
    compiled = lower_fn.lower(*args).compile()
    hlo_text = compiled.as_text()
    (m.hlo_collectives, m.hlo_scoped_collectives,
     m.hlo_unscoped_collectives) = _count_hlo_collectives(hlo_text)
    m.memory = lint_memory.memory_profile(compiled)
    return m


def measure_shard_plan(plan: str) -> ShardMeasurement:
    step, args, config = _BUILDERS[plan]()
    return measure_shard_step(step, args, plan, config)


# --------------------------------------------------------------------------
# hard invariants (budgets-file independent)
# --------------------------------------------------------------------------

def check_shard_invariants(m: ShardMeasurement) -> List[str]:
    errors: List[str] = []
    for leak in m.f32_scoring_leaks:
        errors.append(leak)
    for msg in m.unconstrained_intermediates:
        errors.append(f"plan {m.plan}: {msg}")
    return errors


def check_axis_registry() -> List[str]:
    """GL113's stdlib-side axis list must equal parallel/mesh.py's
    canonical MESH_AXES (Layer 1 cannot import jax to read it, so Layer 3
    owns the anti-drift check)."""
    from mercury_tpu.lint.rules import _MESH_AXES
    from mercury_tpu.parallel.mesh import MESH_AXES

    if tuple(_MESH_AXES) != tuple(MESH_AXES):
        return [
            f"axis-registry drift: lint/rules.py _MESH_AXES "
            f"{tuple(_MESH_AXES)} != parallel/mesh.py MESH_AXES "
            f"{tuple(MESH_AXES)} — update the rules.py mirror (GL113 "
            "would enforce a stale axis set)"]
    return []


# --------------------------------------------------------------------------
# budgets file
# --------------------------------------------------------------------------

def shard_budgets_doc(measurements: Sequence[ShardMeasurement],
                      ) -> Dict[str, Any]:
    return {
        "schema": SCHEMA,
        "provenance": golden.provenance(
            "python -m mercury_tpu.lint --layer sharding --regen",
            extra={"memory_tolerance": lint_memory.DEFAULT_TOLERANCE}),
        "plans": {m.plan: m.as_budget() for m in measurements},
    }


def write_shard_budgets(measurements: Sequence[ShardMeasurement],
                        path: Optional[str] = None) -> str:
    return golden.write_golden(path or default_shard_budgets_path(),
                               shard_budgets_doc(measurements))


def load_shard_budgets(path: Optional[str] = None) -> Dict[str, Any]:
    return golden.load_golden(path or default_shard_budgets_path(),
                              SCHEMA, "--layer sharding --regen")


_diff_counts = golden.diff_counts


def compare_shard_budgets(measurements: Sequence[ShardMeasurement],
                          budgets: Dict[str, Any],
                          ) -> Tuple[List[str], List[str]]:
    """Diff measurements against the committed shard budgets; same
    error/warning split as Layer 2 (foreign jax version demotes count and
    memory diffs — HLO scheduling drifts across releases — while the hard
    invariants always stay errors)."""
    import jax

    errors: List[str] = []
    warnings: List[str] = []
    provenance = budgets.get("provenance", {})
    recorded_jax = provenance.get("jax")
    tolerance = float(provenance.get(
        "memory_tolerance", lint_memory.DEFAULT_TOLERANCE))
    version_match = recorded_jax == jax.__version__
    if not version_match:
        warnings.append(
            f"shard budgets recorded under jax {recorded_jax}, running "
            f"{jax.__version__}: collective/memory diffs demoted to "
            "warnings — regenerate shard_budgets.json on the pinned "
            "version")

    plans = budgets.get("plans", {})
    for m in measurements:
        errors.extend(check_shard_invariants(m))
        budget = plans.get(m.plan)
        if budget is None:
            errors.append(f"plan {m.plan}: no committed shard budget — "
                          "run --layer sharding --regen and review the "
                          "diff")
            continue
        soft: List[str] = []
        if budget.get("config_hash") != m.config_hash():
            soft.append(
                f"  config_hash expected {budget.get('config_hash')}, "
                f"got {m.config_hash()} (the audited config changed — "
                "every downstream diff follows from this)")
        soft.extend(_diff_counts(
            "unscoped_trace_collectives",
            budget.get("unscoped_trace_collectives", {}),
            m.unscoped_trace_collectives))
        if budget.get("sharding_constraints", 0) != m.sharding_constraints:
            e = budget.get("sharding_constraints", 0)
            g = m.sharding_constraints
            soft.append(
                f"  sharding_constraints expected {e}, got {g} "
                f"({g - e:+d})"
                + (" — a with_sharding_constraint was dropped; the "
                   "layout it pinned is now GSPMD's choice"
                   if g < e else ""))
        soft.extend(_diff_counts("hlo_collectives",
                                 budget.get("hlo_collectives", {}),
                                 m.hlo_collectives))
        for scope in SCOPES:
            soft.extend(_diff_counts(
                f"hlo_scoped_collectives[{scope}]",
                budget.get("hlo_scoped_collectives", {}).get(scope, {}),
                m.hlo_scoped_collectives.get(scope, {})))
        unscoped_diff = _diff_counts(
            "hlo_unscoped_collectives",
            budget.get("hlo_unscoped_collectives", {}),
            m.hlo_unscoped_collectives)
        for line in unscoped_diff:
            soft.append(line + "  <- implicit resharding outside the "
                               "mercury scopes")
        mem_errors, mem_warnings = lint_memory.compare_memory(
            m.plan, budget.get("memory", {}), m.memory, tolerance)
        soft.extend(mem_errors)
        warnings.extend(f"plan {m.plan}:{w}" for w in mem_warnings)
        if soft:
            header = (f"plan {m.plan}: compiled program deviates from "
                      "committed shard budget:")
            block = [header] + soft + [
                "  (intentional change? regenerate: python -m "
                "mercury_tpu.lint --layer sharding --regen)"]
            (errors if version_match else warnings).extend(block)
    return errors, warnings


def run_sharding_audit(plans: Sequence[str] = PLAN_NAMES,
                       budgets_path: Optional[str] = None,
                       regen: bool = False,
                       diff_out: Optional[str] = None,
                       ) -> Tuple[List[str], List[str]]:
    """Measure the requested plans' compiled programs and either record
    (``regen=True``) or verify them against the committed shard budgets.
    Returns ``(errors, warnings)``; empty errors means the audit
    passed."""
    ensure_cpu_devices()
    errors: List[str] = list(check_axis_registry())
    measurements = [measure_shard_plan(p) for p in plans]
    if regen:
        path = write_shard_budgets(measurements, budgets_path)
        for m in measurements:
            errors.extend(check_shard_invariants(m))
        return errors, [f"shard budgets written to {path}"]
    budgets = load_shard_budgets(budgets_path)
    cmp_errors, warnings = compare_shard_budgets(measurements, budgets)
    errors.extend(cmp_errors)
    if diff_out and (errors or warnings):
        golden.write_diff_file(diff_out, "graftlint sharding diff",
                               errors, warnings)
    return errors, warnings
