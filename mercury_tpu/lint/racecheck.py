"""TSan-lite: runtime race / thread-leak harness (pure stdlib).

The static side of Layer C (lint/concurrency.py) proves lock discipline
where it can and deliberately leaves the single-writer publish patterns
(whole-tuple ``_snap`` swaps, monotonic counters, ``_closed`` flags) to
runtime checking. This module is that runtime check: a stress test wraps
live objects in a :class:`RaceMonitor`, hammers them from several
threads, and the monitor reports every attribute that two threads
touched (at least one write) without both holding an instrumented lock.

It is *happens-before-free* by design — no vector clocks, just "was any
watched lock held at the access" — so it over-reports code whose safety
comes from ordering rather than locking. That is intentional: the
harness runs on objects the caller nominates, and the caller declares
which attributes are supposed to be lock-guarded.

Usage::

    from mercury_tpu.lint.racecheck import RaceMonitor, ThreadLeakGuard

    mon = RaceMonitor()
    mon.watch(writer, attrs=("errors", "dropped"), locks=("_lock",))
    with mon:
        ... hammer writer from threads ...
    assert not mon.races()

    guard = ThreadLeakGuard()          # snapshot live threads
    ... run the suspect code ...
    assert not guard.strays()          # non-daemon leftovers fail

The conftest-wide leak fixture (tests/conftest.py) is built on
:class:`ThreadLeakGuard`; opt a test out with the ``thread_leak_ok``
marker when it legitimately parks daemon helpers (the slow distributed
matrix).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple

__all__ = [
    "InstrumentedLock",
    "InstrumentedQueue",
    "RaceMonitor",
    "RaceReport",
    "ThreadLeakGuard",
]

# RaceMonitor state lives here, keyed by id(obj), NOT on the watched
# object: the generated __getattribute__ override must never read an
# attribute of the instance it instruments (infinite recursion).
_MONITOR_STATE: Dict[int, "_WatchState"] = {}
_STATE_LOCK = threading.Lock()

# How many watched-object lock tokens the current thread holds. Using a
# single count per thread (rather than per lock) deliberately treats a
# Condition built on the object's lock as the same guard — matching the
# Condition(self._lock) aliasing the static layer applies.
_HELD = threading.local()


def _held_count() -> int:
    return getattr(_HELD, "count", 0)


def _push_held() -> None:
    _HELD.count = _held_count() + 1


def _pop_held() -> None:
    _HELD.count = max(0, _held_count() - 1)


class InstrumentedLock:
    """Proxy around a ``Lock`` / ``RLock`` / ``Condition`` that tracks
    whether the current thread holds it. Delegates everything else
    (``wait``/``notify``/…) to the wrapped object so Condition protocol
    keeps working."""

    def __init__(self, inner: Any) -> None:
        self._inner = inner

    def acquire(self, *args: Any, **kwargs: Any) -> bool:
        got = self._inner.acquire(*args, **kwargs)
        if got:
            _push_held()
        return got

    def release(self) -> None:
        _pop_held()
        self._inner.release()

    def __enter__(self) -> "InstrumentedLock":
        self._inner.__enter__()
        _push_held()
        return self

    def __exit__(self, *exc: Any) -> Any:
        _pop_held()
        return self._inner.__exit__(*exc)

    def wait(self, timeout: Optional[float] = None) -> bool:
        # Condition.wait releases the underlying lock for the wait —
        # mirror that in the held count so accesses made by OTHER
        # threads during our wait are not misattributed.
        _pop_held()
        try:
            return self._inner.wait(timeout)
        finally:
            _push_held()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


@dataclass
class _AttrSide:
    guarded_read: bool = False
    naked_read: bool = False
    guarded_write: bool = False
    naked_write: bool = False
    reads: int = 0
    writes: int = 0


@dataclass
class _WatchState:
    attrs: Tuple[str, ...]
    # (attr, thread ident) -> what that thread did to the attr
    sides: Dict[Tuple[str, int], _AttrSide] = field(default_factory=dict)
    lock: threading.Lock = field(default_factory=threading.Lock)

    def record(self, attr: str, write: bool) -> None:
        key = (attr, threading.get_ident())
        guarded = _held_count() > 0
        with self.lock:
            side = self.sides.get(key)
            if side is None:
                side = self.sides[key] = _AttrSide()
            if write:
                side.writes += 1
                if guarded:
                    side.guarded_write = True
                else:
                    side.naked_write = True
            else:
                side.reads += 1
                if guarded:
                    side.guarded_read = True
                else:
                    side.naked_read = True


@dataclass(frozen=True)
class RaceReport:
    """One attribute two threads raced on."""

    obj: str
    attr: str
    threads: int
    writes: int
    reads: int

    def __str__(self) -> str:
        return (f"race on {self.obj}.{self.attr}: {self.threads} "
                f"threads, {self.writes} writes / {self.reads} reads "
                f"with at least one unsynchronized side")


class InstrumentedQueue:
    """queue.Queue stand-in recording op counts, for queue-discipline
    stress assertions (puts that blocked, gets that timed out)."""

    def __init__(self, inner: Any) -> None:
        self._inner = inner
        self._oplock = threading.Lock()
        self.ops: Dict[str, int] = {
            "put": 0, "put_nowait": 0, "get": 0, "get_nowait": 0,
            "put_blocked": 0, "get_timeout": 0,
        }

    def _bump(self, op: str) -> None:
        with self._oplock:
            self.ops[op] += 1

    def put(self, item: Any, block: bool = True,
            timeout: Optional[float] = None) -> None:
        self._bump("put")
        if block and timeout is None and self._inner.full():
            self._bump("put_blocked")
        self._inner.put(item, block=block, timeout=timeout)

    def put_nowait(self, item: Any) -> None:
        self._bump("put_nowait")
        self._inner.put_nowait(item)

    def get(self, block: bool = True,
            timeout: Optional[float] = None) -> Any:
        self._bump("get")
        try:
            return self._inner.get(block=block, timeout=timeout)
        except Exception:
            if timeout is not None:
                self._bump("get_timeout")
            raise

    def get_nowait(self) -> Any:
        self._bump("get_nowait")
        return self._inner.get_nowait()

    def __getattr__(self, name: str) -> Any:
        return getattr(self._inner, name)


def _make_watched_class(base: type) -> type:
    """A subclass of ``base`` whose attribute hooks report to the
    id-keyed registry. Generated per base class; the instance is
    restored to its original class when the monitor exits."""

    def __getattribute__(self: Any, name: str) -> Any:
        state = _MONITOR_STATE.get(id(self))
        if state is not None and name in state.attrs:
            state.record(name, write=False)
        return base.__getattribute__(self, name)

    def __setattr__(self: Any, name: str, value: Any) -> None:
        state = _MONITOR_STATE.get(id(self))
        if state is not None and name in state.attrs:
            state.record(name, write=True)
        base.__setattr__(self, name, value)

    return type(f"_Watched_{base.__name__}", (base,), {
        "__getattribute__": __getattribute__,
        "__setattr__": __setattr__,
    })


class RaceMonitor:
    """Watches nominated attributes of live objects for cross-thread
    unsynchronized access. Context manager: instrumentation is applied
    on ``__enter__`` and fully reverted on ``__exit__``."""

    def __init__(self) -> None:
        self._watched: List[Tuple[Any, Tuple[str, ...],
                                  Tuple[str, ...]]] = []
        self._applied: List[Tuple[Any, type, List[Tuple[str, Any]]]] = []
        self._retained: Dict[int, _WatchState] = {}
        self._active = False

    def watch(self, obj: Any, attrs: Sequence[str],
              locks: Sequence[str] = ()) -> "RaceMonitor":
        """Register ``obj``: record accesses to ``attrs``; accesses made
        while any of the ``locks`` attributes (Lock/RLock/Condition) is
        held by the accessing thread count as guarded."""
        if self._active:
            raise RuntimeError("watch() before entering the monitor")
        self._watched.append((obj, tuple(attrs), tuple(locks)))
        return self

    def __enter__(self) -> "RaceMonitor":
        self._active = True
        for obj, attrs, locks in self._watched:
            original_cls = type(obj)
            replaced: List[Tuple[str, Any]] = []
            for lock_attr in locks:
                inner = getattr(obj, lock_attr)
                replaced.append((lock_attr, inner))
                object.__setattr__(obj, lock_attr,
                                   InstrumentedLock(inner))
            with _STATE_LOCK:
                _MONITOR_STATE[id(obj)] = _WatchState(attrs=attrs)
            object.__setattr__(obj, "__class__",
                               _make_watched_class(original_cls))
            self._applied.append((obj, original_cls, replaced))
        return self

    def __exit__(self, *exc: Any) -> None:
        for obj, original_cls, replaced in self._applied:
            object.__setattr__(obj, "__class__", original_cls)
            for lock_attr, inner in replaced:
                object.__setattr__(obj, lock_attr, inner)
            with _STATE_LOCK:
                state = _MONITOR_STATE.pop(id(obj), None)
            if state is not None:
                # keep the tallies queryable after exit — the common
                # shape is assert-not-races() once the region ends
                self._retained[id(obj)] = state
        self._applied.clear()
        self._active = False

    def races(self) -> List[RaceReport]:
        """Attributes with ≥2 threads, ≥1 write, and at least one side
        unsynchronized (both-guarded access pairs are clean)."""
        reports: List[RaceReport] = []
        for obj, attrs, _locks in self._watched:
            state = _MONITOR_STATE.get(id(obj)) or self._retained.get(
                id(obj))
            if state is None:
                continue
            by_attr: Dict[str, List[_AttrSide]] = {}
            with state.lock:
                for (attr, _tid), side in state.sides.items():
                    by_attr.setdefault(attr, []).append(side)
            for attr, sides in sorted(by_attr.items()):
                if len(sides) < 2:
                    continue
                if not any(s.writes for s in sides):
                    continue
                # clean only when every participating side was always
                # guarded for everything it did
                naked = any(s.naked_read or s.naked_write
                            for s in sides)
                if not naked:
                    continue
                reports.append(RaceReport(
                    obj=type(obj).__name__.replace("_Watched_", ""),
                    attr=attr,
                    threads=len(sides),
                    writes=sum(s.writes for s in sides),
                    reads=sum(s.reads for s in sides)))
        return reports

class ThreadLeakGuard:
    """Snapshot the live threads now; later, report strays.

    ``strays()`` grace-joins new non-daemon threads briefly (finishing
    threads are not leaks) and returns whatever is still alive. Daemon
    threads are reported separately via ``daemon_strays()`` — they
    cannot wedge interpreter exit, but a test that silently leaves a
    drain loop running is still polluting its neighbours.
    """

    def __init__(self, grace_s: float = 2.0) -> None:
        self.grace_s = grace_s
        self._baseline: Set[int] = {
            t.ident for t in threading.enumerate() if t.ident is not None}

    def _new_threads(self) -> List[threading.Thread]:
        return [t for t in threading.enumerate()
                if t.ident is not None and t.ident not in self._baseline
                and t is not threading.current_thread()]

    def strays(self) -> List[threading.Thread]:
        """Non-daemon threads started after the snapshot and still
        alive after a bounded grace join."""
        fresh = [t for t in self._new_threads() if not t.daemon]
        deadline_each = self.grace_s / max(1, len(fresh)) if fresh else 0
        still: List[threading.Thread] = []
        for t in fresh:
            t.join(timeout=deadline_each)
            if t.is_alive():
                still.append(t)
        return still

    def daemon_strays(self) -> List[threading.Thread]:
        """Daemon threads started after the snapshot and still alive
        (no join — daemons may legitimately park in their run loop)."""
        return [t for t in self._new_threads() if t.daemon and
                t.is_alive()]

    def check(self) -> None:
        """Raise AssertionError naming any non-daemon stray."""
        still = self.strays()
        if still:
            names = ", ".join(sorted(t.name for t in still))
            raise AssertionError(
                f"thread leak: non-daemon threads still alive after "
                f"{self.grace_s:.1f}s grace: {names}")
