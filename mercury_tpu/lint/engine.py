"""graftlint Layer 1 driver: file discovery, suppression handling, output.

Pure stdlib (no jax import — see :mod:`mercury_tpu.lint.rules`).

Suppression syntax, parsed from the token stream so strings containing
the marker don't count::

    x = noisy()  # graftlint: disable=GL101 -- deliberate sentinel stream
    # graftlint: disable=GL104,GL105 -- frozen at import, never mutated
    y = other()    # ^ a standalone suppression comment covers the NEXT line
    # graftlint: disable-file=GL108 -- generated file, cold path only

The ``-- reason`` is mandatory and the rule list must name known rule IDs
or slugs; anything else is itself a finding (GL100), so a suppression can
never silently rot.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from mercury_tpu.lint.rules import RULES, RawFinding, run_rules

__all__ = ["Finding", "lint_source", "lint_paths", "format_findings"]

_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(?P<kind>disable(?:-file)?)\s*=\s*"
    r"(?P<rules>[A-Za-z0-9_,\-\s]*?)"
    r"(?:\s*--\s*(?P<reason>.*))?$"
)
_MARKER_RE = re.compile(r"#\s*graftlint\b")

_SLUG_TO_ID = {r.slug: r.id for r in RULES.values()}


@dataclass(frozen=True)
class Finding:
    """One reportable lint finding, located and suppressible."""

    rule_id: str
    slug: str
    path: str
    line: int
    col: int
    message: str
    hint: str

    def format(self) -> str:
        return (f"{self.path}:{self.line}:{self.col + 1}: "
                f"{self.rule_id} [{self.slug}] {self.message}\n"
                f"    fix: {self.hint}")


@dataclass
class _Suppressions:
    per_line: Dict[int, Set[str]]
    file_wide: Set[str]
    bad: List[Tuple[int, str]]  # (line, why it's malformed)


def _resolve_rules(spec: str) -> Tuple[Set[str], List[str]]:
    ids: Set[str] = set()
    unknown: List[str] = []
    for token in (t.strip() for t in spec.split(",")):
        if not token:
            continue
        rid = token if token in RULES else _SLUG_TO_ID.get(token)
        if rid is None:
            unknown.append(token)
        else:
            ids.add(rid)
    return ids, unknown


def _parse_suppressions(source: str) -> _Suppressions:
    sup = _Suppressions(per_line={}, file_wide=set(), bad=[])
    try:
        tokens = list(tokenize.generate_tokens(
            io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return sup
    # Lines that hold only a comment (suppression applies to next line).
    code_lines: Set[int] = set()
    for tok in tokens:
        if tok.type not in (tokenize.COMMENT, tokenize.NL,
                            tokenize.NEWLINE, tokenize.INDENT,
                            tokenize.DEDENT, tokenize.ENCODING,
                            tokenize.ENDMARKER):
            for ln in range(tok.start[0], tok.end[0] + 1):
                code_lines.add(ln)
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        if not _MARKER_RE.search(tok.string):
            continue
        line = tok.start[0]
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            sup.bad.append(
                (line, "unrecognized graftlint directive — expected "
                       "`# graftlint: disable=RULE -- reason`"))
            continue
        reason = (m.group("reason") or "").strip()
        ids, unknown = _resolve_rules(m.group("rules"))
        if unknown:
            sup.bad.append(
                (line, f"unknown rule(s) {', '.join(unknown)} in "
                       "suppression"))
            continue
        if not ids:
            sup.bad.append((line, "suppression names no rules"))
            continue
        if not reason:
            sup.bad.append(
                (line, f"suppression of {', '.join(sorted(ids))} has no "
                       "reason — append `-- why this is intentional`"))
            continue
        if m.group("kind") == "disable-file":
            sup.file_wide |= ids
        else:
            target = line if line in code_lines else line + 1
            sup.per_line.setdefault(target, set()).update(ids)
    return sup


def lint_source(source: str, path: str = "<string>",
                select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint one module's source. Returns unsuppressed findings (plus a
    GL100 finding per malformed suppression)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as exc:
        gl100 = RULES["GL100"]
        return [Finding("GL999", "syntax-error", path,
                        exc.lineno or 0, (exc.offset or 1) - 1,
                        f"file does not parse: {exc.msg}", gl100.hint)]
    sup = _parse_suppressions(source)
    raw = run_rules(tree, select=select, path=path)
    for f in raw:
        if f.rule.id in sup.file_wide:
            continue
        if f.rule.id in sup.per_line.get(f.line, ()):
            continue
        findings.append(Finding(f.rule.id, f.rule.slug, path, f.line,
                                f.col, f.message, f.rule.hint))
    gl100 = RULES["GL100"]
    want_gl100 = select is None or "GL100" in select \
        or "bad-suppression" in select
    if want_gl100 and "GL100" not in sup.file_wide:
        for line, why in sup.bad:
            if "GL100" in sup.per_line.get(line, ()):
                continue
            findings.append(Finding(gl100.id, gl100.slug, path, line, 0,
                                    why, gl100.hint))
    findings.sort(key=lambda f: (f.line, f.col, f.rule_id))
    return findings


def _iter_py_files(paths: Iterable[Path]) -> Iterable[Path]:
    for p in paths:
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


def lint_paths(paths: Sequence[str],
               select: Optional[Sequence[str]] = None) -> List[Finding]:
    """Lint every ``*.py`` under the given files/directories."""
    findings: List[Finding] = []
    for file in _iter_py_files(Path(p) for p in paths):
        try:
            source = file.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as exc:
            gl100 = RULES["GL100"]
            findings.append(Finding(
                "GL999", "unreadable", str(file), 0, 0,
                f"cannot read file: {exc}", gl100.hint))
            continue
        findings.extend(lint_source(source, path=str(file), select=select))
    return findings


def format_findings(findings: Sequence[Finding]) -> str:
    if not findings:
        return "graftlint: clean (0 findings)"
    lines = [f.format() for f in findings]
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule_id] = by_rule.get(f.rule_id, 0) + 1
    tally = ", ".join(f"{rid}×{n}" for rid, n in sorted(by_rule.items()))
    lines.append(f"graftlint: {len(findings)} finding"
                 f"{'s' if len(findings) != 1 else ''} ({tally})")
    return "\n".join(lines)
