"""graftlint Layer 2: jaxpr/HLO structural auditor.

Traces the fused Mercury train step (and its ZeRO / bf16-scoring /
sequence-parallel / pipeline-parallel / async-scorer variants) on CPU —
trace only, no compile, no execution — and checks *structural invariants
of the traced program* as data:

- **Collective budget**: exact per-primitive counts (psum, all_gather,
  reduce_scatter, ppermute, …) per parallelism plan, globally and inside
  the ``mercury_scoring`` / ``mercury_grad_sync`` named scopes the step
  functions anchor. An extra all-gather on the ZeRO path is a budget
  diff, not a silent 2× wire cost.
- **Zero host callbacks** when ``telemetry=False`` (hard invariant — a
  stray ``debug_callback`` would put a host round-trip on every step).
- **Donation aliasing**: the count of ``tf.aliasing_output`` /
  ``jax.buffer_donor`` markers in the lowered StableHLO must match what
  :func:`mercury_tpu.compat.donate_argnums` configures (on legacy jax the
  shim disables donation, so the recorded budget is 0 — the audit checks
  *consistency*, not a hard-coded count).
- **bf16 scoring stays bf16**: with ``scoring_dtype="bfloat16"``, zero
  f32×f32 dot/conv ops inside the ``mercury_scoring`` scope (hard
  invariant — a silent upcast would erase the plan's FLOP savings).
- **Async refresh carries no scoring**: with ``refresh_mode="async"``,
  zero dot/conv ops and zero collectives inside ``mercury_scoring``
  (hard invariant — the scorer fleet owns the refresh, so any scoring
  compute in the hot program is the regression the mode exists to
  remove).
- **Seed-program digest**: the sha256 of the canonicalized jaxpr for
  ``telemetry=False`` must equal the committed digest, turning PR 2's
  compile-away benchmark claim into a checked invariant, and the dp
  plan's metric-key surface must equal the seed's exactly.

Budgets live in the committed ``lint/budgets.json`` (regenerate with
``python -m mercury_tpu.lint --layer audit --regen`` after an intentional
program change); the file header records provenance (jax/jaxlib version,
per-plan config hash). When the recorded jax version differs from the
running one, digest and collective mismatches are demoted to warnings —
jaxpr text is not stable across jax releases — while the hard invariants
above always fail loudly.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import sys
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from mercury_tpu.lint import golden

SCHEMA = "graftlint_budgets_v1"
PLAN_NAMES = ("dp", "zero", "dp_bf16", "hs", "hs_local", "hs_fused", "sp",
              "pp", "async", "device_scorer")

# The seed step's metric surface — what telemetry=False must reproduce
# exactly (mirrors benchmarks/telemetry_overhead.py::BASE_KEYS).
SEED_METRIC_KEYS = frozenset({
    "train/loss", "train/acc", "train/pool_loss", "train/sparse_rate",
    "train/moe_aux",
})

COLLECTIVE_PRIMS = frozenset({
    "psum", "pmin", "pmax", "ppermute", "pbroadcast", "all_gather",
    "all_to_all", "reduce_scatter", "psum_scatter", "all_reduce",
    "reduce_precision_sum",
})
CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "outside_call",
    "host_callback_call", "python_callback",
})
SCOPES = ("mercury_scoring", "mercury_grad_sync")
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")


def default_budgets_path() -> str:
    return os.path.join(os.path.dirname(__file__), "budgets.json")


def ensure_cpu_devices(n: int = 8) -> None:
    """Force ``n`` virtual CPU devices — must run before the jax backend
    initializes (same dance as tests/conftest.py)."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        # Probe device count ONLY when a backend is already live: calling
        # jax.devices() on a merely-imported jax would itself initialize
        # a 1-device backend and make the XLA_FLAGS below a no-op (the
        # tracecheck CLI hits this — importing compat pulls in jax).
        xb = sys.modules.get("jax._src.xla_bridge")
        if xb is not None and getattr(xb, "_backends", None):
            import jax

            if len(jax.devices()) >= n:
                return  # backend is up with enough devices (pytest)
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}"
        ).strip()
    from mercury_tpu.platform import select_cpu_if_requested

    select_cpu_if_requested()


# --------------------------------------------------------------------------
# jaxpr walking
# --------------------------------------------------------------------------

def _sub_jaxprs(params: Dict[str, Any]) -> Iterator[Any]:
    for value in params.values():
        values = value if isinstance(value, (list, tuple)) else (value,)
        for v in values:
            if hasattr(v, "eqns"):           # Jaxpr
                yield v
            elif hasattr(v, "jaxpr"):        # ClosedJaxpr
                yield v.jaxpr


def iter_eqns(jaxpr) -> Iterator[Any]:
    """Every equation in a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit, scan, cond, shard_map, custom_vjp, …)."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _name_stack(eqn) -> str:
    si = getattr(eqn, "source_info", None)
    ns = getattr(si, "name_stack", None)
    return str(ns) if ns is not None else ""


def _canonical_jaxpr_text(jaxpr) -> str:
    """Pretty-printed jaxpr with run-dependent noise removed (object
    addresses inside custom_vjp/callback thunk reprs)."""
    text = str(jaxpr)
    return re.sub(r"0x[0-9a-fA-F]+", "0xADDR", text)


def _leaf_dtypes(vars_) -> List[str]:
    out = []
    for v in vars_:
        aval = getattr(v, "aval", None)
        dtype = getattr(aval, "dtype", None)
        if dtype is not None:
            out.append(str(dtype))
    return out


# --------------------------------------------------------------------------
# measurement
# --------------------------------------------------------------------------

@dataclass
class PlanMeasurement:
    plan: str
    config: Dict[str, Any]
    collectives: Dict[str, int] = field(default_factory=dict)
    scoped_collectives: Dict[str, Dict[str, int]] = field(
        default_factory=dict)
    host_callbacks: int = 0
    donation_markers: int = 0
    expected_donated_args: int = 0
    f32_scoring_dots: int = 0
    scoring_ops: int = 0
    jaxpr_sha256: str = ""
    metric_keys: List[str] = field(default_factory=list)

    def config_hash(self) -> str:
        blob = json.dumps(self.config, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def as_budget(self) -> Dict[str, Any]:
        return {
            "config_hash": self.config_hash(),
            "config": self.config,
            "collectives": dict(sorted(self.collectives.items())),
            "scoped_collectives": {
                scope: dict(sorted(counts.items()))
                for scope, counts in sorted(
                    self.scoped_collectives.items())
            },
            "host_callbacks": self.host_callbacks,
            "donation_markers": self.donation_markers,
            "f32_scoring_dots": self.f32_scoring_dots,
            "scoring_ops": self.scoring_ops,
            "jaxpr_sha256": self.jaxpr_sha256,
            "metric_keys": self.metric_keys,
        }


def measure_step(step_fn, args: Tuple, plan: str,
                 config: Dict[str, Any]) -> PlanMeasurement:
    """Trace ``step_fn(*args)`` (no execution) and collect the audited
    structural facts."""
    import jax

    from mercury_tpu.compat import donate_argnums

    m = PlanMeasurement(plan=plan, config=config)
    # host_stream plans donate the streamed slab (arg 1) on top of the
    # state (arg 0) — mirror make_train_step's donate_argnums call so the
    # consistency check below audits what the step actually configures.
    if config.get("data_placement") == "host_stream":
        m.expected_donated_args = len(donate_argnums(0, 1))
    else:
        m.expected_donated_args = len(donate_argnums(0))

    closed = jax.make_jaxpr(step_fn)(*args)
    for scope in SCOPES:
        m.scoped_collectives.setdefault(scope, {})
    for eqn in iter_eqns(closed):
        name = eqn.primitive.name
        if name in COLLECTIVE_PRIMS:
            m.collectives[name] = m.collectives.get(name, 0) + 1
            stack = _name_stack(eqn)
            for scope in SCOPES:
                if scope in stack:
                    sc = m.scoped_collectives[scope]
                    sc[name] = sc.get(name, 0) + 1
        elif name in CALLBACK_PRIMS:
            m.host_callbacks += 1
        if name in ("dot_general", "conv_general_dilated") \
                and "mercury_scoring" in _name_stack(eqn):
            m.scoring_ops += 1
            dtypes = _leaf_dtypes(eqn.invars)
            if dtypes and all(d == "float32" for d in dtypes):
                m.f32_scoring_dots += 1
    m.jaxpr_sha256 = hashlib.sha256(
        _canonical_jaxpr_text(closed).encode()).hexdigest()

    lower_fn = step_fn if hasattr(step_fn, "lower") else jax.jit(step_fn)
    try:
        lowered = lower_fn.lower(*args).as_text()
        m.donation_markers = sum(
            lowered.count(marker) for marker in DONATION_MARKERS)
    except Exception:
        m.donation_markers = -1  # lowering unavailable; skip the check

    out = jax.eval_shape(step_fn, *args)
    # (state, metrics) for the fused plans; (state, metrics, next_gidx)
    # for host_stream's lookahead step.
    metrics = out[1] if isinstance(out, tuple) and len(out) >= 2 else {}
    m.metric_keys = sorted(metrics) if isinstance(metrics, dict) else []
    return m


# --------------------------------------------------------------------------
# plan builders — small, fixed configs; trace-only cost
# --------------------------------------------------------------------------

def _build_fused(variant: str):
    """dp / zero / dp_bf16: the fused SPMD step via the Trainer, exactly
    the construction benchmarks/telemetry_overhead.py benchmarks (scaled
    down: world=2)."""
    from mercury_tpu.config import TrainConfig
    from mercury_tpu.parallel.mesh import make_mesh
    from mercury_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=2,
        batch_size=8,
        presample_batches=2,
        sampler="pool",
        num_epochs=1,
        steps_per_epoch=100,
        eval_every=0,
        log_every=0,
        scan_steps=1,
        compute_dtype="float32",
        telemetry=False,
        heartbeat_every=0,
        seed=0,
    )
    if variant == "zero":
        kw["zero_sharding"] = True
    elif variant == "dp_bf16":
        kw["scoring_dtype"] = "bfloat16"
    config = TrainConfig(**kw)
    trainer = Trainer(config, mesh=make_mesh(2, config.mesh_axis))
    ds = trainer.dataset
    args = (trainer.state, ds.x_train, ds.y_train, ds.shard_indices)
    return trainer.train_step, args, dict(kw, plan=variant)


def _build_async():
    """The async-scorer fused step (``refresh_mode="async"``): the
    scoretable sampler with the refresh forward moved onto the host
    scorer fleet. The traced program must carry ZERO scoring ops — that
    is the feature's entire claim, so it is a hard invariant here, not
    just a budget entry. The trainer's fleet is closed immediately: the
    audit traces the step program, and a live background scorer would
    burn CPU under every subsequent plan's trace."""
    from mercury_tpu.config import TrainConfig
    from mercury_tpu.parallel.mesh import make_mesh
    from mercury_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=2,
        batch_size=8,
        presample_batches=2,
        sampler="scoretable",
        refresh_mode="async",
        scorer_workers=1,
        snapshot_every=4,
        num_epochs=1,
        steps_per_epoch=100,
        eval_every=0,
        log_every=0,
        scan_steps=1,
        compute_dtype="float32",
        telemetry=False,
        heartbeat_every=0,
        seed=0,
    )
    config = TrainConfig(**kw)
    trainer = Trainer(config, mesh=make_mesh(2, config.mesh_axis))
    trainer._scorer_fleet.close()
    ds = trainer.dataset
    args = (trainer.state, ds.x_train, ds.y_train, ds.shard_indices)
    return trainer.train_step, args, dict(kw, plan="async")


def _build_device_scorer():
    """The device-backed scorer service (``scorer_backend="device"``):
    rescoring runs as its OWN pjit program on the reserved scorer slice
    (CPU two-program degradation here), so the TRAINER's fused step must
    stay exactly as scoring-free as the ``async`` plan's — the budget
    pins that moving the scoring program onto a device slice changed
    nothing about the hot program. The trainer's service is closed
    immediately, like the async plan's fleet."""
    from mercury_tpu.config import TrainConfig
    from mercury_tpu.parallel.mesh import make_mesh
    from mercury_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=2,
        batch_size=8,
        presample_batches=2,
        sampler="scoretable",
        refresh_mode="async",
        scorer_backend="device",
        scorer_workers=1,
        snapshot_every=4,
        num_epochs=1,
        steps_per_epoch=100,
        eval_every=0,
        log_every=0,
        scan_steps=1,
        compute_dtype="float32",
        telemetry=False,
        heartbeat_every=0,
        seed=0,
    )
    config = TrainConfig(**kw)
    trainer = Trainer(config, mesh=make_mesh(2, config.mesh_axis))
    trainer._scorer_fleet.close()
    ds = trainer.dataset
    args = (trainer.state, ds.x_train, ds.y_train, ds.shard_indices)
    return trainer.train_step, args, dict(kw, plan="device_scorer")


def _build_hs(shard_mode: str = None):
    """host_stream dp: the lookahead step (``hs_body``) — pixels arrive
    as a streamed uint8 batch, the next selection's indices leave as a
    third output. The pixel argument is a shape/dtype template: tracing
    and AOT lowering never need values, and the audit must not depend on
    the prefetch thread having produced anything.

    ``shard_mode="local"`` builds the multi-controller variant (per-host
    slab + callback assembly on the drain side): its budget pins that
    host-local assembly is a pure dataflow change — the traced step
    program (jaxpr digest, collectives, donation of state AND slab) is
    IDENTICAL to the full-slab plan's."""
    import jax

    from mercury_tpu.config import TrainConfig
    from mercury_tpu.parallel.mesh import make_mesh
    from mercury_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=2,
        batch_size=8,
        presample_batches=2,
        sampler="pool",
        data_placement="host_stream",
        prefetch_depth=2,
        num_epochs=1,
        steps_per_epoch=100,
        eval_every=0,
        log_every=0,
        scan_steps=1,
        compute_dtype="float32",
        telemetry=False,
        heartbeat_every=0,
        seed=0,
    )
    if shard_mode is not None:
        kw["stream_shard_mode"] = shard_mode
    config = TrainConfig(**kw)
    trainer = Trainer(config, mesh=make_mesh(2, config.mesh_axis))
    staging = trainer._stream_pipe._staging[0]
    x_t = jax.ShapeDtypeStruct(staging.shape, staging.dtype)
    args = (trainer.state, x_t, trainer._step_y,
            trainer.dataset.shard_indices)
    plan = "hs" if shard_mode is None else f"hs_{shard_mode}"
    return trainer.train_step, args, dict(kw, plan=plan)


def _build_hs_fused():
    """host_stream with the fused uint8 ingest AND end-to-end bf16
    scoring: ``augment_normalize_pallas`` replaces the normalize+augment
    HLO chain (interpret-mode on the CPU audit — same jaxpr structure as
    the Mosaic lowering) and the scoring forward runs bf16 from uint8 to
    score. Gets its OWN plan entry so the fused program carries its own
    ``scoring_ops`` budget and donation-consistency check — the streamed
    slab must stay donated when the kernel consumes it."""
    import jax

    from mercury_tpu.config import TrainConfig
    from mercury_tpu.parallel.mesh import make_mesh
    from mercury_tpu.train.trainer import Trainer

    kw: Dict[str, Any] = dict(
        model="smallcnn",
        dataset="synthetic",
        world_size=2,
        batch_size=8,
        presample_batches=2,
        sampler="pool",
        data_placement="host_stream",
        prefetch_depth=2,
        fused_input=True,
        scoring_dtype="bfloat16",
        num_epochs=1,
        steps_per_epoch=100,
        eval_every=0,
        log_every=0,
        scan_steps=1,
        compute_dtype="float32",
        telemetry=False,
        heartbeat_every=0,
        seed=0,
    )
    config = TrainConfig(**kw)
    trainer = Trainer(config, mesh=make_mesh(2, config.mesh_axis))
    staging = trainer._stream_pipe._staging[0]
    x_t = jax.ShapeDtypeStruct(staging.shape, staging.dtype)
    args = (trainer.state, x_t, trainer._step_y,
            trainer.dataset.shard_indices)
    return trainer.train_step, args, dict(kw, plan="hs_fused")


def _build_sp():
    """2 data × 2 seq mesh, ring-attention transformer — the
    TestDpSpMercuryStep construction, scaled down."""
    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from mercury_tpu.models import TransformerClassifier
    from mercury_tpu.train.sp_step import (
        init_sp_mercury_state,
        make_dp_sp_mercury_step,
    )

    T, F, C, N = 16, 8, 5, 32
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                ("data", "seq"))
    model = TransformerClassifier(
        num_classes=C, d_model=32, num_heads=2, num_layers=2,
        max_len=T, sp_axis="seq",
    )
    tx = optax.sgd(0.05)
    x = jax.random.normal(jax.random.key(40), (N, T, F))
    y = jax.numpy.asarray(
        np.random.default_rng(41).integers(0, C, N))
    state = init_sp_mercury_state(jax.random.key(7), model, tx, x[:1],
                                  2, N)
    step = make_dp_sp_mercury_step(model, tx, mesh, batch_size=4,
                                   presample_batches=2)
    config = dict(plan="sp", model="transformer", d=2, s=2, T=T, F=F,
                  C=C, N=N, batch_size=4, presample_batches=2,
                  telemetry=False)
    return step, (state, x, y), config


def _build_pp():
    """2-stage GPipe schedule — the test_pp_mercury construction, scaled
    down to 2 pipe devices."""
    import jax
    import numpy as np
    import optax
    from jax.sharding import Mesh

    from mercury_tpu.models import TransformerClassifier
    from mercury_tpu.train.pp_step import (
        create_pp_state,
        make_pp_mercury_step,
    )

    T, F, C, N = 16, 8, 5, 32
    mesh = Mesh(np.array(jax.devices()[:2]), ("pipe",))
    model = TransformerClassifier(num_classes=C, d_model=32, num_heads=2,
                                  num_layers=2, max_len=T)
    tx = optax.adam(1e-3)
    k1, k2 = jax.random.split(jax.random.key(0))
    x = jax.random.normal(k1, (N, T, F))
    y = jax.random.randint(k2, (N,), 0, C)
    state = create_pp_state(jax.random.key(0), model, tx, x[:1],
                            shard_len=N, mesh=mesh)
    step = make_pp_mercury_step(model, tx, mesh, batch_size=4,
                                presample_batches=2, num_microbatches=2)
    config = dict(plan="pp", model="transformer", pipe=2, T=T, F=F, C=C,
                  N=N, batch_size=4, presample_batches=2,
                  num_microbatches=2, telemetry=False)
    return step, (state, x, y), config


_BUILDERS = {
    "dp": lambda: _build_fused("dp"),
    "zero": lambda: _build_fused("zero"),
    "dp_bf16": lambda: _build_fused("dp_bf16"),
    "hs": _build_hs,
    "hs_local": lambda: _build_hs("local"),
    "hs_fused": _build_hs_fused,
    "sp": _build_sp,
    "pp": _build_pp,
    "async": _build_async,
    "device_scorer": _build_device_scorer,
}


def measure_plan(plan: str) -> PlanMeasurement:
    step, args, config = _BUILDERS[plan]()
    return measure_step(step, args, plan, config)


# --------------------------------------------------------------------------
# hard invariants (budgets-file independent)
# --------------------------------------------------------------------------

def check_invariants(m: PlanMeasurement) -> List[str]:
    errors: List[str] = []
    if m.host_callbacks != 0:
        errors.append(
            f"plan {m.plan}: {m.host_callbacks} host callback(s) in the "
            "traced program with telemetry=False (expected 0: each one "
            "is a per-step host round-trip)")
    if m.plan == "dp" and set(m.metric_keys) != SEED_METRIC_KEYS:
        errors.append(
            f"plan dp: telemetry=False metric surface "
            f"{sorted(m.metric_keys)} != seed surface "
            f"{sorted(SEED_METRIC_KEYS)} — the compile-away guarantee "
            "is broken")
    if m.config.get("scoring_dtype") == "bfloat16" \
            and m.f32_scoring_dots != 0:
        errors.append(
            f"plan {m.plan}: {m.f32_scoring_dots} f32×f32 dot/conv op(s) "
            "inside the mercury_scoring scope with "
            "scoring_dtype=bfloat16 (expected 0: a silent upcast erases "
            "the scoring FLOP savings)")
    if m.plan in ("async", "device_scorer"):
        if m.scoring_ops != 0:
            errors.append(
                f"plan {m.plan}: {m.scoring_ops} dot/conv op(s) inside "
                "the mercury_scoring scope with refresh_mode=async "
                "(expected 0: the scorer fleet/service owns the refresh "
                "— scoring compute in the hot program is the regression "
                "this plan exists to catch)")
        if m.scoped_collectives.get("mercury_scoring"):
            errors.append(
                f"plan {m.plan}: collectives inside the mercury_scoring "
                f"scope {m.scoped_collectives['mercury_scoring']} with "
                "refresh_mode=async (expected none: no scoring forward, "
                "no scoring collectives)")
    if m.donation_markers >= 0 and m.expected_donated_args == 0 \
            and m.donation_markers != 0:
        errors.append(
            f"plan {m.plan}: {m.donation_markers} donation marker(s) in "
            "the lowered program but compat.donate_argnums configures "
            "none on this jax version")
    if m.donation_markers >= 0 \
            and m.donation_markers < m.expected_donated_args:
        # Donation consistency, the other direction: every configured
        # donated argument must leave at least one aliasing/buffer-donor
        # marker in the lowered program. For host_stream plans this is
        # the "streamed slab actually donated" assertion — a non-donated
        # PendingSelection output silently pinning the slab would show
        # up here as a missing marker.
        errors.append(
            f"plan {m.plan}: only {m.donation_markers} donation "
            f"marker(s) in the lowered program for "
            f"{m.expected_donated_args} donated argument(s) — a donated "
            "input (state or streamed slab) is not actually aliased")
    return errors


# --------------------------------------------------------------------------
# budgets file
# --------------------------------------------------------------------------

def budgets_doc(measurements: Sequence[PlanMeasurement]) -> Dict[str, Any]:
    return {
        "schema": SCHEMA,
        "provenance": golden.provenance(
            "python -m mercury_tpu.lint --layer audit --regen"),
        "plans": {m.plan: m.as_budget() for m in measurements},
    }


def write_budgets(measurements: Sequence[PlanMeasurement],
                  path: Optional[str] = None) -> str:
    return golden.write_golden(path or default_budgets_path(),
                               budgets_doc(measurements))


def load_budgets(path: Optional[str] = None) -> Dict[str, Any]:
    return golden.load_golden(path or default_budgets_path(), SCHEMA,
                              "--layer audit --regen")


_diff_counts = golden.diff_counts


def compare_budgets(measurements: Sequence[PlanMeasurement],
                    budgets: Dict[str, Any],
                    ) -> Tuple[List[str], List[str]]:
    """Diff measurements against the committed budgets.

    Returns ``(errors, warnings)``: hard invariants and same-jax-version
    budget mismatches are errors; budget mismatches under a *different*
    jax version than the budgets were recorded with are warnings (jaxpr
    text and primitive sets drift across releases — regenerate).
    """
    import jax

    errors: List[str] = []
    warnings: List[str] = []
    recorded_jax = budgets.get("provenance", {}).get("jax")
    version_match = recorded_jax == jax.__version__
    if not version_match:
        warnings.append(
            f"budgets recorded under jax {recorded_jax}, running "
            f"{jax.__version__}: digest/collective diffs demoted to "
            "warnings — regenerate budgets.json on the pinned version")

    plans = budgets.get("plans", {})
    for m in measurements:
        errors.extend(check_invariants(m))
        budget = plans.get(m.plan)
        if budget is None:
            errors.append(f"plan {m.plan}: no committed budget — run "
                          "--regen and review the diff")
            continue
        soft: List[str] = []
        if budget.get("config_hash") != m.config_hash():
            soft.append(
                f"  config_hash expected {budget.get('config_hash')}, "
                f"got {m.config_hash()} (the audited config changed — "
                "every downstream diff follows from this)")
        soft.extend(_diff_counts("collectives",
                                 budget.get("collectives", {}),
                                 m.collectives))
        for scope in SCOPES:
            soft.extend(_diff_counts(
                f"scoped_collectives[{scope}]",
                budget.get("scoped_collectives", {}).get(scope, {}),
                m.scoped_collectives.get(scope, {})))
        if budget.get("jaxpr_sha256") != m.jaxpr_sha256:
            soft.append(
                f"  jaxpr_sha256 expected {budget.get('jaxpr_sha256')}, "
                f"got {m.jaxpr_sha256} (the traced program changed)")
        if budget.get("metric_keys") != m.metric_keys:
            soft.append(
                f"  metric_keys expected {budget.get('metric_keys')}, "
                f"got {m.metric_keys}")
        if m.donation_markers >= 0 \
                and budget.get("donation_markers", 0) != m.donation_markers:
            soft.append(
                f"  donation_markers expected "
                f"{budget.get('donation_markers')}, got "
                f"{m.donation_markers}")
        if budget.get("f32_scoring_dots", 0) != m.f32_scoring_dots:
            soft.append(
                f"  f32_scoring_dots expected "
                f"{budget.get('f32_scoring_dots')}, got "
                f"{m.f32_scoring_dots}")
        if budget.get("scoring_ops", m.scoring_ops) != m.scoring_ops:
            soft.append(
                f"  scoring_ops expected {budget.get('scoring_ops')}, "
                f"got {m.scoring_ops}")
        if soft:
            header = (f"plan {m.plan}: traced program deviates from "
                      "committed budget:")
            block = [header] + soft + [
                "  (intentional change? regenerate: python -m "
                "mercury_tpu.lint --layer audit --regen)"]
            (errors if version_match else warnings).extend(block)
    return errors, warnings


def run_audit(plans: Sequence[str] = PLAN_NAMES,
              budgets_path: Optional[str] = None,
              regen: bool = False,
              diff_out: Optional[str] = None,
              ) -> Tuple[List[str], List[str]]:
    """Measure the requested plans and either record (``regen=True``) or
    verify them against the committed budgets. Returns
    ``(errors, warnings)``; empty errors means the audit passed."""
    ensure_cpu_devices()
    measurements = [measure_plan(p) for p in plans]
    if regen:
        path = write_budgets(measurements, budgets_path)
        errors: List[str] = []
        for m in measurements:
            errors.extend(check_invariants(m))
        return errors, [f"budgets written to {path}"]
    budgets = load_budgets(budgets_path)
    errors, warnings = compare_budgets(measurements, budgets)
    if diff_out and (errors or warnings):
        golden.write_diff_file(diff_out, "graftlint audit diff",
                               errors, warnings)
    return errors, warnings
