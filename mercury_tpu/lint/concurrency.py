"""graftlint Layer C: host-concurrency auditor (pure stdlib).

The training process is not one thread — it is a small fleet:
the prefetch worker (``data/stream.py``), the metric drain thread plus
its observers (``obs/writer.py`` / ``obs/aggregate.py`` /
``obs/anomaly.py``), the async checkpoint writer
(``train/checkpoint.py``) and the scorer fleet
(``sampling/scorer_fleet.py``). Layers 1–3 audit the *traced* program;
this layer audits the Python threads that carry score freshness,
telemetry and input streaming around it.

Static model, built per class over :data:`HOT_THREAD_MODULES`:

- **thread entry points** — functions handed to
  ``threading.Thread(target=...)`` / ``executor.submit``, observer
  callbacks (methods passed *by reference* into any call —
  ``observers.append(self.agg.observe_record)``,
  ``context_fn=self._flight_context``), and everything reachable from
  them through ``self.method()`` calls. Every other method is assumed
  to run on the constructing (trainer) thread; a function reachable
  from both roots is treated as running on both sides.
- **lock discipline** — ``self.X = threading.Lock()/RLock()/
  Condition(...)`` declares a lock attribute
  (``Condition(self._lock)`` aliases the underlying lock, so holding
  the condition counts as holding the lock); an attribute's *guard* is
  the lock held at its ``with self._lock:`` accesses.

Rules (IDs registered in lint/rules.py so suppressions/--select resolve;
the checks run only in this layer):

- **GL120** — a cross-thread attribute (written on one side, accessed
  on the other) has an inferred guard but some cross-thread access
  does not hold it. Attributes with NO guard anywhere are flagged only
  for cross-thread *write/write*: single-writer publish patterns
  (whole-tuple ``_snap`` swap, ``_exc``, monotonic counters) are
  CPython-atomic by design and are covered by the runtime harness
  (lint/racecheck.py) instead of static guessing.
- **GL121** — no-timeout ``put`` into a bounded queue, or one queue
  mixing unbounded blocking ``get()`` with timeout gets.
- **GL122** — non-daemon thread with no reachable ``join()``.
- **GL123** — two locks acquired in opposite nesting orders (lexical
  nesting plus one level of ``self.method()`` calls made while holding
  a lock).
- **GL124** — blocking call (``.join``, zero-arg ``.get()``,
  ``time.sleep``) while lexically holding a lock.
- **GL125** — thread / executor pool / queue not declared in the
  committed ``lint/thread_manifest.json`` (``--regen`` / ``--diff-out``
  parity, like the Layer 2/3 budget files): any new thread must be
  declared and reviewed.

Suppression uses the standard engine syntax with a mandatory reason::

    if not self._profile_pending:  # graftlint: disable=GL120 -- lock-free fast path; stale read self-corrects next step
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Set, Tuple

from mercury_tpu.lint.engine import Finding, _parse_suppressions
from mercury_tpu.lint.rules import RULES

__all__ = [
    "HOT_THREAD_MODULES",
    "THREAD_MANIFEST_SCHEMA",
    "default_manifest_path",
    "extract_manifest",
    "lint_concurrency_source",
    "run_concurrency_check",
]

#: Version tag for ``thread_manifest.json``; bump on shape changes.
THREAD_MANIFEST_SCHEMA = "graftlint_thread_manifest_v1"

#: The hot host modules whose thread fleet this layer audits by default.
HOT_THREAD_MODULES = (
    "mercury_tpu/data/stream.py",
    "mercury_tpu/faults.py",
    "mercury_tpu/obs/writer.py",
    "mercury_tpu/obs/aggregate.py",
    "mercury_tpu/obs/anomaly.py",
    "mercury_tpu/obs/events.py",
    "mercury_tpu/obs/serve.py",
    "mercury_tpu/runtime/supervisor.py",
    "mercury_tpu/sampling/scorer_fleet.py",
    "mercury_tpu/sampling/scorer_service.py",
    "mercury_tpu/train/checkpoint.py",
    "mercury_tpu/train/trainer.py",
)

_LOCK_CTORS = frozenset({"Lock", "RLock", "Condition"})
_QUEUE_CTORS = frozenset({"Queue", "LifoQueue", "PriorityQueue"})
#: Method calls on an attribute that mutate it in place (a write for the
#: lock-discipline analysis).
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "remove", "clear", "update", "setdefault", "add", "discard",
    "sort", "reverse",
})


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def default_manifest_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "thread_manifest.json")


def _dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for nested Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` when ``node`` is exactly ``self.X``, else None."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _name_literal(node: Optional[ast.AST]) -> Optional[str]:
    """A thread-name expression as a manifest string: a plain literal
    verbatim, an f-string as its constant prefix plus ``*``, anything
    else (a variable) as None."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        prefix = ""
        for part in node.values:
            if isinstance(part, ast.Constant):
                prefix += str(part.value)
            else:
                return prefix + "*"
        return prefix
    return None


# ---------------------------------------------------------------- specs
@dataclass
class ThreadSpec:
    module: str
    cls: str
    name: str           # literal, "prefix*", or "<dynamic>"
    daemon: bool
    line: int
    target: Optional[str] = None
    store: Optional[str] = None  # attr/var the Thread object landed in


@dataclass
class PoolSpec:
    module: str
    cls: str
    prefix: str
    line: int


@dataclass
class QueueSpec:
    module: str
    cls: str
    attr: str
    maxsize: Optional[str]  # unparse of the bound, None = unbounded
    line: int


@dataclass
class _Access:
    attr: str
    write: bool
    func: str
    locks: frozenset
    line: int
    col: int


@dataclass
class _QueueOp:
    attr: str
    op: str            # put / put_nowait / get / get_nowait
    bounded_wait: bool  # nowait or an explicit timeout
    line: int
    col: int


@dataclass
class ModuleModel:
    """Everything Layer C extracted from one module."""

    path: str
    threads: List[ThreadSpec] = field(default_factory=list)
    pools: List[PoolSpec] = field(default_factory=list)
    queues: List[QueueSpec] = field(default_factory=list)
    findings: List[Finding] = field(default_factory=list)


def _mk_finding(rule_id: str, path: str, line: int, col: int,
                message: str) -> Finding:
    rule = RULES[rule_id]
    return Finding(rule.id, rule.slug, path, line, col, message, rule.hint)


# ------------------------------------------------- callback collection
def collect_callback_names(tree: ast.Module) -> Set[str]:
    """Attribute names referenced *by value* inside call arguments —
    ``observers.append(self.agg.observe_record)`` marks
    ``observe_record``; a method that is immediately CALLED is not a
    callback. Over-approximate by design: a collected name only matters
    when it matches a method of an analyzed class."""
    call_funcs = {id(n.func) for n in ast.walk(tree)
                  if isinstance(n, ast.Call)}
    names: Set[str] = set()
    for call in ast.walk(tree):
        if not isinstance(call, ast.Call):
            continue
        operands = list(call.args) + [kw.value for kw in call.keywords]
        for arg in operands:
            for node in ast.walk(arg):
                if (isinstance(node, ast.Attribute)
                        and isinstance(node.ctx, ast.Load)
                        and id(node) not in call_funcs):
                    names.add(node.attr)
    return names


# ------------------------------------------------------ class analysis
class _ClassAnalyzer:
    """Builds the per-class concurrency model and runs GL120–GL124."""

    def __init__(self, cls: ast.ClassDef, path: str,
                 callback_names: Set[str]) -> None:
        self.cls = cls
        self.path = path
        self.callback_names = callback_names
        self.lock_attrs: Set[str] = set()
        self.cond_alias: Dict[str, str] = {}  # condition attr -> lock attr
        self.queue_attrs: Dict[str, QueueSpec] = {}
        self.threads: List[ThreadSpec] = []
        self.pools: List[PoolSpec] = []
        self.entry_roots: Set[str] = set()
        self.methods: Dict[str, ast.AST] = {}
        self.accesses: List[_Access] = []
        self.queue_ops: List[_QueueOp] = []
        self.calls: Dict[str, Set[str]] = {}          # func -> self-calls
        self.acquired_by: Dict[str, Set[str]] = {}    # func -> locks used
        self.lock_pairs: Dict[Tuple[str, str], int] = {}  # (outer, inner)
        self.blocking: List[Tuple[str, int, int, str]] = []
        self.joined: Set[str] = set()
        self.for_alias: Dict[str, str] = {}
        self.findings: List[Finding] = []

    # ------------------------------------------------------- declarations
    def _scan_declarations(self) -> None:
        """Locks, condition aliases, queues, threads, pools, joins —
        anywhere in the class body."""
        thread_store: Dict[int, str] = {}
        for node in ast.walk(self.cls):
            target = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = node.targets[0]
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                target = node.target
            if target is not None:
                store = (_self_attr(target)
                         or (target.id if isinstance(target, ast.Name)
                             else None))
                if store is not None:
                    for sub in ast.walk(node.value):
                        if (isinstance(sub, ast.Call)
                                and self._ctor_kind(sub) == "thread"):
                            thread_store[id(sub)] = store
                attr = _self_attr(target)
                if attr is not None and isinstance(node.value, ast.Call):
                    self._classify_ctor(attr, node.value)
            elif isinstance(node, ast.For):
                tgt, it = node.target, _self_attr(node.iter)
                if isinstance(tgt, ast.Name) and it is not None:
                    self.for_alias[tgt.id] = it
            elif (isinstance(node, ast.Call)
                  and isinstance(node.func, ast.Attribute)
                  and node.func.attr == "join"):
                recv = node.func.value
                term = _self_attr(recv) or (
                    recv.id if isinstance(recv, ast.Name) else None)
                if term is not None:
                    self.joined.add(term)
        for node in ast.walk(self.cls):
            if isinstance(node, ast.Call):
                kind = self._ctor_kind(node)
                if kind == "thread":
                    self._record_thread(node, thread_store.get(id(node)))
                elif kind == "pool":
                    self._record_pool(node)

    def _ctor_kind(self, call: ast.Call) -> Optional[str]:
        name = _dotted(call.func)
        if name is None:
            return None
        term = name.rsplit(".", 1)[-1]
        if term == "Thread" and (name in ("Thread", "threading.Thread")):
            return "thread"
        if term == "ThreadPoolExecutor":
            return "pool"
        return None

    def _classify_ctor(self, attr: str, call: ast.Call) -> None:
        name = _dotted(call.func)
        if name is None:
            return
        term = name.rsplit(".", 1)[-1]
        if term in _LOCK_CTORS and name.split(".", 1)[0] in (
                "threading", term):
            self.lock_attrs.add(attr)
            if term == "Condition" and call.args:
                inner = _self_attr(call.args[0])
                if inner is not None:
                    self.cond_alias[attr] = inner
        elif term in _QUEUE_CTORS and name.split(".", 1)[0] in (
                "queue", term):
            maxsize: Optional[ast.AST] = None
            if call.args:
                maxsize = call.args[0]
            for kw in call.keywords:
                if kw.arg == "maxsize":
                    maxsize = kw.value
            if (isinstance(maxsize, ast.Constant)
                    and not maxsize.value):
                maxsize = None  # Queue(0) is unbounded
            self.queue_attrs[attr] = QueueSpec(
                self.path, self.cls.name, attr,
                None if maxsize is None else ast.unparse(maxsize),
                call.lineno)

    def _record_thread(self, call: ast.Call,
                       store: Optional[str]) -> None:
        kw = {k.arg: k.value for k in call.keywords}
        target = kw.get("target")
        target_name = None
        if target is not None:
            target_name = _self_attr(target) or (
                target.id if isinstance(target, ast.Name) else None)
        if target_name is not None:
            self.entry_roots.add(target_name)
        daemon = kw.get("daemon")
        daemon_val = bool(daemon.value) if (
            isinstance(daemon, ast.Constant)) else False
        self.threads.append(ThreadSpec(
            self.path, self.cls.name,
            _name_literal(kw.get("name")) or "<dynamic>",
            daemon_val, call.lineno, target=target_name, store=store))

    def _record_pool(self, call: ast.Call) -> None:
        for k in call.keywords:
            if k.arg == "thread_name_prefix":
                prefix = _name_literal(k.value)
                if prefix:
                    self.pools.append(PoolSpec(
                        self.path, self.cls.name, prefix, call.lineno))
        # submit targets become entry points too
        # (handled in the per-function walk: executor.submit(self.m)).

    # --------------------------------------------------- function walks
    def _canon(self, lock: str) -> str:
        return self.cond_alias.get(lock, lock)

    def _walk_function(self, name: str, node: ast.AST) -> None:
        self.methods[name] = node
        self.calls.setdefault(name, set())
        self.acquired_by.setdefault(name, set())

        def lock_of(expr: ast.AST) -> Optional[str]:
            attr = _self_attr(expr)
            if attr is not None and (attr in self.lock_attrs):
                return self._canon(attr)
            return None

        def record_access(attr: str, write: bool, locks: Tuple[str, ...],
                          lineno: int, col: int) -> None:
            if attr in self.lock_attrs or attr in self.queue_attrs:
                return
            if attr in self.methods or attr in (
                    n.name for n in self.cls.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))):
                return  # bound-method references are not state
            self.accesses.append(_Access(
                attr, write, name, frozenset(locks), lineno, col))

        def visit(node: ast.AST, locks: Tuple[str, ...]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # Nested def: its body runs later (possibly on another
                # thread) — analyze as its own function, empty lock ctx.
                self._walk_function(f"{name}.{node.name}", node)
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                held = list(locks)
                for item in node.items:
                    lk = lock_of(item.context_expr)
                    visit(item.context_expr, tuple(held))
                    if lk is not None:
                        for outer in held:
                            if outer != lk:
                                self.lock_pairs.setdefault(
                                    (outer, lk),
                                    item.context_expr.lineno)
                        held.append(lk)
                        self.acquired_by[name].add(lk)
                for stmt in node.body:
                    visit(stmt, tuple(held))
                return
            if isinstance(node, ast.Call):
                self._visit_call(node, locks, name, visit)
                return
            if isinstance(node, ast.Attribute):
                attr = _self_attr(node)
                if attr is not None:
                    record_access(
                        attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                        locks, node.lineno, node.col_offset)
                for child in ast.iter_child_nodes(node):
                    visit(child, locks)
                return
            if (isinstance(node, (ast.Subscript,))
                    and isinstance(node.ctx, (ast.Store, ast.Del))):
                # self._offsets[k] = v mutates _offsets
                attr = _self_attr(node.value)
                if attr is not None:
                    record_access(attr, True, locks,
                                  node.lineno, node.col_offset)
            for child in ast.iter_child_nodes(node):
                visit(child, locks)

        body = node.body if hasattr(node, "body") else []
        for stmt in body:
            visit(stmt, ())

    def _visit_call(self, node: ast.Call, locks: Tuple[str, ...],
                    func_name: str, visit) -> None:
        f = node.func
        # self.method(...) — call-graph edge; while holding a lock it
        # also contributes one-level lock-ordering pairs.
        callee = _self_attr(f)
        if callee is not None:
            self.calls[func_name].add(callee)
            if locks:
                self.calls.setdefault(f"{func_name}", set())
                self._held_calls = getattr(self, "_held_calls", [])
                self._held_calls.append((callee, locks, node.lineno))
        if isinstance(f, ast.Attribute):
            recv_attr = _self_attr(f.value)
            # executor.submit(self.m) / Thread(target=...) in expressions
            if f.attr == "submit":
                for arg in node.args[:1]:
                    t = _self_attr(arg) or (
                        arg.id if isinstance(arg, ast.Name) else None)
                    if t is not None:
                        self.entry_roots.add(t)
            # queue discipline
            if (recv_attr in self.queue_attrs
                    and f.attr in ("put", "put_nowait",
                                   "get", "get_nowait")):
                has_timeout = any(kw.arg == "timeout"
                                  for kw in node.keywords)
                if f.attr in ("put", "get") and len(node.args) > (
                        1 if f.attr == "put" else 0):
                    # positional block/timeout args: treat as bounded
                    has_timeout = True
                self.queue_ops.append(_QueueOp(
                    recv_attr, f.attr,
                    f.attr.endswith("_nowait") or has_timeout,
                    node.lineno, node.col_offset))
            # in-place mutation of a shared attribute
            if (recv_attr is not None and f.attr in _MUTATORS
                    and recv_attr not in self.queue_attrs
                    and recv_attr not in self.lock_attrs):
                self.accesses.append(_Access(
                    recv_attr, True, func_name, frozenset(locks),
                    node.lineno, node.col_offset))
            # blocking calls while holding a lock (GL124)
            if locks:
                self._check_blocking(node, f, func_name)
        elif locks and _dotted(f) in ("time.sleep", "sleep"):
            self.blocking.append(
                (f"time.sleep while holding "
                 f"{'/'.join(sorted(set(locks)))}",
                 node.lineno, node.col_offset, func_name))
        for child in ast.iter_child_nodes(node):
            visit(child, locks)

    def _check_blocking(self, node: ast.Call, f: ast.Attribute,
                        func_name: str) -> None:
        recv_dotted = _dotted(f.value)
        if f.attr == "join":
            # os.path.join / "sep".join are string/path ops, not waits.
            if isinstance(f.value, ast.Constant):
                return
            if recv_dotted is not None and (
                    recv_dotted == "os.path"
                    or recv_dotted.endswith(".path")):
                return
            self.blocking.append(
                (f"blocking join() on "
                 f"'{recv_dotted or ast.unparse(f.value)}' under a lock",
                 node.lineno, node.col_offset, func_name))
        elif (f.attr == "get" and not node.args
                and not any(kw.arg == "timeout" for kw in node.keywords)
                and not node.keywords):
            # zero-arg .get() is a blocking queue get (dict.get needs
            # a key).
            self.blocking.append(
                (f"unbounded blocking get() on "
                 f"'{recv_dotted or ast.unparse(f.value)}' under a lock",
                 node.lineno, node.col_offset, func_name))
        elif (f.attr == "sleep" and recv_dotted is not None
                and recv_dotted.startswith("time")):
            self.blocking.append(
                ("time.sleep under a lock",
                 node.lineno, node.col_offset, func_name))

    # ----------------------------------------------------------- closure
    def _closure(self, roots: Set[str]) -> Set[str]:
        seen: Set[str] = set()
        frontier = [r for r in roots if r in self.calls]
        # nested functions are rooted by their qualified name too
        frontier += [f for f in self.calls
                     if f.split(".")[-1] in roots and f not in frontier]
        while frontier:
            f = frontier.pop()
            if f in seen:
                continue
            seen.add(f)
            for callee in self.calls.get(f, ()):
                for cand in (callee,):
                    if cand in self.calls and cand not in seen:
                        frontier.append(cand)
        return seen

    # -------------------------------------------------------------- rules
    def analyze(self) -> None:
        self._scan_declarations()
        for node in self.cls.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._walk_function(node.name, node)

        # one-level lock-ordering via calls made while holding a lock
        for callee, locks, line in getattr(self, "_held_calls", []):
            for inner in self.acquired_by.get(callee, ()):
                for outer in locks:
                    if outer != inner:
                        self.lock_pairs.setdefault((outer, inner), line)

        entry_roots = set(self.entry_roots)
        entry_roots |= {m for m in self.methods
                        if m.split(".")[-1] in self.callback_names
                        and "." not in m}
        reach_entry = self._closure(entry_roots)
        other_roots = {m for m in self.methods
                       if "." not in m and m not in reach_entry
                       and m != "__init__"}
        reach_other = self._closure(other_roots)

        self._rule_gl120(reach_entry, reach_other)
        self._rule_gl121()
        self._rule_gl122()
        self._rule_gl123()
        self._rule_gl124()

    def _sides(self, func: str, reach_entry: Set[str],
               reach_other: Set[str]) -> Set[str]:
        sides = set()
        if func in reach_entry:
            sides.add("entry")
        if func in reach_other:
            sides.add("other")
        return sides

    def _rule_gl120(self, reach_entry: Set[str],
                    reach_other: Set[str]) -> None:
        if not reach_entry:
            return  # no thread entry points: nothing crosses threads
        by_attr: Dict[str, List[Tuple[_Access, Set[str]]]] = {}
        for a in self.accesses:
            if a.func == "__init__":
                continue  # init-before-start publish is safe
            sides = self._sides(a.func, reach_entry, reach_other)
            if not sides:
                continue
            by_attr.setdefault(a.attr, []).append((a, sides))
        for attr, accs in sorted(by_attr.items()):
            entry_w = any(a.write and "entry" in s for a, s in accs)
            other_w = any(a.write and "other" in s for a, s in accs)
            entry_any = any("entry" in s for a, s in accs)
            other_any = any("other" in s for a, s in accs)
            cross = (entry_w and other_any) or (other_w and entry_any)
            if not cross:
                continue
            locked = [a for a, _ in accs if a.locks]
            if not locked:
                if entry_w and other_w:
                    a = next(a for a, s in accs
                             if a.write and "other" in s)
                    self.findings.append(_mk_finding(
                        "GL120", self.path, a.line, a.col,
                        f"'{self.cls.name}.{attr}' is written from both "
                        f"a thread entry point and the constructing "
                        f"thread with no lock at all"))
                continue
            guards: Dict[str, int] = {}
            for a in locked:
                for lk in a.locks:
                    guards[lk] = guards.get(lk, 0) + 1
            guard = max(sorted(guards), key=lambda k: guards[k])
            held = sum(1 for a, _ in accs if guard in a.locks)
            reported: Set[int] = set()
            for a, sides in accs:
                if guard in a.locks or a.line in reported:
                    continue
                reported.add(a.line)
                side = "thread-entry" if "entry" in sides else "trainer"
                self.findings.append(_mk_finding(
                    "GL120", self.path, a.line, a.col,
                    f"'{self.cls.name}.{attr}' is shared across threads "
                    f"but this {side}-side "
                    f"{'write' if a.write else 'read'} does not hold "
                    f"its guard '{guard}' (held at {held}/{len(accs)} "
                    f"accesses)"))

    def _rule_gl121(self) -> None:
        ops_by_q: Dict[str, List[_QueueOp]] = {}
        for op in self.queue_ops:
            ops_by_q.setdefault(op.attr, []).append(op)
        for attr, ops in sorted(ops_by_q.items()):
            spec = self.queue_attrs[attr]
            if spec.maxsize is not None:
                for op in ops:
                    if op.op == "put" and not op.bounded_wait:
                        self.findings.append(_mk_finding(
                            "GL121", self.path, op.line, op.col,
                            f"no-timeout put() into bounded queue "
                            f"'{self.cls.name}.{attr}' "
                            f"(maxsize={spec.maxsize}): the producer "
                            f"wedges forever once the consumer stops "
                            f"draining"))
            gets = [op for op in ops if op.op == "get"]
            if (any(g.bounded_wait for g in gets)
                    and any(not g.bounded_wait for g in gets)):
                for g in gets:
                    if not g.bounded_wait:
                        self.findings.append(_mk_finding(
                            "GL121", self.path, g.line, g.col,
                            f"queue '{self.cls.name}.{attr}' mixes "
                            f"unbounded blocking get() with timeout "
                            f"gets — one consumer can hang forever "
                            f"while the other is bounded"))

    def _rule_gl122(self) -> None:
        joined = {self.for_alias.get(n, n) for n in self.joined}
        for t in self.threads:
            if t.daemon:
                continue
            if t.store is None or t.store not in joined:
                self.findings.append(_mk_finding(
                    "GL122", self.path, t.line, 0,
                    f"non-daemon thread '{t.name}' in {self.cls.name} "
                    f"has no reachable join(): interpreter exit blocks "
                    f"on it forever if the work wedges"))

    def _rule_gl123(self) -> None:
        for (a, b), line in sorted(self.lock_pairs.items()):
            if (b, a) in self.lock_pairs and a < b:
                other_line = self.lock_pairs[(b, a)]
                self.findings.append(_mk_finding(
                    "GL123", self.path, max(line, other_line), 0,
                    f"locks '{a}' and '{b}' of {self.cls.name} are "
                    f"acquired in both orders ({a}→{b} at line "
                    f"{line}, {b}→{a} at line {other_line}): "
                    f"deadlock ordering"))

    def _rule_gl124(self) -> None:
        for msg, line, col, func in self.blocking:
            self.findings.append(_mk_finding(
                "GL124", self.path, line, col,
                f"{msg} (in {self.cls.name}.{func})"))


# ----------------------------------------------------- module analysis
def analyze_module(tree: ast.Module, path: str,
                   callback_names: Set[str]) -> ModuleModel:
    model = ModuleModel(path=path)
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            an = _ClassAnalyzer(node, path, callback_names)
            an.analyze()
            model.findings.extend(an.findings)
            model.threads.extend(an.threads)
            model.pools.extend(an.pools)
            model.queues.extend(an.queue_attrs.values())
    _resolve_dynamic_names(tree, model)
    model.findings.sort(key=lambda f: (f.line, f.col, f.rule_id))
    return model


def _resolve_dynamic_names(tree: ast.Module, model: ModuleModel) -> None:
    """A Thread whose ``name=`` is a constructor parameter (the
    ``_AsyncSave`` pattern) resolves through the class's call sites:
    ``_AsyncSave(..., name=f"ckpt-write-{step}")`` names the thread."""
    for spec in model.threads:
        if spec.name != "<dynamic>":
            continue
        resolved: Set[str] = set()
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fname = _dotted(node.func)
            if fname is None or fname.rsplit(".", 1)[-1] != spec.cls:
                continue
            for kw in node.keywords:
                if kw.arg == "name":
                    lit = _name_literal(kw.value)
                    if lit:
                        resolved.add(lit)
        if len(resolved) == 1:
            spec.name = resolved.pop()


# --------------------------------------------------- manifest handling
def _manifest_doc(models: Sequence[ModuleModel]) -> Dict[str, Any]:
    threads = sorted(
        ({"module": t.module, "class": t.cls, "name": t.name,
          "daemon": t.daemon} for m in models for t in m.threads),
        key=lambda d: (d["module"], d["class"], d["name"]))
    pools = sorted(
        ({"module": p.module, "class": p.cls, "prefix": p.prefix}
         for m in models for p in m.pools),
        key=lambda d: (d["module"], d["class"], d["prefix"]))
    queues = sorted(
        ({"module": q.module, "class": q.cls, "attr": q.attr,
          "maxsize": q.maxsize} for m in models for q in m.queues),
        key=lambda d: (d["module"], d["class"], d["attr"]))
    return {
        "schema": THREAD_MANIFEST_SCHEMA,
        "regenerate_with":
            "python -m mercury_tpu.lint --layer concurrency --regen",
        "threads": threads,
        "pools": pools,
        "queues": queues,
    }


def extract_manifest(paths: Sequence[str]) -> Dict[str, Any]:
    """The thread manifest the given modules would declare today."""
    models, _, errors = _analyze_paths(list(paths))
    if errors:
        raise ValueError("; ".join(errors))
    return _manifest_doc(models)


def _compare_manifest(models: Sequence[ModuleModel],
                      manifest: Dict[str, Any],
                      ) -> Tuple[List[Finding], List[str], List[str]]:
    """(undeclared findings, stale warnings, diff lines)."""
    findings: List[Finding] = []
    warnings: List[str] = []
    diff: List[str] = []

    def key_of(d: Dict[str, Any], fields: Tuple[str, ...]) -> Tuple:
        return tuple(d.get(f) for f in fields)

    declared_threads = {key_of(d, ("module", "class", "name")): d
                        for d in manifest.get("threads", ())}
    declared_pools = {key_of(d, ("module", "class", "prefix"))
                      for d in manifest.get("pools", ())}
    declared_queues = {key_of(d, ("module", "class", "attr")): d
                       for d in manifest.get("queues", ())}

    seen_t, seen_p, seen_q = set(), set(), set()
    for m in models:
        for t in m.threads:
            k = (t.module, t.cls, t.name)
            seen_t.add(k)
            d = declared_threads.get(k)
            if d is None:
                findings.append(_mk_finding(
                    "GL125", t.module, t.line, 0,
                    f"thread '{t.name}' (class {t.cls}, "
                    f"daemon={t.daemon}) is not declared in the thread "
                    f"manifest"))
                diff.append(f"+ thread {t.module}:{t.cls} '{t.name}' "
                            f"daemon={t.daemon}")
            elif bool(d.get("daemon")) != t.daemon:
                findings.append(_mk_finding(
                    "GL125", t.module, t.line, 0,
                    f"thread '{t.name}' (class {t.cls}) is declared "
                    f"daemon={d.get('daemon')} but constructed "
                    f"daemon={t.daemon}"))
                diff.append(f"~ thread {t.module}:{t.cls} '{t.name}' "
                            f"daemon {d.get('daemon')} -> {t.daemon}")
        for p in m.pools:
            k = (p.module, p.cls, p.prefix)
            seen_p.add(k)
            if k not in declared_pools:
                findings.append(_mk_finding(
                    "GL125", p.module, p.line, 0,
                    f"executor pool '{p.prefix}' (class {p.cls}) is not "
                    f"declared in the thread manifest"))
                diff.append(f"+ pool {p.module}:{p.cls} '{p.prefix}'")
        for q in m.queues:
            k = (q.module, q.cls, q.attr)
            seen_q.add(k)
            d = declared_queues.get(k)
            if d is None:
                findings.append(_mk_finding(
                    "GL125", q.module, q.line, 0,
                    f"queue '{q.cls}.{q.attr}' "
                    f"(maxsize={q.maxsize}) is not declared in the "
                    f"thread manifest"))
                diff.append(f"+ queue {q.module}:{q.cls}.{q.attr} "
                            f"maxsize={q.maxsize}")
            elif d.get("maxsize") != q.maxsize:
                findings.append(_mk_finding(
                    "GL125", q.module, q.line, 0,
                    f"queue '{q.cls}.{q.attr}' capacity changed: "
                    f"declared maxsize={d.get('maxsize')}, constructed "
                    f"maxsize={q.maxsize}"))
                diff.append(f"~ queue {q.module}:{q.cls}.{q.attr} "
                            f"maxsize {d.get('maxsize')} -> {q.maxsize}")
    for k in sorted(set(declared_threads) - seen_t):
        warnings.append(f"thread manifest entry {k} no longer exists "
                        "(stale — regenerate with --regen)")
        diff.append(f"- thread {k[0]}:{k[1]} '{k[2]}'")
    for k in sorted(declared_pools - seen_p):
        warnings.append(f"pool manifest entry {k} no longer exists "
                        "(stale — regenerate with --regen)")
        diff.append(f"- pool {k[0]}:{k[1]} '{k[2]}'")
    for k in sorted(set(declared_queues) - seen_q):
        warnings.append(f"queue manifest entry {k} no longer exists "
                        "(stale — regenerate with --regen)")
        diff.append(f"- queue {k[0]}:{k[1]}.{k[2]}")
    return findings, warnings, diff


# ----------------------------------------------------------- entrypoints
def lint_concurrency_source(source: str,
                            path: str = "<string>") -> List[Finding]:
    """Static GL120–GL124 over one module's source, suppressions
    applied. The manifest check (GL125) needs the repo — see
    :func:`run_concurrency_check`."""
    tree = ast.parse(source)
    callbacks = collect_callback_names(tree)
    model = analyze_module(tree, path, callbacks)
    return _apply_suppressions(model.findings, source)


def _apply_suppressions(findings: Sequence[Finding],
                        source: str) -> List[Finding]:
    sup = _parse_suppressions(source)
    kept = [f for f in findings
            if f.rule_id not in sup.file_wide
            and f.rule_id not in sup.per_line.get(f.line, ())]
    return kept


def _analyze_paths(files: List[str]) -> Tuple[
        List[ModuleModel], Dict[str, str], List[str]]:
    """Parse + analyze every file. Returns (models, sources by relpath,
    hard errors). Module paths are repo-relative with forward slashes."""
    root = _repo_root()
    sources: Dict[str, str] = {}
    trees: Dict[str, ast.Module] = {}
    errors: List[str] = []
    for f in files:
        rel = os.path.relpath(os.path.abspath(f), root).replace(
            os.sep, "/")
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
            trees[rel] = ast.parse(src, filename=f)
            sources[rel] = src
        except (OSError, SyntaxError) as exc:
            errors.append(f"{rel}: cannot analyze: {exc}")
    callbacks: Set[str] = set()
    for tree in trees.values():
        callbacks |= collect_callback_names(tree)
    models = [analyze_module(tree, rel, callbacks)
              for rel, tree in sorted(trees.items())]
    return models, sources, errors


def run_concurrency_check(paths: Optional[Sequence[str]] = None,
                          manifest_path: Optional[str] = None,
                          regen: bool = False,
                          diff_out: Optional[str] = None,
                          ) -> Tuple[List[str], List[str]]:
    """Layer C driver: static rules over the hot thread modules plus
    thread-manifest parity. Returns ``(errors, warnings)`` — the Layer
    2/3 contract; raises FileNotFoundError when the manifest is missing
    and ``regen`` is false."""
    root = _repo_root()
    if paths is None:
        files = [os.path.join(root, m) for m in HOT_THREAD_MODULES]
    else:
        files = []
        for p in paths:
            if os.path.isdir(p):
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = [d for d in dirnames
                                   if d not in ("__pycache__", ".git")]
                    files.extend(os.path.join(dirpath, f)
                                 for f in sorted(filenames)
                                 if f.endswith(".py"))
            else:
                files.append(p)
    models, sources, errors = _analyze_paths(files)

    manifest_path = manifest_path or default_manifest_path()
    warnings: List[str] = []
    per_module: Dict[str, List[Finding]] = {
        m.path: list(m.findings) for m in models}

    if regen:
        from mercury_tpu.lint import golden

        doc = _manifest_doc(models)
        golden.write_golden(manifest_path, doc)
        warnings.append(
            f"thread manifest written to {manifest_path} "
            f"({len(doc['threads'])} threads, {len(doc['pools'])} "
            f"pools, {len(doc['queues'])} queues) — review the diff "
            f"before committing")
    else:
        if not os.path.exists(manifest_path):
            raise FileNotFoundError(manifest_path)
        with open(manifest_path) as f:
            manifest = json.load(f)
        if manifest.get("schema") != THREAD_MANIFEST_SCHEMA:
            errors.append(
                f"{manifest_path}: schema "
                f"{manifest.get('schema')!r}, expected "
                f"{THREAD_MANIFEST_SCHEMA!r} — regenerate with --regen")
            manifest = {"threads": [], "pools": [], "queues": []}
        m_findings, m_warnings, diff = _compare_manifest(models, manifest)
        warnings.extend(m_warnings)
        for f in m_findings:
            per_module.setdefault(f.path, []).append(f)
        if diff and diff_out:
            from mercury_tpu.lint import golden

            golden.write_diff_file(
                diff_out, "graftlint thread-manifest diff", diff)

    all_findings: List[Finding] = []
    for rel, findings in sorted(per_module.items()):
        src = sources.get(rel)
        kept = (_apply_suppressions(findings, src)
                if src is not None else list(findings))
        all_findings.extend(kept)
    all_findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule_id))
    errors.extend(f.format() for f in all_findings)
    return errors, warnings
