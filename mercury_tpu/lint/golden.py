"""Shared golden-file plumbing for graftlint's budget layers.

Layers 2 (``audit.py``), 3 (``sharding.py``), C (``concurrency.py``),
P (``perf.py``), S (``control.py``) and E (``state.py``) all commit a
JSON golden next to
the lint package and verify against it with the same contract: ``--regen`` rewrites the file
after an intentional change, ``--diff-out`` leaves a CI artifact on
mismatch, and a schema tag plus provenance header make stale files fail
loud instead of quietly passing. The first three grew that logic as
triplicated module tails; this module is the single implementation they
(and every future layer) share.

Two write paths, one atomicity story:

- :func:`write_golden` — one file, written to ``<path>.tmp`` and
  ``os.replace``d into place, so a crash mid-serialization never leaves
  a half-written golden behind.
- :func:`commit_goldens` — the all-or-nothing multi-file form behind
  ``python -m mercury_tpu.lint --regen`` (no ``--layer``): every doc is
  serialized to its tmp file first; only when *all* of them serialized
  does any ``os.replace`` run. A failure while preparing deletes the
  tmps and leaves every committed golden exactly as it was.

:func:`regen_all_goldens` is the driver for the latter: it *measures*
every layer first (the expensive, failure-prone part), then commits all
six goldens in one batch — so a plan that fails to trace aborts the
whole regen with nothing rewritten.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple


def provenance(regen_cmd: str,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The standard golden-file provenance header: jax/jaxlib/python
    versions plus the exact command that regenerates the file. Layers
    append layer-specific knobs (e.g. memory tolerance) via ``extra``."""
    import jax
    import jaxlib

    doc: Dict[str, Any] = {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "python": ".".join(map(str, sys.version_info[:3])),
        "regenerate_with": regen_cmd,
    }
    if extra:
        doc.update(extra)
    return doc


def _dump(doc: Dict[str, Any]) -> str:
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def write_golden(path: str, doc: Dict[str, Any]) -> str:
    """Atomically write one golden JSON file (tmp + ``os.replace``)."""
    blob = _dump(doc)  # serialize BEFORE touching the filesystem
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(blob)
    os.replace(tmp, path)
    return path


def commit_goldens(writes: Sequence[Tuple[str, Dict[str, Any]]],
                   ) -> List[str]:
    """All-or-nothing multi-golden commit.

    Every ``(path, doc)`` is serialized and staged to ``<path>.tmp``
    first; only when the whole batch staged cleanly are the tmps
    ``os.replace``d into place. Any failure during staging removes the
    tmps and re-raises — no committed golden is touched.
    """
    staged: List[Tuple[str, str]] = []
    try:
        for path, doc in writes:
            blob = _dump(doc)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(blob)
            staged.append((tmp, path))
    except Exception:
        for tmp, _ in staged:
            try:
                os.remove(tmp)
            except OSError:
                pass
        raise
    for tmp, path in staged:
        os.replace(tmp, path)
    return [path for path, _ in writes]


def load_golden(path: str, schema: str, regen_hint: str) -> Dict[str, Any]:
    """Load + schema-check a committed golden. Raises FileNotFoundError
    when missing (the CLI maps that to exit code 2 with a regen hint)
    and ValueError on a schema-tag mismatch."""
    if not os.path.exists(path):
        raise FileNotFoundError(path)
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != schema:
        raise ValueError(
            f"{path}: schema {doc.get('schema')!r}, expected {schema!r} "
            f"— regenerate with {regen_hint}")
    return doc


def write_diff_file(path: str, title: str, errors: Sequence[str],
                    warnings: Optional[Sequence[str]] = None) -> None:
    """The ``--diff-out`` CI artifact: findings under a ``# title``
    header, warnings (when given) under ``# warnings``."""
    lines = [f"# {title}"] + list(errors)
    if warnings is not None:
        lines += ["# warnings"] + list(warnings)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")


def diff_counts(what: str, expected: Dict[str, int],
                got: Dict[str, int]) -> List[str]:
    """Per-key count diff lines, the shared budget-comparison idiom."""
    lines = []
    for key in sorted(set(expected) | set(got)):
        e, g = expected.get(key, 0), got.get(key, 0)
        if e != g:
            lines.append(f"  {what}: {key} expected {e}, got {g} "
                         f"({g - e:+d})")
    return lines


# --------------------------------------------------------------------------
# atomic all-layer regen
# --------------------------------------------------------------------------

def regen_all_goldens(plans: Optional[Sequence[str]] = None,
                      budgets_path: Optional[str] = None,
                      shard_budgets_path: Optional[str] = None,
                      manifest_path: Optional[str] = None,
                      perf_budgets_path: Optional[str] = None,
                      control_path: Optional[str] = None,
                      state_schema_path: Optional[str] = None,
                      retrace_steps: int = 4,
                      ) -> Tuple[List[str], List[str]]:
    """Re-measure and rewrite EVERY layer's golden in one atomic batch.

    Measurement order is cheap-to-expensive (Layer E state-schema and
    Layer S control-plane extraction, manifest AST scan, Layer 2
    traces, Layer 3 compiles, Layer P compiles + retrace execution); a
    failure anywhere aborts before a single committed file changes.
    Returns ``(errors, warnings)`` where errors are the layers' hard
    invariants evaluated on the fresh measurements (a regen must not
    mask e.g. an f32 scoring leak — or an oscillating ladder) and
    warnings list the written files.
    """
    # Lazy layer imports: the layers import this module for their own
    # golden plumbing, so the dependency must point inward only at call
    # time.
    from mercury_tpu.lint import (audit, concurrency, control,
                                  modelcheck, perf, sharding)
    from mercury_tpu.lint import state as state_lint

    state_facts = state_lint.extract_state_facts()
    state_doc = state_lint.state_doc(state_facts)
    control_facts = control.extract_control_facts()
    control_doc = control.control_doc(control_facts)

    audit.ensure_cpu_devices()
    plan_names = tuple(plans) if plans else audit.PLAN_NAMES

    manifest_doc = concurrency.extract_manifest(
        [os.path.join(concurrency._repo_root(), m)
         for m in concurrency.HOT_THREAD_MODULES])
    audit_ms = [audit.measure_plan(p) for p in plan_names]
    shard_ms = [sharding.measure_shard_plan(p) for p in plan_names]
    perf_ms = [perf.measure_perf_plan(p) for p in plan_names]
    retrace_ms = [perf.measure_plan_retraces(p, steps=retrace_steps)
                  for p in plan_names]

    errors: List[str] = []
    errors.extend(state_lint.check_extraction(state_facts))
    errors.extend(control.check_extraction(control_facts))
    errors.extend(modelcheck.check_invariants(control_doc["machine"]))
    for m in audit_ms:
        errors.extend(audit.check_invariants(m))
    errors.extend(sharding.check_axis_registry())
    for m in shard_ms:
        errors.extend(sharding.check_shard_invariants(m))
    for m in perf_ms:
        errors.extend(perf.check_perf_invariants(m))

    writes = [
        (state_schema_path or state_lint.default_state_schema_path(),
         state_doc),
        (control_path or control.default_control_path(), control_doc),
        (manifest_path or concurrency.default_manifest_path(),
         manifest_doc),
        (budgets_path or audit.default_budgets_path(),
         audit.budgets_doc(audit_ms)),
        (shard_budgets_path or sharding.default_shard_budgets_path(),
         sharding.shard_budgets_doc(shard_ms)),
        (perf_budgets_path or perf.default_perf_budgets_path(),
         perf.perf_budgets_doc(perf_ms, retrace_ms)),
    ]
    written = commit_goldens(writes)
    warnings = [f"golden written to {p}" for p in written]
    return errors, warnings
