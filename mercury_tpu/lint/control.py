"""graftlint Layer S: the control plane as an extracted, checked machine.

The supervisor's degradation ladder, its SLO latches, probe pinning and
restart budgets form a small finite state machine — but until this layer
it lived implicitly in ~600 lines of ``runtime/supervisor.py`` and its
peers. Layer S makes it explicit three ways:

1. **Extract** (:func:`extract_control_facts`): an AST walk over
   ``runtime/supervisor.py``, ``sampling/scorer_service.py``,
   ``obs/anomaly.py`` and ``faults.py`` pulls the structural facts the
   machine is built from — the ladder levels, the ±1 transition deltas
   and their guards, which journal ``kind`` each transition site emits,
   the SLO breach latch, the probe pin, the restart-budget bookkeeping,
   the fault alphabet and the anomaly trigger names. Facts are semantic
   (no line numbers), so the golden only drifts on *behavioral* edits.
2. **Build + commit** (:func:`build_machine`, :func:`control_doc`): the
   facts deterministically construct the product transition system
   (state = ladder level × restart-budget bucket × SLO latch set ×
   probe-pin flag; every edge annotated with the journal kinds it
   emits) committed as ``lint/control_plane.json`` (schema
   ``graftlint_control_plane_v1``) with the standard ``--regen`` /
   ``--diff-out`` contract from ``lint/golden.py`` — code↔model drift
   is a lint failure. ``lint/modelcheck.py`` then BFS-explores the
   machine and proves the GLS01–GLS06 invariants as hard gates.
3. **Replay** (:func:`check_journal_conformance`): the runtime half,
   mirroring ``tracecheck.py`` — a recorded ``events.h{p}.jsonl`` is
   replayed against the committed machine and every observed transition
   the model does not allow (level skips, re-breach without release,
   probes while pinned, restarts past exhaustion, non-monotone budget
   attempts, unregistered kinds, broken parent chains) is a finding.
   ``python -m mercury_tpu.lint.control RUN_DIR`` is the CI entry the
   chaos job runs over its fault-matrix artifacts;
   :func:`conformance_coverage` reports allowed-but-never-observed
   transitions so the chaos matrix's blind spots are visible too.

Everything here is stdlib-only (AST + JSON): the lint-control CI job and
the chaos replay both run on jax-free machines. The replay is rotation-
and torn-shard-tolerant: unknown state components bind from the first
event that declares them (a rotated shard is a suffix of a valid run),
and only *contradictions* with already-replayed state are violations.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mercury_tpu.lint import golden

__all__ = [
    "CONTROL_SCHEMA", "extract_control_facts", "check_extraction",
    "build_machine", "control_doc", "default_control_path",
    "run_control_check", "check_journal_conformance",
    "conformance_coverage",
]

#: Golden schema tag; bump on any incompatible machine-shape change.
CONTROL_SCHEMA = "graftlint_control_plane_v1"

REGEN_HINT = "python -m mercury_tpu.lint --layer control --regen"

#: The modules the extractor walks, keyed by the short name facts use.
CONTROL_MODULES: Dict[str, str] = {
    "supervisor": os.path.join("runtime", "supervisor.py"),
    "scorer_service": os.path.join("sampling", "scorer_service.py"),
    "anomaly": os.path.join("obs", "anomaly.py"),
    "faults": "faults.py",
}

#: Supervisor methods that move control-plane state; each MUST journal
#: what it did (an unjournaled transition is invisible to the replay —
#: GLS11 makes that a lint failure, not a silent gap).
TRANSITION_SITES = ("_degrade", "_recover", "_try_restart",
                    "_note_exhausted", "_check_slos", "_maybe_probe")

#: Modeled SLO latch slots. The trainer registers one ladder SLO today
#: (``scorer_service``); two slots leave headroom while keeping the
#: product space small (4 levels × 4 buckets × 2² latch sets).
MODEL_SLO_SLOTS = ("slo0", "slo1")


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_control_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "control_plane.json")


def _registry_path() -> str:
    return os.path.join(_package_root(), "obs", "registry.py")


def _registered_kinds() -> Dict[str, str]:
    from mercury_tpu.lint.metrics import load_event_registry

    return load_event_registry(_registry_path())


# --------------------------------------------------------------------------
# AST fact extraction
# --------------------------------------------------------------------------

def _module_tree(key: str,
                 sources: Optional[Dict[str, str]] = None) -> ast.AST:
    rel = CONTROL_MODULES[key]
    if sources is not None and key in sources:
        return ast.parse(sources[key], filename=f"<fixture:{rel}>")
    path = os.path.join(_package_root(), rel)
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _methods(tree: ast.AST, class_name: str) -> Dict[str, ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == class_name:
            return {n.name: n for n in node.body
                    if isinstance(n, ast.FunctionDef)}
    return {}


def _module_literal(tree: ast.AST, name: str) -> Optional[Any]:
    """Value of a module-level ``NAME = <literal>`` assignment.
    ``frozenset({...})`` unwraps to its argument (the fault alphabet)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        value = node.value
        if (isinstance(value, ast.Call)
                and isinstance(value.func, ast.Name)
                and value.func.id == "frozenset" and value.args):
            value = value.args[0]
        try:
            return ast.literal_eval(value)
        except (ValueError, SyntaxError):
            return None
    return None


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _emit_kinds(fn: ast.AST) -> List[str]:
    """Journal kinds emitted inside ``fn`` — first-positional string
    constants of calls whose attribute contains ``emit`` and whose
    dotted callable name contains ``journal`` (the same producer-call
    signature Layer M's GLM04 census keys on)."""
    kinds: List[str] = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and "emit" in node.func.attr
                and "journal" in _dotted(node.func).lower()
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            kinds.append(node.args[0].value)
    return sorted(set(kinds))


def _is_level_attr(node: ast.AST) -> bool:
    return isinstance(node, ast.Attribute) and node.attr == "_level"


def _level_delta(fn: ast.AST) -> Optional[int]:
    """The signed step applied to ``self._level`` inside ``fn``: follows
    ``src = self._level; self._level = src ± k`` as well as the direct
    and augmented forms. None when the function never writes the level."""
    bound = set()
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and _is_level_attr(node.value)):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    bound.add(t.id)

    def from_level(node: ast.AST) -> bool:
        return (_is_level_attr(node)
                or (isinstance(node, ast.Name) and node.id in bound))

    for node in ast.walk(fn):
        if (isinstance(node, ast.AugAssign) and _is_level_attr(node.target)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, int)):
            k = node.value.value
            return k if isinstance(node.op, ast.Add) else -k
        if (isinstance(node, ast.Assign)
                and any(_is_level_attr(t) for t in node.targets)
                and isinstance(node.value, ast.BinOp)
                and from_level(node.value.left)
                and isinstance(node.value.right, ast.Constant)
                and isinstance(node.value.right.value, int)):
            k = node.value.right.value
            if isinstance(node.value.op, ast.Add):
                return k
            if isinstance(node.value.op, ast.Sub):
                return -k
    return None


def _has_level_guard(fn: ast.AST, ops: Tuple[type, ...]) -> bool:
    """An ``if self._level <cmp> ...: return`` early-out — the absorbing
    top (``>=``) / floor (``<=``) guard of the ladder."""
    for node in ast.walk(fn):
        if (isinstance(node, ast.If)
                and isinstance(node.test, ast.Compare)
                and _is_level_attr(node.test.left)
                and len(node.test.ops) == 1
                and isinstance(node.test.ops[0], ops)
                and any(isinstance(b, ast.Return)
                        for b in ast.walk(node))):
            return True
    return False


def _assigns_attr(fn: ast.AST, attr: str,
                  value: Any = ...) -> bool:
    """``<expr>.attr = ...`` anywhere in ``fn`` (optionally requiring a
    specific constant value)."""
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Attribute) and t.attr == attr
                        for t in node.targets)):
            if value is ...:
                return True
            if (isinstance(node.value, ast.Constant)
                    and node.value.value == value):
                return True
    return False


def _calls_method(fn: ast.AST, names: Tuple[str, ...]) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in names):
            return True
    return False


def _budget_reset_on_full_recovery(fn: ast.AST) -> bool:
    """``if dst == 0:`` (comparison against the constant 0) wrapping a
    ``restarts_used = 0`` reset — the budget refresh is gated on landing
    at the BOTTOM of the ladder, not on any ascent."""
    for node in ast.walk(fn):
        if not (isinstance(node, ast.If)
                and isinstance(node.test, ast.Compare)
                and len(node.test.ops) == 1
                and isinstance(node.test.ops[0], ast.Eq)
                and any(isinstance(c, ast.Constant) and c.value == 0
                        for c in node.test.comparators)):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign)
                    and any(isinstance(t, ast.Attribute)
                            and t.attr == "restarts_used"
                            for t in sub.targets)
                    and isinstance(sub.value, ast.Constant)
                    and sub.value.value == 0):
                return True
    return False


def _probe_pinned_by_slo(fn: ast.AST) -> bool:
    """``any(... .breached ...)`` feeding the probe's due condition —
    the pin that holds recovery while any SLO is latched."""
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "any"):
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Attribute)
                        and sub.attr == "breached"):
                    return True
    return False


def _increments_attr(fn: ast.AST, attr: str) -> bool:
    for node in ast.walk(fn):
        if (isinstance(node, ast.AugAssign)
                and isinstance(node.target, ast.Attribute)
                and node.target.attr == attr
                and isinstance(node.op, ast.Add)):
            return True
    return False


def _once_latch(fn: ast.AST, attr: str) -> bool:
    """``if x.attr: return`` + ``x.attr = True`` — the handled-once
    latch that stops a persistent condition from re-firing every tick."""
    guarded = any(
        isinstance(node, ast.If)
        and isinstance(node.test, ast.Attribute)
        and node.test.attr == attr
        and any(isinstance(b, ast.Return) for b in node.body)
        for node in ast.walk(fn))
    return guarded and _assigns_attr(fn, attr, True)


def _trigger_kinds(tree: ast.AST) -> List[str]:
    """First-arg string constants of ``self._trigger(...)`` calls — the
    anomaly engine's trigger alphabet."""
    kinds = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "_trigger"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            kinds.append(node.args[0].value)
    return sorted(set(kinds))


def extract_control_facts(
        sources: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Walk the control-plane modules and return the structural facts
    the machine is built from. ``sources`` overrides module source text
    by :data:`CONTROL_MODULES` key (seeded-violation fixtures)."""
    sup_tree = _module_tree("supervisor", sources)
    svc_tree = _module_tree("scorer_service", sources)
    ano_tree = _module_tree("anomaly", sources)
    flt_tree = _module_tree("faults", sources)

    methods = _methods(sup_tree, "HostSupervisor")
    sites = {name: (_emit_kinds(methods[name]) if name in methods else None)
             for name in TRANSITION_SITES}

    def fn(name: str) -> ast.AST:
        return methods.get(name, ast.parse("pass"))

    levels = _module_literal(sup_tree, "LEVEL_NAMES")
    buckets = _module_literal(sup_tree, "BUDGET_BUCKETS")
    fault_kinds = _module_literal(flt_tree, "KNOWN_KINDS")

    svc_kinds: List[str] = []
    for node in ast.walk(svc_tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            svc_kinds.extend(_emit_kinds(node))

    facts: Dict[str, Any] = {
        "modules": {k: CONTROL_MODULES[k].replace(os.sep, "/")
                    for k in sorted(CONTROL_MODULES)},
        "levels": list(levels) if levels else [],
        "buckets": list(buckets) if buckets else [],
        "degrade": {
            "delta": _level_delta(fn("_degrade")),
            "absorbing_guard": _has_level_guard(fn("_degrade"),
                                                (ast.GtE, ast.Gt)),
            "emits": sites.get("_degrade") or [],
        },
        "recover": {
            "delta": _level_delta(fn("_recover")),
            "floor_guard": _has_level_guard(fn("_recover"),
                                            (ast.LtE, ast.Lt)),
            "budget_reset_on_full_recovery":
                _budget_reset_on_full_recovery(fn("_recover")),
            "emits": sites.get("_recover") or [],
        },
        "slo": {
            "latched": _assigns_attr(fn("_check_slos"), "breached"),
            "breach_degrades": _calls_method(fn("_check_slos"),
                                             ("_degrade",)),
            "emits_breach": [k for k in sites.get("_check_slos") or []
                             if k.endswith("breach")],
            "emits_release": [k for k in sites.get("_check_slos") or []
                              if k.endswith("release")],
        },
        "probe": {
            "pinned_by_latched_slo":
                _probe_pinned_by_slo(fn("_maybe_probe")),
            "ok_recovers": _calls_method(fn("_maybe_probe"),
                                         ("_recover",)),
            "fail_degrades": _calls_method(fn("_maybe_probe"),
                                           ("report_failure", "_degrade")),
            "emits_ok": [k for k in sites.get("_maybe_probe") or []
                         if k.endswith("_ok")],
            "emits_fail": [k for k in sites.get("_maybe_probe") or []
                           if k.endswith("failed")],
        },
        "restart": {
            "consumes_budget_on_attempt":
                _increments_attr(fn("_try_restart"), "restarts_used"),
            "emits_ok": [k for k in sites.get("_try_restart") or []
                         if not k.endswith("failed")],
            "emits_fail": [k for k in sites.get("_try_restart") or []
                           if k.endswith("failed")],
        },
        "exhaustion": {
            "once_latched": _once_latch(fn("_note_exhausted"),
                                        "exhausted_handled"),
            "escalates_degrade": _calls_method(fn("_note_exhausted"),
                                               ("_degrade",)),
            "emits": sites.get("_note_exhausted") or [],
        },
        "transition_sites": sites,
        "fault_kinds": sorted(fault_kinds) if fault_kinds else [],
        "anomaly_triggers": _trigger_kinds(ano_tree),
        "peer_kinds": {
            "scorer_service": sorted(set(svc_kinds)),
            "faults": _emit_kinds(flt_tree),
            "anomaly": _emit_kinds(ano_tree),
        },
        "scorer_slo_latched": any(
            _assigns_attr(node, "slo_latched", True)
            for node in ast.walk(svc_tree)
            if isinstance(node, ast.FunctionDef)),
    }
    kinds: List[str] = []
    for site_kinds in sites.values():
        kinds.extend(site_kinds or [])
    facts["supervisor_kinds"] = sorted(set(kinds))
    return facts


# --------------------------------------------------------------------------
# static extraction gates (GLS10–GLS13)
# --------------------------------------------------------------------------

def check_extraction(facts: Dict[str, Any],
                     registered: Optional[Dict[str, str]] = None
                     ) -> List[str]:
    """Hard gates on the extracted facts themselves — violations the
    extractor can prove without building the machine (the level-skip and
    unjournaled-transition fixtures are caught here)."""
    errors: List[str] = []
    if not facts["levels"]:
        errors.append("GLS10 control: LEVEL_NAMES not extractable from "
                      "runtime/supervisor.py")
    if not facts["buckets"]:
        errors.append("GLS10 control: BUDGET_BUCKETS not extractable "
                      "from runtime/supervisor.py")
    if facts["degrade"]["delta"] != 1:
        errors.append(
            f"GLS10 control: _degrade moves the ladder by "
            f"{facts['degrade']['delta']} — levels must change by +1 "
            f"only (one level per decision, no skips)")
    if facts["recover"]["delta"] != -1:
        errors.append(
            f"GLS10 control: _recover moves the ladder by "
            f"{facts['recover']['delta']} — levels must change by -1 "
            f"only (one probe success climbs one level)")
    if not facts["degrade"]["absorbing_guard"]:
        errors.append("GLS10 control: _degrade has no top-of-ladder "
                      "guard — uniform must be absorbing")
    if not facts["recover"]["floor_guard"]:
        errors.append("GLS10 control: _recover has no level-0 floor "
                      "guard")
    for site, kinds in facts["transition_sites"].items():
        if kinds is None:
            errors.append(f"GLS11 control: transition site {site} not "
                          f"found in HostSupervisor")
        elif not kinds:
            errors.append(
                f"GLS11 control: transition site {site} emits no "
                f"journal kind — every control-plane transition must "
                f"be journaled (the conformance replay cannot see an "
                f"unjournaled move)")
    if not facts["recover"]["budget_reset_on_full_recovery"]:
        errors.append("GLS12 control: _recover does not reset restart "
                      "budgets on full recovery (dst == 0) — budgets "
                      "must reset exactly there and nowhere else")
    if not facts["restart"]["consumes_budget_on_attempt"]:
        errors.append("GLS12 control: _try_restart does not consume "
                      "budget on the attempt — budgets must be "
                      "monotone within an episode")
    if not facts["exhaustion"]["once_latched"]:
        errors.append("GLS12 control: _note_exhausted is not once-"
                      "latched (exhausted_handled) — a persistent "
                      "exhaustion would re-fire every tick")
    if registered is None:
        registered = _registered_kinds()
    emitted = set(facts["supervisor_kinds"])
    for kinds in facts["peer_kinds"].values():
        emitted.update(kinds)
    for kind in sorted(emitted - set(registered)):
        errors.append(f"GLS13 control: emitted journal kind {kind!r} "
                      f"is not in obs/registry.py::EVENT_KINDS")
    return errors


# --------------------------------------------------------------------------
# machine construction
# --------------------------------------------------------------------------

def _state_id(level: int, bucket: str, latched: frozenset,
              pinned: bool) -> str:
    latch = "+".join(sorted(latched)) if latched else "none"
    return f"L{level}/{bucket}/{latch}/{'pinned' if pinned else 'free'}"


def build_machine(facts: Dict[str, Any]) -> Dict[str, Any]:
    """Construct the explicit product transition system from the facts.

    Deterministic (sorted worklist, stable edge order) so the committed
    golden is byte-stable across regens. The budget component abstracts
    ``restarts_used``/``restart_budget`` into the ordered buckets of
    ``BUDGET_BUCKETS``; a restart attempt lands in ``partial`` or
    ``spent`` nondeterministically (the concrete budget is config), and
    exhaustion is reachable from any non-exhausted bucket (budget 0
    exhausts without any attempt)."""
    levels: List[str] = facts["levels"]
    buckets: List[str] = facts["buckets"] or ["fresh", "partial",
                                              "spent", "exhausted"]
    top = len(levels) - 1
    fresh, exhausted = buckets[0], buckets[-1]
    attempt_targets = [b for b in buckets[1:-1]]  # partial, spent
    latch_on = bool(facts["slo"]["latched"])
    pin_on = bool(facts["probe"]["pinned_by_latched_slo"])
    d_delta = facts["degrade"]["delta"] or 1
    r_delta = facts["recover"]["delta"] or -1
    d_emits = facts["degrade"]["emits"]
    r_emits = facts["recover"]["emits"]

    def deg(level: int) -> Optional[int]:
        dst = level + d_delta
        return dst if 0 <= dst <= top else None

    def rec(level: int) -> Optional[int]:
        dst = level + r_delta
        return dst if 0 <= dst <= top else None

    def edges_from(state: Tuple[int, str, frozenset]
                   ) -> List[Tuple[str, Tuple[int, str, frozenset],
                                   List[str]]]:
        level, bucket, latched = state
        pinned = pin_on and bool(latched)
        out = []
        # Restart attempts: budget consumed on the attempt, success or
        # failure alike; the bucket only ever moves up the order.
        if bucket not in (buckets[-2], exhausted):
            for nb in attempt_targets:
                out.append(("restart_ok", (level, nb, latched),
                            list(facts["restart"]["emits_ok"])))
                out.append(("restart_fail", (level, nb, latched),
                            list(facts["restart"]["emits_fail"])))
        elif bucket == buckets[-2]:
            pass  # spent: no attempts left, only exhaustion
        # Exhaustion of the escalating unit: once-latched, degrades one
        # level unless already at the absorbing top (where _degrade's
        # guard returns before journaling — only `exhausted` is emitted).
        if bucket != exhausted:
            emits = list(facts["exhaustion"]["emits"])
            nl = level
            if facts["exhaustion"]["escalates_degrade"]:
                d = deg(level)
                if d is not None:
                    emits += d_emits
                    nl = d
            out.append(("unit_exhausted", (nl, exhausted, latched),
                        emits))
        # SLO breach (rising edge, latches) / release (falling edge).
        for slot in MODEL_SLO_SLOTS:
            if latch_on and slot in latched:
                out.append((f"slo_release:{slot}",
                            (level, bucket, latched - {slot}),
                            list(facts["slo"]["emits_release"])))
                continue
            emits = list(facts["slo"]["emits_breach"])
            nl = level
            if facts["slo"]["breach_degrades"]:
                d = deg(level)
                if d is not None:
                    emits += d_emits
                    nl = d
            nlat = (latched | {slot}) if latch_on else latched
            out.append((f"slo_breach:{slot}", (nl, bucket, nlat), emits))
        # Recovery probes: only while degraded and not pinned; the climb
        # into level 0 refreshes the restart budget.
        if level > 0 and not pinned:
            r = rec(level)
            if r is not None and facts["probe"]["ok_recovers"]:
                nb = fresh if r == 0 else bucket
                out.append(("probe_ok", (r, nb, latched),
                            list(facts["probe"]["emits_ok"]) + r_emits))
            emits = list(facts["probe"]["emits_fail"])
            nl = level
            if facts["probe"]["fail_degrades"]:
                d = deg(level)
                if d is not None:
                    emits += d_emits
                    nl = d
            out.append(("probe_fail", (nl, bucket, latched), emits))
        # A degraded-path action failing on the trainer thread (the
        # level-1 sync refresh raising) escalates with no causal parent.
        if 0 < level < top:
            d = deg(level)
            if d is not None:
                out.append(("degraded_path_fail", (d, bucket, latched),
                            list(d_emits)))
        return out

    initial = (0, fresh, frozenset())
    seen = {initial}
    order = [initial]
    edges: List[Dict[str, Any]] = []
    frontier = [initial]
    while frontier:
        nxt: List[Tuple[int, str, frozenset]] = []
        for state in frontier:
            for inp, dst, emits in edges_from(state):
                pinned_src = pin_on and bool(state[2])
                pinned_dst = pin_on and bool(dst[2])
                edges.append({
                    "from": _state_id(state[0], state[1], state[2],
                                      pinned_src),
                    "input": inp,
                    "to": _state_id(dst[0], dst[1], dst[2], pinned_dst),
                    "emits": emits,
                })
                if dst not in seen:
                    seen.add(dst)
                    order.append(dst)
                    nxt.append(dst)
        frontier = sorted(nxt)

    states = [{
        "id": _state_id(lv, b, lat, pin_on and bool(lat)),
        "level": lv, "bucket": b, "latched": sorted(lat),
        "pinned": pin_on and bool(lat),
    } for lv, b, lat in order]

    # Parent-chain contract per kind: derived from same-edge emit
    # ordering (the second emit parents to the first) plus the static
    # causal links the code threads through stored event ids.
    parents: Dict[str, List[Optional[str]]] = {}
    for kind in facts["supervisor_kinds"]:
        parents[kind] = []
    for e in edges:
        for a, b in zip(e["emits"], e["emits"][1:]):
            if b in parents and a not in parents[b]:
                parents[b].append(a)
    static_parents: Dict[str, List[Optional[str]]] = {
        "supervisor/slo_breach": [None],
        "supervisor/slo_release": ["supervisor/slo_breach", None],
        "supervisor/degrade": [None],
        "supervisor/restart": [None],
        "supervisor/restart_failed": [None],
        "supervisor/exhausted": ["supervisor/restart_failed", None],
        "supervisor/probe_ok": ["supervisor/degrade", None],
        "supervisor/probe_failed": ["supervisor/degrade", None],
    }
    for kind, extra in static_parents.items():
        if kind in parents:
            for p in extra:
                if p not in parents[kind]:
                    parents[kind].append(p)

    kind_rules: Dict[str, Dict[str, Any]] = {}

    def _from_levels(kind: str) -> List[int]:
        out = set()
        lv = {s["id"]: s["level"] for s in states}
        for e in edges:
            if kind in e["emits"]:
                out.add(lv[e["from"]])
        return sorted(out)

    for kind in facts["degrade"]["emits"]:
        kind_rules[kind] = {"delta": d_delta,
                            "from_levels": _from_levels(kind)}
    for kind in facts["recover"]["emits"]:
        kind_rules[kind] = {"delta": r_delta,
                            "from_levels": _from_levels(kind),
                            "requires_unpinned": pin_on,
                            "resets_buckets_at": 0}
    for kind in facts["probe"]["emits_ok"]:
        kind_rules[kind] = {"probe": True,
                            "from_levels": _from_levels(kind),
                            "requires_unpinned": pin_on}
    for kind in facts["probe"]["emits_fail"]:
        kind_rules[kind] = {"probe": True,
                            "from_levels": _from_levels(kind),
                            "requires_unpinned": pin_on}
    for kind in facts["slo"]["emits_breach"]:
        kind_rules[kind] = {"latch": "set" if latch_on else "none"}
    for kind in facts["slo"]["emits_release"]:
        kind_rules[kind] = {"latch": "clear" if latch_on else "none"}
    for kind in facts["restart"]["emits_ok"]:
        kind_rules[kind] = {"budget": "attempt"}
    for kind in facts["restart"]["emits_fail"]:
        kind_rules[kind] = {"budget": "attempt"}
    for kind in facts["exhaustion"]["emits"]:
        kind_rules[kind] = {"budget": "exhaust"}

    registered = _registered_kinds()
    ambient = sorted(set(registered) - set(facts["supervisor_kinds"]))

    return {
        "initial": _state_id(*initial, False),
        "levels": list(levels),
        "buckets": list(buckets),
        "slo_slots": list(MODEL_SLO_SLOTS),
        "alphabet": {
            "ladder_inputs": sorted({e["input"] for e in edges}),
            "ambient_inputs": (
                [f"fault:{k}" for k in facts["fault_kinds"]]
                + [f"anomaly:{t}" for t in facts["anomaly_triggers"]]),
        },
        "states": states,
        "edges": edges,
        "kind_rules": kind_rules,
        "parents": parents,
        "ambient_kinds": ambient,
    }


def control_doc(facts: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The committed golden document. Provenance carries only the regen
    command (no jax versions — Layer S is stdlib-only and the golden
    must not drift on toolchain upgrades)."""
    if facts is None:
        facts = extract_control_facts()
    return {
        "schema": CONTROL_SCHEMA,
        "provenance": {"regenerate_with": REGEN_HINT},
        "facts": facts,
        "machine": build_machine(facts),
    }


# --------------------------------------------------------------------------
# golden verify / regen (the --layer control CLI contract)
# --------------------------------------------------------------------------

def _doc_diff(committed: Dict[str, Any],
              fresh: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    for section in ("facts", "machine"):
        a, b = committed.get(section, {}), fresh.get(section, {})
        for key in sorted(set(a) | set(b)):
            va, vb = a.get(key), b.get(key)
            if va == vb:
                continue
            if key in ("states", "edges") and isinstance(va, list) \
                    and isinstance(vb, list):
                ka = {json.dumps(x, sort_keys=True) for x in va}
                kb = {json.dumps(x, sort_keys=True) for x in vb}
                for gone in sorted(ka - kb)[:5]:
                    lines.append(f"  {section}.{key}: committed-only "
                                 f"{gone}")
                for new in sorted(kb - ka)[:5]:
                    lines.append(f"  {section}.{key}: code-only {new}")
                lines.append(f"  {section}.{key}: {len(va)} committed "
                             f"vs {len(vb)} extracted")
            else:
                lines.append(f"  {section}.{key}: committed "
                             f"{json.dumps(va, sort_keys=True)[:200]} "
                             f"vs extracted "
                             f"{json.dumps(vb, sort_keys=True)[:200]}")
    if lines:
        lines.insert(0, "control plane drifted from committed model "
                        f"(regenerate with {REGEN_HINT}):")
    return lines


def run_control_check(control_path: Optional[str] = None,
                      regen: bool = False,
                      diff_out: Optional[str] = None,
                      ) -> Tuple[List[str], List[str]]:
    """Layer S entry: extract, model-check, and verify (or ``--regen``)
    the committed control plane. Returns ``(errors, warnings)`` on the
    shared layer-CLI contract; raises FileNotFoundError when verifying
    with no committed golden (the CLI maps it to exit 2 + regen hint)."""
    from mercury_tpu.lint import modelcheck

    path = control_path or default_control_path()
    facts = extract_control_facts()
    errors = check_extraction(facts)
    doc = control_doc(facts)
    errors.extend(modelcheck.check_invariants(doc["machine"]))
    warnings: List[str] = []
    if regen:
        golden.write_golden(path, doc)
        warnings.append(f"control plane written to {path}")
        return errors, warnings
    committed = golden.load_golden(path, CONTROL_SCHEMA, REGEN_HINT)
    diff = _doc_diff(committed, doc)
    if diff:
        errors.extend(diff)
        if diff_out:
            golden.write_diff_file(diff_out,
                                   "graftlint control-plane diff", diff)
    return errors, warnings


# --------------------------------------------------------------------------
# runtime half: journal conformance replay
# --------------------------------------------------------------------------

def _load_machine(control_path: Optional[str] = None) -> Dict[str, Any]:
    doc = golden.load_golden(control_path or default_control_path(),
                             CONTROL_SCHEMA, REGEN_HINT)
    return doc["machine"]


def check_journal_conformance(events: Sequence[Dict[str, Any]],
                              machine: Optional[Dict[str, Any]] = None,
                              ) -> List[str]:
    """Replay recorded journal events against the committed machine;
    returns one finding per observed transition the model does not
    allow (empty = conformant).

    The replay is per-host and binds unknown state components from the
    first event that declares them, so a rotated shard (a suffix of a
    valid run) and a torn final line replay clean — only contradictions
    with already-replayed state are violations."""
    if machine is None:
        machine = _load_machine()
    by_host: Dict[int, List[Dict[str, Any]]] = {}
    for evt in events:
        if isinstance(evt, dict):
            by_host.setdefault(int(evt.get("host", 0)), []).append(evt)
    findings: List[str] = []
    for host in sorted(by_host):
        findings.extend(_replay_host(host, by_host[host], machine))
    return findings


def _replay_host(host: int, events: List[Dict[str, Any]],
                 machine: Dict[str, Any]) -> List[str]:
    rules = machine["kind_rules"]
    parents = machine["parents"]
    ambient = set(machine["ambient_kinds"])
    levels: List[str] = machine["levels"]
    buckets: List[str] = machine["buckets"]
    fresh, exhausted = buckets[0], buckets[-1]
    order = {b: i for i, b in enumerate(buckets)}
    findings: List[str] = []
    level: Optional[int] = None      # unknown until anchored
    latched: Dict[str, bool] = {}    # SLO name -> latch bit (known only)
    unit_bucket: Dict[str, str] = {}
    unit_attempt: Dict[str, int] = {}
    by_id: Dict[str, str] = {}       # event_id -> kind (earlier events)

    def flag(evt: Dict[str, Any], msg: str) -> None:
        findings.append(f"h{host} {evt.get('event_id')} "
                        f"step {evt.get('step')}: {msg}")

    for evt in events:
        kind = evt.get("kind")
        detail = evt.get("detail") or {}
        if kind in ambient:
            by_id[evt.get("event_id", "")] = kind
            continue
        if kind not in rules:
            flag(evt, f"journal kind {kind!r} is not in the model "
                      f"(unregistered or unmodeled transition)")
            by_id[evt.get("event_id", "")] = str(kind)
            continue
        rule = rules[kind]
        allowed = parents.get(kind)
        pid = evt.get("parent_id")
        if allowed is not None:
            if pid is None:
                if None not in allowed:
                    flag(evt, f"{kind} with no parent — the model "
                              f"requires a causal parent in {allowed}")
            elif pid in by_id and by_id[pid] not in allowed:
                flag(evt, f"{kind} parented to {by_id[pid]} — the "
                          f"model allows {allowed}")

        if "delta" in rule:  # degrade / recover
            frm, to = detail.get("from"), detail.get("to")
            if frm not in levels or to not in levels:
                flag(evt, f"{kind} between unknown levels "
                          f"{frm!r} -> {to!r}")
            else:
                fi, ti = levels.index(frm), levels.index(to)
                if ti - fi != rule["delta"]:
                    flag(evt, f"{kind} {frm} -> {to} skips levels — "
                              f"the model moves by {rule['delta']:+d} "
                              f"only")
                if level is None:
                    level = fi
                elif level != fi:
                    flag(evt, f"{kind} declares from={frm} but the "
                              f"replayed state is "
                              f"{levels[level]} — a transition between "
                              f"them was not journaled")
                if (rule.get("requires_unpinned")
                        and any(latched.values())):
                    pinned = sorted(k for k, v in latched.items() if v)
                    flag(evt, f"{kind} while SLO(s) {pinned} are "
                              f"latched — the probe pin forbids "
                              f"recovery until every SLO releases")
                level = ti
                if (rule.get("resets_buckets_at") == ti):
                    unit_bucket = {u: fresh for u in unit_bucket}
                    unit_attempt = {}
        elif rule.get("probe"):
            lv = detail.get("level")
            if isinstance(lv, int) and 0 <= lv < len(levels):
                if level is None:
                    level = lv
                elif level != lv:
                    flag(evt, f"{kind} at declared level "
                              f"{levels[lv]} but the replayed state "
                              f"is {levels[level]}")
            if level == 0:
                flag(evt, f"{kind} at level 0 — probes only run while "
                          f"degraded")
            if rule.get("requires_unpinned") and any(latched.values()):
                pinned = sorted(k for k, v in latched.items() if v)
                flag(evt, f"{kind} while SLO(s) {pinned} are latched — "
                          f"the pin holds probes until release")
        elif "latch" in rule:
            slo = str(detail.get("slo", "?"))
            if rule["latch"] == "set":
                if latched.get(slo) is True:
                    flag(evt, f"re-breach of SLO {slo!r} without a "
                              f"release — the rising-edge latch allows "
                              f"one breach per episode")
                latched[slo] = True
            elif rule["latch"] == "clear":
                if latched.get(slo) is False:
                    flag(evt, f"release of SLO {slo!r} that was not "
                              f"latched")
                latched[slo] = False
        elif "budget" in rule:
            unit = str(detail.get("unit", "?"))
            if rule["budget"] == "attempt":
                if unit_bucket.get(unit) == exhausted:
                    flag(evt, f"restart of {unit!r} after exhaustion — "
                              f"budgets reset only on full recovery")
                attempt = detail.get("attempt")
                budget = detail.get("budget")
                if isinstance(attempt, int):
                    last = unit_attempt.get(unit)
                    if last is not None and attempt <= last:
                        flag(evt, f"restart attempt {attempt} of "
                                  f"{unit!r} after attempt {last} — "
                                  f"budget use must be monotone within "
                                  f"an episode")
                    unit_attempt[unit] = attempt
                    nb = (buckets[-2]
                          if isinstance(budget, int) and attempt >= budget
                          else buckets[1])
                    if order[nb] >= order.get(
                            unit_bucket.get(unit, fresh), 0):
                        unit_bucket[unit] = nb
            elif rule["budget"] == "exhaust":
                if unit_bucket.get(unit) == exhausted:
                    flag(evt, f"duplicate exhaustion of {unit!r} — "
                              f"exhaustion is once-latched per episode")
                unit_bucket[unit] = exhausted
        by_id[evt.get("event_id", "")] = str(kind)
    return findings


def conformance_coverage(events: Sequence[Dict[str, Any]],
                         machine: Optional[Dict[str, Any]] = None,
                         ) -> List[str]:
    """Allowed-but-never-observed transitions across a run (or a whole
    chaos matrix): one warning per modeled kind (and per allowed source
    level for ladder kinds) that no event exercised. Coverage gaps are
    chaos-matrix blind spots, not failures."""
    if machine is None:
        machine = _load_machine()
    rules = machine["kind_rules"]
    levels: List[str] = machine["levels"]
    seen_kinds = set()
    seen_levels: Dict[str, set] = {}
    for evt in events:
        if not isinstance(evt, dict):
            continue
        kind = evt.get("kind")
        if kind not in rules:
            continue
        seen_kinds.add(kind)
        detail = evt.get("detail") or {}
        frm = detail.get("from")
        if isinstance(frm, str) and frm in levels:
            seen_levels.setdefault(kind, set()).add(levels.index(frm))
        lv = detail.get("level")
        if isinstance(lv, int):
            seen_levels.setdefault(kind, set()).add(lv)
    gaps: List[str] = []
    for kind in sorted(rules):
        if kind not in seen_kinds:
            gaps.append(f"coverage: modeled kind {kind} never observed")
            continue
        for lv in rules[kind].get("from_levels", []):
            if lv not in seen_levels.get(kind, set()):
                gaps.append(f"coverage: {kind} never observed from "
                            f"level {levels[lv]}")
    return gaps


# --------------------------------------------------------------------------
# CLI: python -m mercury_tpu.lint.control RUN_DIR [...]
# --------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    from mercury_tpu.obs.events import load_events

    ap = argparse.ArgumentParser(
        prog="python -m mercury_tpu.lint.control",
        description="Replay recorded event journals against the "
                    "committed control-plane machine "
                    "(lint/control_plane.json); exit 1 on any "
                    "nonconforming transition.")
    ap.add_argument("run_dirs", nargs="+",
                    help="run directories containing events.h*.jsonl")
    ap.add_argument("--control-plane", default=None, metavar="PATH",
                    help="machine golden to replay against (default: "
                         "the committed lint/control_plane.json)")
    ap.add_argument("--coverage", action="store_true",
                    help="also report modeled transitions never "
                         "observed across the given runs (warnings)")
    args = ap.parse_args(argv)

    try:
        machine = _load_machine(args.control_plane)
    except FileNotFoundError as exc:
        print(f"graftlint control: machine golden missing ({exc}) — "
              f"run {REGEN_HINT} first", file=sys.stderr)
        return 2
    except (OSError, ValueError) as exc:
        print(f"graftlint control: {exc}", file=sys.stderr)
        return 2

    rc = 0
    merged: List[Dict[str, Any]] = []
    for run_dir in args.run_dirs:
        events = load_events(run_dir)
        if not events:
            print(f"graftlint control: no journal events under "
                  f"{run_dir} (expected events.h*.jsonl)",
                  file=sys.stderr)
            rc = 2
            continue
        merged.extend(events)
        findings = check_journal_conformance(events, machine)
        for line in findings:
            print(f"{run_dir}: {line}")
        if findings:
            rc = max(rc, 1)
        else:
            print(f"graftlint control: {run_dir}: {len(events)} events "
                  f"replay conformant")
    if args.coverage and merged:
        for line in conformance_coverage(merged, machine):
            print(f"warning: {line}")
    return rc


if __name__ == "__main__":
    sys.exit(main())
