"""graftlint Layer 1: AST rules for JAX-hazard patterns.

Pure stdlib — this module must never import jax (Layer 1 runs in CI
before any backend exists, and on machines with no accelerator stack).

Every rule is registered in :data:`RULES` with an ID (``GL1xx``), a slug,
a one-line summary, and a fix-it hint; the catalog with examples lives in
``docs/LINT.md``. Rules operate on a shared per-file analysis
(:class:`ModuleAnalysis`) that computes, once:

- the parent map and the enclosing function of every node;
- import aliases for ``numpy`` / ``jax.numpy`` / ``jax.lax``;
- the set of *traced* functions — functions whose bodies execute under a
  jax trace, detected structurally: decorated with ``jit``-family
  decorators, passed (possibly through ``functools.partial`` or local
  ``name = other`` aliases) into ``jax.jit`` / ``shard_map`` /
  ``lax.scan`` / ``lax.cond`` / ``grad`` / ``vmap`` / …, or nested inside
  such a function (closures trace with their parent).

The traced-function detection is deliberately structural rather than a
call-graph: it has no false positives on plain host code, and the JAX
rules (host-sync, tracer-branch, mutable-global closure) only fire inside
functions it marks.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

__all__ = ["Rule", "RULES", "RawFinding", "ModuleAnalysis", "run_rules"]


@dataclass(frozen=True)
class Rule:
    id: str          # "GL101"
    slug: str        # "key-reuse"
    summary: str     # one-line what/why
    hint: str        # generic fix-it


@dataclass(frozen=True)
class RawFinding:
    rule: Rule
    line: int
    col: int
    message: str


RULES: Dict[str, Rule] = {}


def _rule(id: str, slug: str, summary: str, hint: str) -> Rule:
    r = Rule(id, slug, summary, hint)
    RULES[id] = r
    return r


GL100 = _rule(
    "GL100", "bad-suppression",
    "graftlint suppression comment is malformed, names an unknown rule, "
    "or omits the mandatory reason",
    "write `# graftlint: disable=GL1xx -- why this is intentional`",
)
GL101 = _rule(
    "GL101", "key-reuse",
    "a PRNG key is consumed by two jax.random calls (including "
    "split-then-reuse-parent): the two draws are correlated, not "
    "independent",
    "split fresh subkeys (`k1, k2 = jax.random.split(key)`) or fold_in a "
    "distinct constant per stream; never pass an already-consumed key to "
    "another jax.random call",
)
GL102 = _rule(
    "GL102", "host-sync",
    "host synchronization inside a traced function (`.item()`, "
    "`np.asarray`, `jax.device_get`, `float()` on a tracer): blocks "
    "dispatch or fails at trace time",
    "keep device values on device inside jit; move host conversion "
    "outside the traced function or use jnp equivalents",
)
GL103 = _rule(
    "GL103", "tracer-branch",
    "Python `if`/`assert`/`while` on a tracer-valued expression inside a "
    "traced function: the branch is resolved once at trace time (or "
    "raises TracerBoolConversionError)",
    "use `lax.cond` / `jnp.where` for data-dependent control flow, or "
    "`checkify` for runtime assertions",
)
GL104 = _rule(
    "GL104", "mutable-default",
    "mutable default argument (list/dict/set): shared across calls, and "
    "a silent trace-time constant under jit",
    "default to None and construct the container inside the function",
)
GL105 = _rule(
    "GL105", "unordered-iter",
    "dict/set iteration feeding array or pytree construction: the "
    "structure (and thus the traced program) depends on insertion/hash "
    "order",
    "iterate `sorted(d.items())` (or a fixed key list) so the pytree "
    "structure is deterministic",
)
GL106 = _rule(
    "GL106", "use-after-donate",
    "an argument donated via `donate_argnums` is read after the call: "
    "its buffer may already be aliased to the output (garbage or a "
    "deleted-array error)",
    "rebind the donated name from the call's output "
    "(`state, aux = step(state, ...)`) or drop the donation",
)
GL107 = _rule(
    "GL107", "mutable-global",
    "traced function reads a mutable module-level global: the value is "
    "baked in at trace time, so later mutation is silently invisible to "
    "the compiled program",
    "pass the value as an argument (static or traced) or make the global "
    "an immutable constant",
)
GL108 = _rule(
    "GL108", "eager-log-format",
    "eager f-string/.format/% formatting in a logging call: the string "
    "is built even when the level is disabled — on a hot path that is "
    "per-step host work for nothing",
    "use lazy %-style args: `log.info(\"loss %.4f at %d\", loss, step)`",
)
GL110 = _rule(
    "GL110", "unconstrained-jit-output",
    "jax.jit/pjit pins in_shardings but not out_shardings: the output "
    "layout is whatever GSPMD propagation picks, which can silently "
    "gather a sharded result back to one layout per release",
    "pin out_shardings alongside in_shardings (or drop both and commit "
    "layouts on the arrays)",
)
GL111 = _rule(
    "GL111", "unsharded-device-put",
    "jax.device_put without an explicit sharding in a hot module: the "
    "array lands wherever the default device points, and the first "
    "computation touching it pays a silent reshard",
    "pass the target placement: "
    "`jax.device_put(x, NamedSharding(mesh, spec))`",
)
GL112 = _rule(
    "GL112", "manual-all-gather",
    "lax.all_gather in jit-traced (non-shard_map) code: under GSPMD a "
    "with_sharding_constraint expresses the same layout change and lets "
    "XLA schedule/fuse the collective instead of pinning it",
    "replace with `jax.lax.with_sharding_constraint(x, sharding)`, or "
    "move the call inside a shard_map where manual collectives belong",
)
GL113 = _rule(
    "GL113", "unknown-mesh-axis",
    "mesh-axis name literal not in the canonical registry "
    "(parallel/mesh.py MESH_AXES): a typo here shards nothing and fails "
    "only at mesh-binding time, far from the mistake",
    "use a canonical axis name (data/model/seq/pipe) or register the "
    "new axis in parallel/mesh.py MESH_AXES",
)
GL114 = _rule(
    "GL114", "worker-device-sync",
    "blocking device sync (device_get / block_until_ready / numpy "
    "materialization) inside a thread-worker function (threading.Thread "
    "target, executor.submit): the worker serializes against device "
    "execution, stalling the very pipeline it exists to overlap",
    "keep worker threads host-only; when the sync IS the worker's job "
    "(e.g. a prefetch thread absorbing an index readback so the training "
    "thread never waits), suppress with the reason spelled out: "
    "`# graftlint: disable=GL114 -- <why this thread may block>`",
)

# Layer C host-concurrency rules (lint/concurrency.py). Registered here
# so suppressions and --select resolve their IDs/slugs, but deliberately
# NOT in _CHECKS: Layer 1 never runs them — they need the cross-module
# thread-entry / lock-discipline model only the concurrency layer builds.
GL120 = _rule(
    "GL120", "unguarded-shared-attr",
    "shared mutable attribute crosses the thread boundary without its "
    "guarding lock: written on one side (thread entry point or trainer "
    "thread) and accessed on the other while the lock that guards its "
    "other accesses is not held",
    "hold the inferred guard around every cross-thread access (snapshot "
    "under the lock, use the copy outside), or restructure to a "
    "single-writer whole-object publish and suppress with the invariant "
    "spelled out",
)
GL121 = _rule(
    "GL121", "queue-discipline",
    "inconsistent queue.Queue blocking discipline: a no-timeout put into "
    "a BOUNDED queue (a shutdown wedge — the producer parks forever once "
    "the consumer stops draining), or one queue mixing unbounded "
    "blocking get() with timeout gets",
    "bounded puts loop `put(item, timeout=...)` checking the shutdown "
    "flag (the PrefetchPipeline._publish idiom); pick ONE get discipline "
    "per queue",
)
GL122 = _rule(
    "GL122", "unjoined-thread",
    "non-daemon thread started with no reachable join(): interpreter "
    "shutdown blocks forever on it if its work wedges",
    "join it on the shutdown path (bounded timeout + log), or mark it "
    "daemon=True when abandoning it at exit is safe",
)
GL123 = _rule(
    "GL123", "lock-order",
    "two locks acquired in opposite nesting orders on different code "
    "paths of the same class: classic deadlock ordering once the paths "
    "run on different threads",
    "impose one global acquisition order (document it on the class) or "
    "collapse the critical sections onto a single lock",
)
GL124 = _rule(
    "GL124", "blocking-under-lock",
    "blocking call (thread/queue join, unbounded queue get(), "
    "time.sleep) while holding a lock: every thread touching that lock "
    "stalls for the full wait",
    "snapshot state under the lock and block after releasing it",
)
GL125 = _rule(
    "GL125", "undeclared-thread",
    "thread / executor pool / queue not declared in "
    "lint/thread_manifest.json (or declared with a different daemon "
    "flag / capacity): the process's concurrency surface changed "
    "without review",
    "run `python -m mercury_tpu.lint --layer concurrency --regen`, "
    "review the manifest diff, and commit it",
)

# Layer P retrace-hazard rules (runtime counterpart: the retrace guard
# in lint/tracecheck.py catches these when they slip through). Static
# and hot-module scoped, like GL111: the step path is where a silent
# compile-per-step treadmill costs real money.
GL130 = _rule(
    "GL130", "retrace-closure-capture",
    "traced function closes over a variable its enclosing function "
    "rebinds (loop target, augmented assignment, repeated assignment): "
    "the captured python value either bakes stale into the trace or "
    "re-traces the function on every rebind",
    "pass the value as an argument (traced, or static if hashable) "
    "instead of closing over it, or hoist the jit outside the loop "
    "that rebinds the captured name",
)
GL131 = _rule(
    "GL131", "shape-branch-retrace",
    "host-level `if`/`while` on a traced argument's shape/len/ndim "
    "inside a jitted function: every distinct input shape traces and "
    "compiles its own executable — a shape-churning caller turns one "
    "program into a compile treadmill",
    "pad or bucket inputs to a fixed shape before the jit boundary, or "
    "move the shape branch outside the traced function",
)
GL132 = _rule(
    "GL132", "np-constant-in-trace",
    "np. constant constructor inside a traced function: a numpy scalar "
    "or array built per call is strongly typed where a python literal "
    "stays weak, so the operand dtype (and with it the jit cache key) "
    "depends on which call site ran — weak-type churn is a retrace",
    "hoist the constant to module scope, or spell it as a python "
    "literal / jnp constructor so its type is owned by the trace",
)
GL133 = _rule(
    "GL133", "unhashable-static-arg",
    "jit static argument fed an unhashable value: a list/dict/set "
    "literal at the call site (TypeError at best, per-call conversion "
    "churn at worst) or a mutable default on the wrapped function's "
    "static parameter",
    "make static arguments hashable and call-stable: tuples instead of "
    "lists, frozen structs instead of dicts; hoist per-call conversions "
    "out of the call expression",
)

# Mirror of parallel/mesh.py::MESH_AXES. Layer 1 must not import jax (or
# anything that does), so the set is duplicated here; Layer 3's audit
# cross-checks the two at every run (lint/sharding.py
# check_axis_registry), so drift cannot persist.
_MESH_AXES = ("data", "model", "seq", "pipe", "scorer")


# --------------------------------------------------------------------------
# shared per-module analysis
# --------------------------------------------------------------------------

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef)

# Call targets whose function-valued arguments are traced by jax. Bare
# names (from-imports) and attribute names (jax.jit, lax.scan, ...) both
# match on the final component.
_TRACE_ENTRY_NAMES = {
    "jit", "pjit", "shard_map", "vmap", "pmap", "xmap", "grad",
    "value_and_grad", "jacfwd", "jacrev", "hessian", "linearize", "jvp",
    "vjp", "scan", "cond", "switch", "while_loop", "fori_loop",
    "associative_scan", "checkpoint", "remat", "custom_jvp", "custom_vjp",
    "eval_shape", "make_jaxpr", "named_call", "defjvp", "defvjp",
}

# The subset of trace entries whose bodies run in MANUAL SPMD — named
# mesh axes are bound and hand-written collectives are the idiom there
# (GL112 exempts these).
_MANUAL_ENTRY_NAMES = {"shard_map", "pmap", "xmap"}

_RANDOM_CONSUMERS = {
    "bits", "uniform", "normal", "truncated_normal", "randint", "choice",
    "permutation", "shuffle", "bernoulli", "categorical", "gumbel",
    "exponential", "gamma", "beta", "dirichlet", "laplace", "logistic",
    "poisson", "rademacher", "split", "fold_in", "ball", "cauchy",
    "multivariate_normal", "orthogonal", "t",
}

_RANDOM_MODULE_HINTS = {"random", "jr", "jrandom", "jrand"}

_LOG_METHODS = {"debug", "info", "warning", "warn", "error", "exception",
                "critical", "log"}


def _dotted(node: ast.AST) -> Optional[str]:
    """'jax.random.split' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _last_attr(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


class ModuleAnalysis:
    """One pass of shared facts rules key on (see module docstring)."""

    def __init__(self, tree: ast.Module, path: str = "<string>") -> None:
        self.tree = tree
        self.path = path
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.np_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.lax_aliases: Set[str] = set()
        self._collect_imports()
        self._collect_defs()
        self.traced: Set[ast.AST] = set()
        self.manual: Set[ast.AST] = set()
        self._detect_traced()
        self.workers: Set[ast.AST] = set()
        self._detect_workers()
        self.mutable_globals: Dict[str, int] = {}
        self._collect_mutable_globals()

    # -------------------------------------------------------------- imports
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name
                    if a.name == "numpy":
                        self.np_aliases.add(name)
                    elif a.name == "jax.numpy":
                        self.jnp_aliases.add(name)
                    elif a.name == "jax.lax":
                        self.lax_aliases.add(name)
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                for a in node.names:
                    name = a.asname or a.name
                    if mod == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(name)
                    elif mod == "jax" and a.name == "lax":
                        self.lax_aliases.add(name)
                    elif mod == "jax" and a.name == "random":
                        pass  # handled via _RANDOM_MODULE_HINTS
        # Conventional aliases even without an import statement in this
        # file (a rule should not go blind because of a star import).
        self.np_aliases.add("np")
        self.jnp_aliases.add("jnp")
        self.lax_aliases.add("lax")

    # ------------------------------------------------------- traced funcs
    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None and not isinstance(cur, _FUNC_NODES):
            cur = self.parents.get(cur)
        return cur

    def _scope_of(self, node: ast.AST) -> ast.AST:
        return self.enclosing_function(node) or self.tree

    def _collect_defs(self) -> None:
        # name -> funcdefs per defining scope, and alias edges
        # (scope, alias) -> {source names} from `alias = source`. Shared by
        # the traced-function and thread-worker detectors.
        self._defs: Dict[Tuple[int, str], List[ast.AST]] = {}
        self._aliases: Dict[Tuple[int, str], Set[str]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES):
                scope = self._scope_of(node)
                self._defs.setdefault(
                    (id(scope), node.name), []).append(node)
            elif isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Name):
                scope = self._scope_of(node)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self._aliases.setdefault(
                            (id(scope), t.id), set()).add(node.value.id)

    def _make_marker(self, target: Set[ast.AST]):
        seen: Set[Tuple[int, str]] = set()

        def mark(scope: ast.AST, name: str) -> None:
            key = (id(scope), name)
            if key in seen:
                return
            seen.add(key)
            for src in self._aliases.get(key, ()):  # fn = body → body too
                mark(scope, src)
            for fn in self._defs.get(key, ()):
                target.add(fn)

        return mark

    def _propagate_closures(self, *sets: Set[ast.AST]) -> None:
        # Functions nested inside a marked function share its fate
        # (trace with it / run on its thread).
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if not isinstance(node, _FUNC_NODES):
                    continue
                enc = self.enclosing_function(node)
                if enc is None:
                    continue
                for s in sets:
                    if enc in s and node not in s:
                        s.add(node)
                        changed = True

    def _detect_traced(self) -> None:
        mark = self._make_marker(self.traced)
        mark_manual = self._make_marker(self.manual)

        def candidate_funcs(arg: ast.AST) -> Iterator[ast.expr]:
            """The function-valued expressions a trace-entry arg carries
            (unwrapping functools.partial one level)."""
            if isinstance(arg, (ast.Name, ast.Lambda)):
                yield arg
            elif isinstance(arg, ast.Call) and _last_attr(
                    arg.func) == "partial" and arg.args:
                yield from candidate_funcs(arg.args[0])

        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            entry = _last_attr(node.func)
            if entry not in _TRACE_ENTRY_NAMES:
                continue
            scope = self._scope_of(node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for fn in candidate_funcs(arg):
                    if isinstance(fn, ast.Name):
                        mark(scope, fn.id)
                        if entry in _MANUAL_ENTRY_NAMES:
                            mark_manual(scope, fn.id)

        # decorators: @jax.jit, @partial(jax.jit, ...), @shard_map(...)
        for node in ast.walk(self.tree):
            if not isinstance(node, _FUNC_NODES):
                continue
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                name = _last_attr(target)
                if name == "partial" and isinstance(dec, ast.Call) \
                        and dec.args:
                    name = _last_attr(dec.args[0])
                if name in _TRACE_ENTRY_NAMES:
                    self.traced.add(node)
                    if name in _MANUAL_ENTRY_NAMES:
                        self.manual.add(node)

        self._propagate_closures(self.traced, self.manual)

    # ------------------------------------------------------- worker funcs
    def _detect_workers(self) -> None:
        """Functions handed to a background thread: ``threading.Thread``'s
        ``target=`` and ``executor.submit``'s first argument. The hand-off
        is structural (no call-graph following): a helper a worker merely
        *calls* is not marked — GL114 stays scoped to code that is
        unambiguously on a worker thread."""
        mark = self._make_marker(self.workers)
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            entry = _last_attr(node.func)
            targets: List[ast.AST] = []
            if entry == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        targets.append(kw.value)
            elif entry == "submit" and isinstance(node.func, ast.Attribute) \
                    and node.args:
                targets.append(node.args[0])
            for t in targets:
                name = _last_attr(t)  # bare name or self._method terminal
                if name is None:
                    continue
                mark(self._scope_of(node), name)
                # Methods and module functions both define at tree scope.
                mark(self.tree, name)
        self._propagate_closures(self.workers)

    # -------------------------------------------------- mutable globals
    def _collect_mutable_globals(self) -> None:
        for stmt in self.tree.body:
            targets: List[ast.expr] = []
            value: Optional[ast.expr] = None
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            if value is None or not _is_mutable_ctor(value):
                continue
            for t in targets:
                if isinstance(t, ast.Name):
                    self.mutable_globals[t.id] = stmt.lineno

    # ------------------------------------------------------------ helpers
    def nodes_of_function(self, fn: ast.AST) -> Iterator[ast.AST]:
        """Nodes whose *immediately* enclosing function is ``fn`` (nested
        function bodies belong to their own scope)."""
        for node in ast.walk(fn):
            if node is fn:
                continue
            if self.enclosing_function(node) is fn:
                yield node

    def functions(self) -> Iterator[ast.AST]:
        for node in ast.walk(self.tree):
            if isinstance(node, _FUNC_NODES):
                yield node

    def calls_into(self, aliases: Set[str], node: ast.AST) -> bool:
        """Does the subtree contain a call rooted at one of ``aliases``
        (e.g. ``jnp.any(...)``)?"""
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                dotted = _dotted(sub.func)
                if dotted and dotted.split(".")[0] in aliases:
                    return True
        return False


def _is_mutable_ctor(node: ast.expr) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                         ast.DictComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
        return node.func.id in {"list", "dict", "set", "defaultdict",
                                "deque", "OrderedDict", "Counter"}
    return False


# --------------------------------------------------------------------------
# rules
# --------------------------------------------------------------------------

def _random_consume_key(node: ast.Call) -> Optional[str]:
    """The dotted key expression a jax.random call consumes, or None."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr not in _RANDOM_CONSUMERS:
        return None
    base = _dotted(func.value)
    if base is None:
        return None
    parts = set(base.split("."))
    if not (parts & _RANDOM_MODULE_HINTS):
        return None
    if not node.args:
        return None
    return _dotted(node.args[0])


def _stores_in(node: ast.AST) -> Iterator[str]:
    """Dotted names this statement (re)binds."""
    targets: List[ast.expr] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        targets = [node.target]
    elif isinstance(node, ast.NamedExpr):
        targets = [node.target]
    elif isinstance(node, (ast.For, ast.AsyncFor)):
        targets = [node.target]
    elif isinstance(node, (ast.With, ast.AsyncWith)):
        targets = [i.optional_vars for i in node.items
                   if i.optional_vars is not None]
    for t in targets:
        for el in ast.walk(t):
            name = _dotted(el)
            if name:
                yield name


def check_key_reuse(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    scopes: List[ast.AST] = [an.tree] + list(an.functions())
    for fn in scopes:
        events: List[Tuple[Tuple[int, int, int], str, str, ast.AST]] = []
        nodes = (an.nodes_of_function(fn) if isinstance(fn, _FUNC_NODES)
                 else (n for n in ast.walk(fn)
                       if an.enclosing_function(n) is None and n is not fn))
        for node in nodes:
            if isinstance(node, ast.Call):
                key = _random_consume_key(node)
                if key:
                    events.append(((node.lineno, node.col_offset, 0),
                                   "consume", key, node))
            for name in _stores_in(node):
                end = (getattr(node, "end_lineno", node.lineno) or
                       node.lineno)
                endc = getattr(node, "end_col_offset", 0) or 0
                events.append(((end, endc, 1), "store", name, node))
        events.sort(key=lambda e: e[0])
        live: Dict[str, ast.AST] = {}  # key name -> first consuming call
        for _, kind, name, node in events:
            if kind == "store":
                live.pop(name, None)
                continue
            first = live.get(name)
            if first is None:
                live[name] = node
            else:
                fn_name = _last_attr(node.func) or "?"
                out.append(RawFinding(
                    GL101, node.lineno, node.col_offset,
                    f"PRNG key '{name}' consumed again by jax.random."
                    f"{fn_name} (first consumed on line "
                    f"{first.lineno}) — the streams are correlated",
                ))
        del live
    return out


_NP_CONVERTERS = {"asarray", "array", "copyto", "save", "float32",
                  "float64", "int32", "int64"}


def check_host_sync(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for fn in an.traced:
        for node in an.nodes_of_function(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = _last_attr(func)
            if isinstance(func, ast.Attribute) and attr == "item" \
                    and not node.args:
                out.append(RawFinding(
                    GL102, node.lineno, node.col_offset,
                    ".item() inside a traced function forces a "
                    "device→host sync (or a tracer error)",
                ))
                continue
            if attr == "device_get":
                out.append(RawFinding(
                    GL102, node.lineno, node.col_offset,
                    "jax.device_get inside a traced function is a host "
                    "round-trip per call",
                ))
                continue
            if isinstance(func, ast.Attribute) \
                    and attr in _NP_CONVERTERS:
                base = _dotted(func.value)
                if base and base.split(".")[0] in an.np_aliases:
                    out.append(RawFinding(
                        GL102, node.lineno, node.col_offset,
                        f"numpy {attr}() inside a traced function "
                        "materializes on host (tracer error or silent "
                        "constant-folding)",
                    ))
                    continue
            if isinstance(func, ast.Name) and func.id in ("float", "int",
                                                          "bool") \
                    and node.args:
                arg = node.args[0]
                if an.calls_into(an.jnp_aliases | an.lax_aliases, arg):
                    out.append(RawFinding(
                        GL102, node.lineno, node.col_offset,
                        f"{func.id}() on a tracer-valued expression "
                        "inside a traced function is a concretization "
                        "error (or a hidden host sync outside jit)",
                    ))
    return out


def check_tracer_branch(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    jaxy = None
    for fn in an.traced:
        if jaxy is None:
            jaxy = an.jnp_aliases | an.lax_aliases
        for node in an.nodes_of_function(fn):
            test: Optional[ast.expr] = None
            kind = None
            if isinstance(node, (ast.If, ast.While)):
                test, kind = node.test, type(node).__name__.lower()
            elif isinstance(node, ast.Assert):
                test, kind = node.test, "assert"
            elif isinstance(node, ast.IfExp):
                test, kind = node.test, "conditional expression"
            if test is None:
                continue
            if an.calls_into(jaxy, test):
                out.append(RawFinding(
                    GL103, node.lineno, node.col_offset,
                    f"Python {kind} on a tracer-valued expression inside "
                    "a traced function: resolved once at trace time",
                ))
    return out


def check_mutable_default(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for fn in an.functions():
        args = fn.args
        for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None]:
            if _is_mutable_ctor(default):
                out.append(RawFinding(
                    GL104, default.lineno, default.col_offset,
                    f"mutable default argument in {fn.name}(): shared "
                    "across calls",
                ))
    return out


_ARRAY_CTORS = {"stack", "concatenate", "array", "asarray", "hstack",
                "vstack"}


def _unordered_iterable(node: ast.expr) -> Optional[str]:
    """'d.values()' / 'set(...)' description if ``node`` iterates in
    dict/set order, None otherwise (sorted(...) launders it)."""
    if isinstance(node, ast.Call):
        attr = _last_attr(node.func)
        if isinstance(node.func, ast.Attribute) and attr in (
                "values", "keys", "items"):
            base = _dotted(node.func.value) or "dict"
            return f"{base}.{attr}()"
        if isinstance(node.func, ast.Name) and node.func.id == "set":
            return "set(...)"
        if isinstance(node.func, ast.Name) and node.func.id == "list" \
                and node.args:
            return _unordered_iterable(node.args[0])
    if isinstance(node, (ast.Set, ast.SetComp)):
        return "a set literal"
    return None


def check_unordered_iter(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call):
            continue
        attr = _last_attr(node.func)
        if attr not in _ARRAY_CTORS:
            continue
        if isinstance(node.func, ast.Attribute):
            base = _dotted(node.func.value)
            if not base or base.split(".")[0] not in an.jnp_aliases \
                    | an.np_aliases:
                continue
        else:
            continue
        for arg in node.args:
            src = _unordered_iterable(arg)
            if src is None and isinstance(
                    arg, (ast.ListComp, ast.GeneratorExp)):
                src = _unordered_iterable(arg.generators[0].iter)
            if src is not None:
                out.append(RawFinding(
                    GL105, arg.lineno, arg.col_offset,
                    f"{attr}() consumes {src}: array/pytree layout "
                    "depends on dict/set iteration order",
                ))
    return out


def _donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """For a ``jax.jit(..., donate_argnums=...)`` call with a constant
    argnums, the donated positions; None if absent/non-constant."""
    if _last_attr(call.func) not in ("jit", "pjit"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)) and all(
                isinstance(e, ast.Constant) and isinstance(e.value, int)
                for e in v.elts):
            return {e.value for e in v.elts}
        return None
    return None


def check_use_after_donate(an: ModuleAnalysis) -> List[RawFinding]:
    # name -> donated positions, for module/function-local `f = jax.jit(...,
    # donate_argnums=...)` bindings (constant argnums only).
    donators: Dict[str, Set[int]] = {}
    for node in ast.walk(an.tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            pos = _donated_positions(node.value)
            if pos:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donators[t.id] = pos
    if not donators:
        return []
    out: List[RawFinding] = []
    scopes: List[ast.AST] = [an.tree] + list(an.functions())
    for fn in scopes:
        nodes = (list(an.nodes_of_function(fn))
                 if isinstance(fn, _FUNC_NODES)
                 else [n for n in ast.walk(fn)
                       if an.enclosing_function(n) is None and n is not fn])
        calls = [n for n in nodes if isinstance(n, ast.Call)
                 and isinstance(n.func, ast.Name)
                 and n.func.id in donators]
        for call in calls:
            donated = {call.args[i].id for i in donators[call.func.id]
                       if i < len(call.args)
                       and isinstance(call.args[i], ast.Name)}
            if not donated:
                continue
            # names the call's own statement rebinds (state, m = f(state))
            stmt = call
            while stmt in an.parents and not isinstance(
                    stmt, ast.stmt):
                stmt = an.parents[stmt]
            rebound = set(_stores_in(stmt)) if isinstance(
                stmt, ast.stmt) else set()
            pos = (call.lineno, call.col_offset)
            for node in nodes:
                if not isinstance(node, ast.Name) or not isinstance(
                        node.ctx, ast.Load):
                    continue
                if node.id not in donated or node.id in rebound:
                    continue
                if (node.lineno, node.col_offset) <= pos:
                    continue
                out.append(RawFinding(
                    GL106, node.lineno, node.col_offset,
                    f"'{node.id}' was donated to {call.func.id}() on "
                    f"line {call.lineno} and is read afterwards: its "
                    "buffer may be aliased away",
                ))
                break  # one finding per donated name per call
    return out


def check_mutable_global(an: ModuleAnalysis) -> List[RawFinding]:
    if not an.mutable_globals:
        return []
    out: List[RawFinding] = []
    for fn in an.traced:
        local: Set[str] = {a.arg for a in list(fn.args.args)
                           + list(fn.args.kwonlyargs)
                           + list(fn.args.posonlyargs)}
        if fn.args.vararg:
            local.add(fn.args.vararg.arg)
        if fn.args.kwarg:
            local.add(fn.args.kwarg.arg)
        for node in an.nodes_of_function(fn):
            for name in _stores_in(node):
                local.add(name.split(".")[0])
        seen: Set[str] = set()
        for node in an.nodes_of_function(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) \
                    and node.id in an.mutable_globals \
                    and node.id not in local and node.id not in seen:
                seen.add(node.id)
                out.append(RawFinding(
                    GL107, node.lineno, node.col_offset,
                    f"traced function reads mutable module global "
                    f"'{node.id}' (defined line "
                    f"{an.mutable_globals[node.id]}): its value is "
                    "frozen into the trace",
                ))
    return out


def _is_eager_format(arg: ast.expr) -> Optional[str]:
    if isinstance(arg, ast.JoinedStr):
        return "f-string"
    if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Mod):
        return "eager %-interpolation"
    if isinstance(arg, ast.Call) and _last_attr(arg.func) == "format":
        return ".format()"
    return None


def check_eager_log_format(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call) or not isinstance(
                node.func, ast.Attribute):
            continue
        if node.func.attr not in _LOG_METHODS:
            continue
        receiver = (_dotted(node.func.value) or "").lower()
        if "log" not in receiver:
            continue
        idx = 1 if node.func.attr == "log" else 0
        if len(node.args) <= idx:
            continue
        how = _is_eager_format(node.args[idx])
        if how:
            out.append(RawFinding(
                GL108, node.lineno, node.col_offset,
                f"{how} built eagerly in a {node.func.attr}() log call",
            ))
    return out


def check_unconstrained_jit_output(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_attr(node.func) not in ("jit", "pjit"):
            continue
        kws = {kw.arg for kw in node.keywords if kw.arg}
        if "in_shardings" in kws and "out_shardings" not in kws:
            out.append(RawFinding(
                GL110, node.lineno, node.col_offset,
                "jit call pins in_shardings but leaves out_shardings to "
                "GSPMD: the output layout is propagation's choice",
            ))
    return out


# Modules whose device_put placements are per-step costs: a bare
# device_put there puts an implicit reshard on the hot path. "<string>"
# counts as hot so the rule is unit-testable through lint_source.
_HOT_DIRS = ("parallel", "train", "sampling", "ops")


def _in_hot_module(path: str) -> bool:
    if path == "<string>":
        return True
    parts = path.replace("\\", "/").split("/")
    return any(d in parts[:-1] for d in _HOT_DIRS)


def check_unsharded_device_put(an: ModuleAnalysis) -> List[RawFinding]:
    if not _in_hot_module(an.path):
        return []
    out: List[RawFinding] = []
    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last_attr(node.func) != "device_put":
            continue
        has_placement = len(node.args) >= 2 or any(
            kw.arg == "device" for kw in node.keywords)
        if not has_placement:
            out.append(RawFinding(
                GL111, node.lineno, node.col_offset,
                "device_put without an explicit sharding in a hot "
                "module: placement falls to the default device",
            ))
    return out


def check_manual_all_gather(an: ModuleAnalysis) -> List[RawFinding]:
    out: List[RawFinding] = []
    for fn in an.traced:
        if fn in an.manual:
            continue
        for node in an.nodes_of_function(fn):
            if not isinstance(node, ast.Call):
                continue
            if _last_attr(node.func) != "all_gather":
                continue
            base = _dotted(node.func)
            if base and base.split(".")[0] not in (
                    an.lax_aliases | {"jax"}):
                continue
            out.append(RawFinding(
                GL112, node.lineno, node.col_offset,
                "lax.all_gather in jit-traced (non-shard_map) code: a "
                "with_sharding_constraint expresses the same layout and "
                "lets XLA schedule the collective",
            ))
    return out


# Keyword names that carry mesh-axis names as strings, and the positional
# slot of the axis-name argument in lax collectives.
_AXIS_KWARG_NAMES = {
    "axis_name", "data_axis", "model_axis", "seq_axis", "pipe_axis",
    "sp_axis", "moe_ep_axis", "ep_axis", "mesh_axis", "stat_axis",
}
_AXIS_ARG_POSITIONS = {
    "psum": 1, "pmean": 1, "pmax": 1, "pmin": 1, "ppermute": 1,
    "all_gather": 1, "all_to_all": 1, "pbroadcast": 1, "psum_scatter": 1,
    "axis_index": 0, "axis_size": 0,
}


def _axis_literals(node: ast.expr) -> Iterator[ast.Constant]:
    """String constants in an axis-naming expression (a literal or a
    tuple/list of literals)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        yield node
    elif isinstance(node, (ast.Tuple, ast.List)):
        for el in node.elts:
            yield from _axis_literals(el)


def check_unknown_mesh_axis(an: ModuleAnalysis) -> List[RawFinding]:
    suspects: List[ast.Constant] = []
    for node in ast.walk(an.tree):
        if isinstance(node, _FUNC_NODES):
            # `def f(..., axis: str = "data")`: axis-named params' string
            # defaults are axis names.
            args = node.args
            named = list(args.posonlyargs) + list(args.args)
            defaults = list(args.defaults)
            for arg, default in zip(named[len(named) - len(defaults):],
                                    defaults):
                if (arg.arg in _AXIS_KWARG_NAMES or arg.arg == "axis") \
                        and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    suspects.append(default)
            for arg, default in zip(args.kwonlyargs, args.kw_defaults):
                if default is not None \
                        and (arg.arg in _AXIS_KWARG_NAMES
                             or arg.arg == "axis") \
                        and isinstance(default, ast.Constant) \
                        and isinstance(default.value, str):
                    suspects.append(default)
            continue
        if not isinstance(node, ast.Call):
            continue
        name = _last_attr(node.func)
        # P("data") / PartitionSpec("data", None)
        if name in ("P", "PartitionSpec"):
            for arg in node.args:
                suspects.extend(_axis_literals(arg))
        # Mesh(devices, ("data", "model"))
        if name == "Mesh" and len(node.args) >= 2:
            suspects.extend(_axis_literals(node.args[1]))
        # lax.psum(x, "data"), lax.axis_index("data"), ...
        pos = _AXIS_ARG_POSITIONS.get(name)
        if pos is not None and len(node.args) > pos:
            suspects.extend(_axis_literals(node.args[pos]))
        # axis_name= / data_axis= / ... kwargs anywhere
        for kw in node.keywords:
            if kw.arg in _AXIS_KWARG_NAMES:
                suspects.extend(_axis_literals(kw.value))
    out: List[RawFinding] = []
    for lit in suspects:
        if lit.value not in _MESH_AXES:
            out.append(RawFinding(
                GL113, lit.lineno, lit.col_offset,
                f"mesh-axis literal {lit.value!r} is not in the "
                f"canonical registry {_MESH_AXES} "
                "(parallel/mesh.py MESH_AXES)",
            ))
    return out


def check_worker_sync(an: ModuleAnalysis) -> List[RawFinding]:
    """GL114: blocking device syncs inside thread-worker functions.

    The prefetch/streaming design puts exactly one sync per hand-off on
    the worker (materializing the in-flight index output, fencing the
    staging-buffer reuse) — and those sites carry suppressions explaining
    themselves. Any OTHER sync on a worker thread is the bug this rule
    exists for: it re-serializes the worker against device execution, so
    the overlap the thread was spawned to buy quietly disappears.
    """
    out: List[RawFinding] = []
    for fn in an.workers:
        for node in an.nodes_of_function(fn):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            attr = _last_attr(func)
            if attr == "block_until_ready":
                out.append(RawFinding(
                    GL114, node.lineno, node.col_offset,
                    "block_until_ready() on a worker thread parks it "
                    "until device execution drains",
                ))
            elif attr == "device_get":
                out.append(RawFinding(
                    GL114, node.lineno, node.col_offset,
                    "jax.device_get on a worker thread is a blocking "
                    "device→host transfer",
                ))
            elif isinstance(func, ast.Attribute) \
                    and attr in ("asarray", "array"):
                base = _dotted(func.value)
                if base and base.split(".")[0] in an.np_aliases:
                    out.append(RawFinding(
                        GL114, node.lineno, node.col_offset,
                        f"numpy {attr}() on a worker thread blocks on "
                        "any device-resident input it is handed",
                    ))
    return out


def _bound_names(an: ModuleAnalysis, fn: ast.AST) -> Set[str]:
    """Names bound inside ``fn``'s immediate scope: parameters, assign /
    aug-assign / for targets, with-as and walrus bindings."""
    names: Set[str] = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args)
              + list(args.kwonlyargs)):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    for node in an.nodes_of_function(fn):
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        elif isinstance(node, _FUNC_NODES):
            names.add(node.name)
    return names


def check_retrace_closure_capture(an: ModuleAnalysis) -> List[RawFinding]:
    """GL130: traced nested function reads a name its enclosing function
    churns (rebinds in a loop, aug-assigns, or assigns repeatedly)."""
    if not _in_hot_module(an.path):
        return []
    out: List[RawFinding] = []
    for fn in sorted(an.traced, key=lambda n: n.lineno):
        enc = an.enclosing_function(fn)
        if enc is None or enc in an.traced:
            continue  # module-level, or a closure inside another trace
        # Only rebinds that happen AFTER the traced def (or the loop the
        # def sits inside) churn the capture; straight-line assignments
        # before it are config normalization, stable by trace time.
        ancestors: Set[ast.AST] = set()
        cursor: Optional[ast.AST] = fn
        while cursor is not None:
            ancestors.add(cursor)
            cursor = an.parents.get(cursor)
        churned: Set[str] = set()
        assign_counts: Dict[str, int] = {}
        late_assigns: Set[str] = set()
        for node in an.nodes_of_function(enc):
            if isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name):
                if node.lineno > fn.lineno:
                    churned.add(node.target.id)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                if node in ancestors or node.lineno > fn.lineno:
                    for t in ast.walk(node.target):
                        if isinstance(t, ast.Name):
                            churned.add(t.id)
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    for nm in ast.walk(t):
                        if isinstance(nm, ast.Name):
                            assign_counts[nm.id] = \
                                assign_counts.get(nm.id, 0) + 1
                            if nm.lineno > fn.lineno:
                                late_assigns.add(nm.id)
        churned |= {n for n in late_assigns
                    if assign_counts.get(n, 0) >= 2}
        if not churned:
            continue
        local = _bound_names(an, fn)
        reported: Set[str] = set()
        for node in an.nodes_of_function(fn):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, ast.Load) and node.id in churned \
                    and node.id not in local \
                    and node.id not in reported:
                reported.add(node.id)
                out.append(RawFinding(
                    GL130, node.lineno, node.col_offset,
                    f"traced function '{fn.name}' closes over "
                    f"'{node.id}', which '{enc.name}' rebinds — the "
                    "captured value bakes stale into the trace or "
                    "re-traces on every rebind"))
    return out


def check_shape_branch_retrace(an: ModuleAnalysis) -> List[RawFinding]:
    """GL131: if/while test probes a traced parameter's shape."""
    if not _in_hot_module(an.path):
        return []
    out: List[RawFinding] = []
    for fn in sorted(an.traced, key=lambda n: n.lineno):
        params: Set[str] = set()
        args = fn.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            params.add(a.arg)
        for node in an.nodes_of_function(fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if isinstance(node, ast.If) and node.body and not node.orelse \
                    and all(isinstance(s, ast.Raise) for s in node.body):
                # `if x.shape...: raise` is static shape *validation* —
                # a one-shot trace-time guard, not a per-shape branch
                continue
            probe = _shape_probe(node.test, params)
            if probe:
                out.append(RawFinding(
                    GL131, node.test.lineno, node.test.col_offset,
                    f"traced function '{fn.name}' branches on "
                    f"`{probe}` — each distinct input shape compiles "
                    "its own executable"))
    return out


def _shape_probe(test: ast.AST, params: Set[str]) -> Optional[str]:
    for sub in ast.walk(test):
        if isinstance(sub, ast.Attribute) \
                and sub.attr in ("shape", "ndim", "size"):
            dotted = _dotted(sub)
            if dotted and dotted.split(".")[0] in params:
                return dotted
        elif isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Name) and sub.func.id == "len" \
                and sub.args and isinstance(sub.args[0], ast.Name) \
                and sub.args[0].id in params:
            return f"len({sub.args[0].id})"
    return None


#: np constructors whose *literal-argument* use inside a trace builds a
#: fresh strongly-typed constant per call (GL132). Converting a traced
#: value with np.asarray is GL102's host-sync territory, not this.
_NP_CONST_CTORS = {
    "array", "asarray", "ones", "zeros", "full", "arange", "eye",
    "linspace", "float32", "float64", "float16", "int8", "int16",
    "int32", "int64", "uint8", "uint16", "uint32", "uint64", "bool_",
}


def _literal_only(node: ast.AST) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, (ast.Name, ast.Call, ast.Attribute)):
            return False
    return True


def check_np_constant_in_trace(an: ModuleAnalysis) -> List[RawFinding]:
    """GL132: per-call np constant built inside a traced function."""
    if not _in_hot_module(an.path):
        return []
    out: List[RawFinding] = []
    for fn in sorted(an.traced, key=lambda n: n.lineno):
        for node in an.nodes_of_function(fn):
            if not isinstance(node, ast.Call):
                continue
            dotted = _dotted(node.func)
            if not dotted or "." not in dotted:
                continue
            base, last = dotted.split(".")[0], dotted.split(".")[-1]
            if base not in an.np_aliases \
                    or last not in _NP_CONST_CTORS:
                continue
            if node.args and not all(_literal_only(a)
                                     for a in node.args):
                continue  # converting a value: GL102's territory
            out.append(RawFinding(
                GL132, node.lineno, node.col_offset,
                f"`{dotted}(...)` builds a strongly-typed numpy "
                f"constant per call inside traced function "
                f"'{fn.name}' — weak-type churn against python "
                "literals re-traces; hoist it to module scope"))
    return out


def _static_slots(call: ast.Call) -> Tuple[List[int], List[str]]:
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, int):
                    nums.append(v.value)
        elif kw.arg == "static_argnames":
            vals = (kw.value.elts
                    if isinstance(kw.value, (ast.Tuple, ast.List))
                    else [kw.value])
            for v in vals:
                if isinstance(v, ast.Constant) and isinstance(
                        v.value, str):
                    names.append(v.value)
    return nums, names


def check_unhashable_static_arg(an: ModuleAnalysis) -> List[RawFinding]:
    """GL133: mutable defaults on static parameters, and call sites
    passing unhashable literals at static positions."""
    if not _in_hot_module(an.path):
        return []
    out: List[RawFinding] = []
    defs_by_name: Dict[str, ast.AST] = {}
    for f in an.functions():
        defs_by_name.setdefault(f.name, f)

    def flag_mutable_defaults(fn: ast.AST, nums: List[int],
                              names: List[str], where: ast.AST) -> None:
        args = list(fn.args.posonlyargs) + list(fn.args.args)
        defaults = fn.args.defaults
        offset = len(args) - len(defaults)
        for i, default in enumerate(defaults):
            pos = offset + i
            if pos >= len(args):
                continue
            pname = args[pos].arg
            if (pos in nums or pname in names) \
                    and _is_mutable_ctor(default):
                out.append(RawFinding(
                    GL133, where.lineno, where.col_offset,
                    f"static parameter '{pname}' of '{fn.name}' has a "
                    "mutable default — jit static arguments must be "
                    "hashable"))

    jitted_calls: Dict[str, Tuple[List[int], List[str]]] = {}
    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call) \
                or _last_attr(node.func) not in ("jit", "pjit"):
            continue
        nums, names = _static_slots(node)
        if not nums and not names:
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            wrapped = defs_by_name.get(node.args[0].id)
            if wrapped is not None:
                flag_mutable_defaults(wrapped, nums, names, node)
            parent = an.parents.get(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        jitted_calls[t.id] = (nums, names)

    # decorator form: @partial(jax.jit, static_argnums=...)
    for fn in an.functions():
        for dec in fn.decorator_list:
            if isinstance(dec, ast.Call) and _last_attr(
                    dec.func) == "partial" and dec.args \
                    and _last_attr(dec.args[0]) in ("jit", "pjit"):
                nums, names = _static_slots(dec)
                if nums or names:
                    flag_mutable_defaults(fn, nums, names, dec)
                    jitted_calls[fn.name] = (nums, names)

    for node in ast.walk(an.tree):
        if not isinstance(node, ast.Call) \
                or not isinstance(node.func, ast.Name) \
                or node.func.id not in jitted_calls:
            continue
        nums, names = jitted_calls[node.func.id]
        for i, arg in enumerate(node.args):
            if i in nums and _is_mutable_ctor(arg):
                out.append(RawFinding(
                    GL133, arg.lineno, arg.col_offset,
                    f"unhashable literal at static position {i} of "
                    f"jitted '{node.func.id}' — jit raises on it, and "
                    "a per-call conversion would re-trace every call"))
        for kw in node.keywords:
            if kw.arg in names and _is_mutable_ctor(kw.value):
                out.append(RawFinding(
                    GL133, kw.value.lineno, kw.value.col_offset,
                    f"unhashable literal for static argument "
                    f"'{kw.arg}' of jitted '{node.func.id}' — jit "
                    "static arguments must be hashable"))
    return out


_CHECKS = (
    check_key_reuse,
    check_host_sync,
    check_tracer_branch,
    check_mutable_default,
    check_unordered_iter,
    check_use_after_donate,
    check_mutable_global,
    check_eager_log_format,
    check_unconstrained_jit_output,
    check_unsharded_device_put,
    check_manual_all_gather,
    check_unknown_mesh_axis,
    check_worker_sync,
    check_retrace_closure_capture,
    check_shape_branch_retrace,
    check_np_constant_in_trace,
    check_unhashable_static_arg,
)


def run_rules(tree: ast.Module,
              select: Optional[Sequence[str]] = None,
              path: str = "<string>") -> List[RawFinding]:
    """All raw (pre-suppression) findings for a parsed module. ``path``
    scopes the path-sensitive rules (GL111 fires in hot modules only)."""
    an = ModuleAnalysis(tree, path=path)
    findings: List[RawFinding] = []
    for check in _CHECKS:
        findings.extend(check(an))
    if select is not None:
        wanted = set(select)
        findings = [f for f in findings
                    if f.rule.id in wanted or f.rule.slug in wanted]
    findings.sort(key=lambda f: (f.line, f.col, f.rule.id))
    return findings
