"""graftlint Layer P: AOT cost/roofline budgets + fusion/precision scan.

Layers 2/3 pin the traced program's *structure* (collectives, sharding,
memory); this layer pins its *cost*. For every plan in the matrix it
AOT-compiles the step on the CPU mesh and commits three families of
facts to ``lint/perf_budgets.json``:

- **Scoped cost budgets.** ``compiled.cost_analysis()`` total FLOPs and
  bytes-accessed anchor the roofline; a jaxpr walk (dot/conv FLOP
  formulas, 1 FLOP/element for elementwise, scan bodies weighted by
  trip count) attributes estimated FLOPs and operand bytes to the five
  named scopes the step factories anchor (``mercury_scoring``,
  ``mercury_grad_sync``, ``mercury_augmentation``, ``mercury_optimizer``,
  ``mercury_input_fuse``), giving per-scope arithmetic intensity.
  Estimates are deterministic per jax version — that is all a ratchet
  needs; they are not a performance model.
- **Scoring-FLOP ceiling (hard).** Scoring FLOPs as a fraction of step
  FLOPs is the paper's economics: *Not All Samples Are Created Equal*
  only pays when selection stays a small fraction of the step. Each
  plan commits a ceiling (measured fraction plus headroom at regen
  time); exceeding it is an error that is NEVER demoted, version skew
  or not. **Unscoped FLOP growth** (estimated FLOPs outside every
  mercury scope) is the companion finding, mirroring Layer 3's
  unscoped-collective rule: compute nobody claimed is compute nobody
  budgeted.
- **Fusion/precision HLO scan.** The post-optimization HLO is walked
  per computation: f32 ``convert`` results carrying a
  ``mercury_scoring`` op_name are precision leaks (hard error on bf16
  scoring plans — the post-fusion generalization of Layer 3's dataflow
  walk); ``copy``/``transpose`` ops attributed to any mercury scope are
  layout churn, ratcheted per scope; elementwise ops carrying
  ``mercury_input_fuse`` op_names that sit *outside* any fused
  computation are exactly the chains PR 11's kernel exists to fuse,
  ratcheted with named examples.

The runtime half of Layer P — the retrace guard that executes each plan
and pins steady-state compile counts — lives in
:mod:`mercury_tpu.lint.tracecheck`; its per-plan expectations are
committed in this file's ``retrace`` section so one golden carries the
whole perf contract. Regenerate with
``python -m mercury_tpu.lint --layer perf --regen`` (or the atomic
all-layer ``python -m mercury_tpu.lint --regen``).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mercury_tpu.lint import golden
from mercury_tpu.lint.audit import (
    PLAN_NAMES,
    _BUILDERS,
    _name_stack,
    ensure_cpu_devices,
)

SCHEMA = "graftlint_perf_budgets_v1"

#: The named scopes the step factories anchor — the attribution targets.
PERF_SCOPES = ("mercury_scoring", "mercury_grad_sync",
               "mercury_augmentation", "mercury_optimizer",
               "mercury_input_fuse")

#: Attribution is first-match so nested scopes (the fused ingest kernel
#: runs inside the augmentation region) don't double-count: most
#: specific first.
_ATTRIBUTION_ORDER = ("mercury_input_fuse", "mercury_scoring",
                      "mercury_grad_sync", "mercury_augmentation",
                      "mercury_optimizer")

#: Relative drift tolerated on ratcheted FLOP/byte counts before a
#: finding fires (recorded in provenance so old goldens keep their own).
DEFAULT_TOLERANCE = 0.10

#: Regen-time headroom multiplier for the scoring-FLOP fraction ceiling.
SCORING_FRAC_HEADROOM = 1.25

_EW_PRIMS = frozenset({
    "add", "sub", "mul", "div", "rem", "max", "min", "neg", "abs",
    "sign", "floor", "ceil", "round", "exp", "expm1", "log", "log1p",
    "tanh", "logistic", "rsqrt", "sqrt", "cbrt", "square",
    "reciprocal", "pow", "integer_pow", "erf", "erfc", "erf_inv",
    "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
    "cosh", "select_n", "clamp", "nextafter", "add_any",
    "convert_element_type",
})
_REDUCE_PRIMS = frozenset({
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "reduce_and", "reduce_or", "argmax", "argmin", "cumsum", "cumprod",
    "cummax", "cummin", "reduce_precision", "psum", "all_reduce",
})

#: HLO opcodes the input-fuse scan treats as "should have fused".
_HLO_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "exponential", "log", "tanh", "logistic", "negate", "abs", "sign",
    "sqrt", "rsqrt", "power", "convert", "compare", "select", "and",
    "or", "xor", "not", "clamp",
})

#: One HLO instruction: ``%name = <type> <opcode>(...)``.
_HLO_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\S+)\s+([\w\-]+)\(")
#: One HLO computation header: ``[ENTRY] %name (params) -> type {``.
_HLO_COMPUTATION_RE = re.compile(
    r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.+\{\s*$")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
#: A bf16-typed operand in an instruction's argument list — HLO text
#: prints operands with their shapes: ``convert(bf16[4,4]{1,0} %x)``.
_BF16_OPERAND_RE = re.compile(r"\(\s*bf16\[")


def default_perf_budgets_path() -> str:
    return os.path.join(os.path.dirname(__file__), "perf_budgets.json")


# --------------------------------------------------------------------------
# jaxpr cost attribution
# --------------------------------------------------------------------------

def _prod(shape) -> int:
    out = 1
    for s in shape:
        out *= int(s)
    return out


def _out_size(eqn) -> int:
    return max((_prod(v.aval.shape) for v in eqn.outvars
                if getattr(v, "aval", None) is not None
                and hasattr(v.aval, "shape")), default=0)


def _in_size(eqn) -> int:
    return max((_prod(v.aval.shape) for v in eqn.invars
                if getattr(v, "aval", None) is not None
                and hasattr(v.aval, "shape")), default=0)


def eqn_flops(eqn) -> float:
    """Deterministic FLOP estimate for one equation: exact formulas for
    dot/conv, size-proportional for elementwise/reductions, zero for
    layout/control ops. Ratchet fodder, not a performance model."""
    name = eqn.primitive.name
    try:
        if name == "dot_general":
            (lhs_c, _), _ = eqn.params["dimension_numbers"]
            lhs = eqn.invars[0].aval
            k = _prod(lhs.shape[i] for i in lhs_c)
            return 2.0 * _out_size(eqn) * k
        if name == "conv_general_dilated":
            dn = eqn.params["dimension_numbers"]
            rhs = eqn.invars[1].aval
            out_features = rhs.shape[dn.rhs_spec[0]]
            k = _prod(rhs.shape) / max(1, out_features)
            return 2.0 * _out_size(eqn) * k
        if name in _EW_PRIMS:
            return float(_out_size(eqn))
        if name in _REDUCE_PRIMS:
            return float(_in_size(eqn))
    except Exception:
        return 0.0
    return 0.0


def eqn_bytes(eqn) -> float:
    """Operand + result bytes if nothing were fused or cached — the
    denominator of the per-scope arithmetic-intensity estimate."""
    total = 0.0
    for v in list(eqn.invars) + list(eqn.outvars):
        aval = getattr(v, "aval", None)
        shape = getattr(aval, "shape", None)
        dtype = getattr(aval, "dtype", None)
        if shape is None or dtype is None:
            continue
        total += _prod(shape) * dtype.itemsize
    return total


def _sub_jaxprs_weighted(eqn):
    """(sub_jaxpr, weight) pairs for one equation — scan bodies count
    ``length`` times, every other higher-order body once."""
    weight = 1
    if eqn.primitive.name == "scan":
        weight = int(eqn.params.get("length", 1) or 1)
    for value in eqn.params.values():
        values = value if isinstance(value, (list, tuple)) else (value,)
        for v in values:
            if hasattr(v, "eqns"):
                yield v, weight
            elif hasattr(v, "jaxpr"):
                yield v.jaxpr, weight


def walk_costed_eqns(jaxpr, _mult: int = 1):
    """Yield ``(eqn, multiplier)`` over the whole program, recursing into
    sub-jaxprs with scan trip counts folded into the multiplier."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn, _mult
        for sub, weight in _sub_jaxprs_weighted(eqn):
            yield from walk_costed_eqns(sub, _mult * weight)


def _attribute_scope(stack: str) -> Optional[str]:
    for scope in _ATTRIBUTION_ORDER:
        if scope in stack:
            return scope
    return None


# --------------------------------------------------------------------------
# HLO fusion / precision scan
# --------------------------------------------------------------------------

def _scope_tail(op_name: str) -> str:
    parts = op_name.split("/")
    return "/".join(parts[-2:]) if len(parts) > 2 else op_name


def scan_hlo(hlo_text: str, plan: str) -> Dict[str, Any]:
    """Walk post-optimization HLO text; returns the Layer P scan facts:

    - ``f32_scoring_converts``: messages for f32 ``convert`` results
      attributed to ``mercury_scoring`` (the post-fusion precision
      leak).
    - ``scope_layout_ops``: per-scope ``copy``/``transpose`` counts.
    - ``unfused_elementwise``: count of elementwise ops carrying a
      ``mercury_input_fuse`` op_name *outside* any fused computation,
      with up to three named examples.
    """
    f32_converts: List[str] = []
    layout: Dict[str, Dict[str, int]] = {s: {} for s in PERF_SCOPES}
    unfused = 0
    examples: List[str] = []
    in_fusion = False
    for line in hlo_text.splitlines():
        header = _HLO_COMPUTATION_RE.match(line)
        if header:
            comp = header.group(1)
            in_fusion = "fused" in comp
            continue
        m = _HLO_INSTR_RE.match(line)
        if not m:
            continue
        result_type, opcode = m.groups()
        om = _OP_NAME_RE.search(line)
        op_name = om.group(1) if om else ""
        scope = _attribute_scope(op_name)
        if scope is None:
            continue
        if (opcode == "convert" and result_type.startswith("f32")
                and scope == "mercury_scoring"
                and _BF16_OPERAND_RE.search(line)):
            # Only a bf16→f32 upcast is a leak: the scoring region fell
            # back to f32 math. Input-pixel conversions (u8/f32 → f32
            # normalization before the bf16 downcast) are the designed
            # dataflow and land in Layer 3's walk, not here.
            f32_converts.append(
                f"plan {plan}: bf16→f32 upcast inside mercury_scoring "
                f"(result {result_type.split('{')[0]}, "
                f"op {_scope_tail(op_name)}) — the compiled program "
                "fell back to f32 math inside the bf16 scoring region")
        if opcode in ("copy", "transpose"):
            sc = layout[scope]
            sc[opcode] = sc.get(opcode, 0) + 1
        if (scope == "mercury_input_fuse" and not in_fusion
                and opcode in _HLO_ELEMENTWISE):
            unfused += 1
            if len(examples) < 3:
                examples.append(
                    f"plan {plan}: `{opcode}` escaped fusion inside "
                    f"mercury_input_fuse (op {_scope_tail(op_name)})")
    return {
        "f32_scoring_converts": f32_converts,
        "scope_layout_ops": {s: dict(sorted(c.items()))
                             for s, c in layout.items() if c},
        "unfused_elementwise": unfused,
        "unfused_examples": examples,
    }


# --------------------------------------------------------------------------
# measurement
# --------------------------------------------------------------------------

@dataclass
class PerfMeasurement:
    plan: str
    config: Dict[str, Any]
    #: compiled.cost_analysis() anchors
    cost_flops: float = 0.0
    cost_bytes: float = 0.0
    #: jaxpr-walk estimates per scope
    scope_flops: Dict[str, int] = field(default_factory=dict)
    scope_bytes: Dict[str, int] = field(default_factory=dict)
    est_total_flops: int = 0
    unscoped_flops: int = 0
    scoring_flop_frac: float = 0.0
    #: HLO scan facts
    f32_scoring_converts: List[str] = field(default_factory=list)
    scope_layout_ops: Dict[str, Dict[str, int]] = field(
        default_factory=dict)
    unfused_elementwise: int = 0
    unfused_examples: List[str] = field(default_factory=list)

    def config_hash(self) -> str:
        blob = json.dumps(self.config, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:16]

    def scope_intensity(self) -> Dict[str, float]:
        out = {}
        for scope, flops in self.scope_flops.items():
            b = self.scope_bytes.get(scope, 0)
            out[scope] = round(flops / b, 4) if b else 0.0
        return out

    def as_budget(self) -> Dict[str, Any]:
        frac = self.scoring_flop_frac
        ceiling = (round(min(1.0, frac * SCORING_FRAC_HEADROOM + 0.005),
                         4) if frac > 0 else 0.0)
        return {
            "config_hash": self.config_hash(),
            "config": self.config,
            "cost_flops": self.cost_flops,
            "cost_bytes": self.cost_bytes,
            "scope_flops": dict(sorted(self.scope_flops.items())),
            "scope_bytes": dict(sorted(self.scope_bytes.items())),
            "scope_intensity": dict(sorted(
                self.scope_intensity().items())),
            "est_total_flops": self.est_total_flops,
            "unscoped_flops": self.unscoped_flops,
            "scoring_flop_frac": round(frac, 6),
            "scoring_frac_ceiling": ceiling,
            "f32_scoring_converts": len(self.f32_scoring_converts),
            "scope_layout_ops": {s: dict(sorted(c.items()))
                                 for s, c in sorted(
                                     self.scope_layout_ops.items())},
            "unfused_elementwise": self.unfused_elementwise,
        }


def measure_perf_step(step_fn, args: Tuple, plan: str,
                      config: Dict[str, Any]) -> PerfMeasurement:
    """Trace + AOT-compile ``step_fn(*args)`` (no execution) and collect
    the Layer P cost and HLO-scan facts."""
    import jax

    m = PerfMeasurement(plan=plan, config=config)
    closed = jax.make_jaxpr(step_fn)(*args)

    scope_flops = {s: 0.0 for s in PERF_SCOPES}
    scope_bytes = {s: 0.0 for s in PERF_SCOPES}
    total = 0.0
    for eqn, mult in walk_costed_eqns(closed):
        flops = eqn_flops(eqn) * mult
        if not flops:
            continue
        total += flops
        scope = _attribute_scope(_name_stack(eqn))
        if scope is not None:
            scope_flops[scope] += flops
            scope_bytes[scope] += eqn_bytes(eqn) * mult
    m.scope_flops = {s: int(v) for s, v in scope_flops.items()}
    m.scope_bytes = {s: int(v) for s, v in scope_bytes.items()}
    m.est_total_flops = int(total)
    m.unscoped_flops = max(
        0, m.est_total_flops - sum(m.scope_flops.values()))
    if m.est_total_flops:
        m.scoring_flop_frac = (
            m.scope_flops.get("mercury_scoring", 0) / m.est_total_flops)

    lower_fn = step_fn if hasattr(step_fn, "lower") else jax.jit(step_fn)
    compiled = lower_fn.lower(*args).compile()
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if isinstance(cost, dict):
        m.cost_flops = float(cost.get("flops", 0.0) or 0.0)
        m.cost_bytes = float(cost.get("bytes accessed", 0.0) or 0.0)
    scan = scan_hlo(compiled.as_text(), plan)
    m.f32_scoring_converts = scan["f32_scoring_converts"]
    m.scope_layout_ops = scan["scope_layout_ops"]
    m.unfused_elementwise = scan["unfused_elementwise"]
    m.unfused_examples = scan["unfused_examples"]
    return m


def measure_perf_plan(plan: str) -> PerfMeasurement:
    step, args, config = _BUILDERS[plan]()
    return measure_perf_step(step, args, plan, config)


# --------------------------------------------------------------------------
# hard invariants (budgets-file independent)
# --------------------------------------------------------------------------

def check_perf_invariants(m: PerfMeasurement) -> List[str]:
    errors: List[str] = []
    if str(m.config.get("scoring_dtype", "")) == "bfloat16":
        # The compiled-HLO form of Layer 3's dataflow leak walk: after
        # fusion, any f32 convert still attributed to the scoring scope
        # is an upcast XLA actually scheduled.
        errors.extend(m.f32_scoring_converts)
    return errors


# --------------------------------------------------------------------------
# budgets file
# --------------------------------------------------------------------------

def perf_budgets_doc(measurements: Sequence[PerfMeasurement],
                     retrace_measurements: Optional[Sequence[Any]] = None,
                     ) -> Dict[str, Any]:
    return {
        "schema": SCHEMA,
        "provenance": golden.provenance(
            "python -m mercury_tpu.lint --layer perf --regen",
            extra={"flop_tolerance": DEFAULT_TOLERANCE,
                   "scoring_frac_headroom": SCORING_FRAC_HEADROOM}),
        "plans": {m.plan: m.as_budget() for m in measurements},
        "retrace": {r.plan: r.as_budget()
                    for r in (retrace_measurements or ())},
    }


def write_perf_budgets(measurements: Sequence[PerfMeasurement],
                       retrace_measurements: Optional[Sequence[Any]] = None,
                       path: Optional[str] = None) -> str:
    return golden.write_golden(
        path or default_perf_budgets_path(),
        perf_budgets_doc(measurements, retrace_measurements))


def load_perf_budgets(path: Optional[str] = None) -> Dict[str, Any]:
    return golden.load_golden(path or default_perf_budgets_path(),
                              SCHEMA, "--layer perf --regen")


def _diff_ratcheted(what: str, expected: float, got: float,
                    tolerance: float) -> Optional[str]:
    if expected <= 0 and got <= 0:
        return None
    base = max(abs(expected), 1.0)
    if abs(got - expected) / base > tolerance:
        return (f"  {what}: expected {expected:.6g}, got {got:.6g} "
                f"({(got - expected) / base:+.1%}, tolerance "
                f"{tolerance:.0%})")
    return None


def compare_perf_budgets(measurements: Sequence[PerfMeasurement],
                         budgets: Dict[str, Any],
                         ) -> Tuple[List[str], List[str]]:
    """Diff measurements against the committed perf budgets. Version
    skew demotes the ratcheted count/FLOP diffs to warnings (XLA
    scheduling and jax lowering drift across releases); the scoring
    FLOP-fraction ceiling and the bf16 precision-leak invariant are
    NEVER demoted — they are the contract, not a fingerprint."""
    import jax

    errors: List[str] = []
    warnings: List[str] = []
    provenance = budgets.get("provenance", {})
    tolerance = float(provenance.get("flop_tolerance", DEFAULT_TOLERANCE))
    version_match = provenance.get("jax") == jax.__version__
    if not version_match:
        warnings.append(
            f"perf budgets recorded under jax {provenance.get('jax')}, "
            f"running {jax.__version__}: FLOP/layout diffs demoted to "
            "warnings — the scoring-fraction ceiling still binds; "
            "regenerate perf_budgets.json on the pinned version")

    plans = budgets.get("plans", {})
    for m in measurements:
        errors.extend(check_perf_invariants(m))
        budget = plans.get(m.plan)
        if budget is None:
            errors.append(f"plan {m.plan}: no committed perf budget — "
                          "run --layer perf --regen and review the diff")
            continue

        # Hard ceiling: scoring cost as a fraction of the step.
        ceiling = float(budget.get("scoring_frac_ceiling", 0.0))
        if m.scoring_flop_frac > ceiling + 1e-9:
            errors.append(
                f"plan {m.plan}: scoring FLOPs are "
                f"{m.scoring_flop_frac:.1%} of the step, above the "
                f"committed ceiling {ceiling:.1%} — sampler work "
                "regressed the scoring-cost economics (hard ceiling, "
                "never demoted; if intentional, regenerate and review "
                "the new ceiling)")

        soft: List[str] = []
        if budget.get("config_hash") != m.config_hash():
            soft.append(
                f"  config_hash expected {budget.get('config_hash')}, "
                f"got {m.config_hash()} (the audited config changed — "
                "every downstream diff follows from this)")
        for what, expected, got in (
                ("cost_flops", budget.get("cost_flops", 0.0),
                 m.cost_flops),
                ("cost_bytes", budget.get("cost_bytes", 0.0),
                 m.cost_bytes),
                ("est_total_flops", budget.get("est_total_flops", 0),
                 m.est_total_flops)):
            line = _diff_ratcheted(what, float(expected), float(got),
                                   tolerance)
            if line:
                soft.append(line)
        for scope in PERF_SCOPES:
            line = _diff_ratcheted(
                f"scope_flops[{scope}]",
                float(budget.get("scope_flops", {}).get(scope, 0)),
                float(m.scope_flops.get(scope, 0)), tolerance)
            if line:
                soft.append(line)
        unscoped_line = _diff_ratcheted(
            "unscoped_flops", float(budget.get("unscoped_flops", 0)),
            float(m.unscoped_flops), tolerance)
        if unscoped_line and m.unscoped_flops > budget.get(
                "unscoped_flops", 0):
            soft.append(unscoped_line + "  <- unscoped FLOP growth: "
                        "compute outside every mercury scope (the "
                        "cost analogue of an implicit resharding)")
        elif unscoped_line:
            soft.append(unscoped_line)
        for scope in PERF_SCOPES:
            soft.extend(golden.diff_counts(
                f"scope_layout_ops[{scope}]",
                budget.get("scope_layout_ops", {}).get(scope, {}),
                m.scope_layout_ops.get(scope, {})))
        if budget.get("f32_scoring_converts", 0) != len(
                m.f32_scoring_converts):
            soft.append(
                f"  f32_scoring_converts expected "
                f"{budget.get('f32_scoring_converts', 0)}, got "
                f"{len(m.f32_scoring_converts)}")
            soft.extend(f"    {msg}" for msg in m.f32_scoring_converts)
        if m.unfused_elementwise > budget.get("unfused_elementwise", 0):
            soft.append(
                f"  unfused_elementwise expected "
                f"{budget.get('unfused_elementwise', 0)}, got "
                f"{m.unfused_elementwise} — elementwise chains escaped "
                "fusion inside mercury_input_fuse")
            soft.extend(f"    {msg}" for msg in m.unfused_examples)
        if soft:
            header = (f"plan {m.plan}: compiled cost profile deviates "
                      "from committed perf budget:")
            block = [header] + soft + [
                "  (intentional change? regenerate: python -m "
                "mercury_tpu.lint --layer perf --regen)"]
            (errors if version_match else warnings).extend(block)
    return errors, warnings


def run_perf_audit(plans: Sequence[str] = PLAN_NAMES,
                   budgets_path: Optional[str] = None,
                   regen: bool = False,
                   diff_out: Optional[str] = None,
                   retrace_steps: int = 4,
                   ) -> Tuple[List[str], List[str]]:
    """Layer P driver: measure the requested plans' compiled cost
    profiles and either record (``regen=True``, which also re-measures
    the retrace expectations — the runtime half of the golden) or verify
    them against the committed perf budgets. Returns
    ``(errors, warnings)``; empty errors means the layer passed."""
    ensure_cpu_devices()
    measurements = [measure_perf_plan(p) for p in plans]
    if regen:
        from mercury_tpu.lint.tracecheck import measure_plan_retraces

        retraces = [measure_plan_retraces(p, steps=retrace_steps)
                    for p in plans]
        path = write_perf_budgets(measurements, retraces, budgets_path)
        errors: List[str] = []
        for m in measurements:
            errors.extend(check_perf_invariants(m))
        return errors, [f"perf budgets written to {path}"]
    budgets = load_perf_budgets(budgets_path)
    errors, warnings = compare_perf_budgets(measurements, budgets)
    if diff_out and (errors or warnings):
        golden.write_diff_file(diff_out, "graftlint perf diff",
                               errors, warnings)
    return errors, warnings


#: Re-exported for golden.regen_all_goldens, which treats Layer P as one
#: unit (static budgets + retrace expectations share the golden).
def measure_plan_retraces(plan: str, steps: int = 4):
    from mercury_tpu.lint import tracecheck

    return tracecheck.measure_plan_retraces(plan, steps=steps)
