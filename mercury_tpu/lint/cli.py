"""graftlint command line: ``python -m mercury_tpu.lint``.

Exit codes: 0 clean, 1 findings / budget mismatch, 2 internal error.

Layer selection:

- ``--layer ast`` (default): Layer 1 over the given paths (default: the
  ``mercury_tpu`` package). Pure stdlib — never initializes jax.
- ``--layer audit``: Layer 2 — trace the parallelism-plan matrix on CPU
  and verify against the committed ``lint/budgets.json`` (``--regen`` to
  re-record it after an intentional program change).
- ``--layer all``: both.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mercury_tpu.lint",
        description="graftlint: JAX-hazard AST linter (Layer 1) + "
                    "jaxpr/HLO structural auditor (Layer 2)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories for Layer 1 (default: the "
                         "mercury_tpu package)")
    ap.add_argument("--layer", choices=("ast", "audit", "all"),
                    default="ast")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE",
                    help="restrict Layer 1 to these rule IDs/slugs "
                         "(repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the Layer 1 rule catalog and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--plans", default=None,
                    help="comma-separated audit plans "
                         "(default: dp,zero,dp_bf16,sp,pp)")
    ap.add_argument("--budgets", default=None, metavar="PATH",
                    help="budgets.json to verify against / regenerate")
    ap.add_argument("--regen", action="store_true",
                    help="re-measure and WRITE budgets.json instead of "
                         "verifying (review the diff before committing)")
    ap.add_argument("--diff-out", default=None, metavar="PATH",
                    help="write the audit diff to this file on mismatch "
                         "(CI artifact)")
    args = ap.parse_args(argv)

    from mercury_tpu.lint.rules import RULES

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{rule.id} [{rule.slug}] {rule.summary}")
            print(f"    fix: {rule.hint}")
        return 0

    rc = 0
    if args.layer in ("ast", "all"):
        from mercury_tpu.lint.engine import format_findings, lint_paths

        paths = args.paths or [_package_root()]
        findings = lint_paths(paths, select=args.select)
        if args.as_json:
            print(json.dumps([f.__dict__ for f in findings], indent=2))
        else:
            print(format_findings(findings))
        if findings:
            rc = 1

    if args.layer in ("audit", "all"):
        from mercury_tpu.lint import audit

        plans = (tuple(p.strip() for p in args.plans.split(","))
                 if args.plans else audit.PLAN_NAMES)
        unknown = [p for p in plans if p not in audit.PLAN_NAMES]
        if unknown:
            print(f"unknown audit plan(s): {', '.join(unknown)} "
                  f"(known: {', '.join(audit.PLAN_NAMES)})",
                  file=sys.stderr)
            return 2
        try:
            errors, warnings = audit.run_audit(
                plans=plans, budgets_path=args.budgets,
                regen=args.regen, diff_out=args.diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint audit: budgets file missing ({exc}) — "
                  "run with --regen first", file=sys.stderr)
            return 2
        for line in warnings:
            print(f"warning: {line}")
        for line in errors:
            print(line)
        if errors:
            rc = 1
        else:
            print(f"graftlint audit: {len(plans)} plan(s) verified "
                  f"({', '.join(plans)})")

    return rc


if __name__ == "__main__":
    sys.exit(main())
