"""graftlint command line: ``python -m mercury_tpu.lint``.

Exit codes: 0 clean, 1 findings / budget mismatch, 2 internal error.

Layer selection:

- ``--layer ast`` (default): Layer 1 over the given paths (default: the
  ``mercury_tpu`` package). Pure stdlib — never initializes jax.
- ``--layer metrics``: Layer M — every ``category/name`` metric-key
  literal in the package must exist in ``obs/registry.py::METRIC_KEYS``
  and in the ``docs/API.md`` glossary. Pure stdlib, like Layer 1.
- ``--layer audit``: Layer 2 — trace the parallelism-plan matrix on CPU
  and verify against the committed ``lint/budgets.json`` (``--regen`` to
  re-record it after an intentional program change).
- ``--layer sharding``: Layer 3 — AOT-lower + compile each plan on the
  CPU mesh and verify the sharding/memory invariants against the
  committed ``lint/shard_budgets.json`` (``--regen`` parity).
- ``--layer concurrency``: Layer C — static host-concurrency audit
  (GL120–GL125) over the hot thread modules plus thread-manifest parity
  against the committed ``lint/thread_manifest.json`` (``--regen`` to
  re-record after an intentional fleet change). Pure stdlib.
- ``--layer perf``: Layer P — AOT cost/roofline budgets per named scope
  plus the fusion/precision HLO scan, verified against the committed
  ``lint/perf_budgets.json`` (``--regen`` parity; the regen also
  re-measures the retrace expectations that the runtime guard,
  ``python -m mercury_tpu.lint.tracecheck``, asserts).
- ``--layer control``: Layer S — extract the supervisor's control-plane
  state machine, model-check the GLS01–GLS06 invariants, and verify
  against the committed ``lint/control_plane.json`` (``--regen``
  parity; the journal-conformance replay half is
  ``python -m mercury_tpu.lint.control RUN_DIR``). Pure stdlib.
- ``--layer state``: Layer E — extract the MercuryState schema (fields,
  shape-roles, elastic policies, checkpoint lineage + upgrade shims,
  carry sites), gate the GLE01–GLE06 invariants, and verify against the
  committed ``lint/state_schema.json`` (``--regen`` parity; the
  differential reshard-conformance half is
  ``python -m mercury_tpu.lint.state --differential``). Pure stdlib.
- ``--layer all``: all of the above. With ``--diff-out PATH`` the audit
  diff goes to ``PATH``, the sharding diff to ``PATH.sharding``, the
  thread-manifest diff to ``PATH.threads``, the perf diff to
  ``PATH.perf``, the control-plane diff to ``PATH.control``, and the
  state-schema diff to ``PATH.state``.

``--regen`` with the default ``--layer ast`` (or ``--layer all``) is the
one-stop regen: it re-measures EVERY budget layer and rewrites all six
goldens atomically — either every file updates or none does (a plan that
fails mid-measure cannot leave a half-regenerated set).

``--json`` emits one document for every layer that ran::

    {"schema": "graftlint_findings_v2",
     "findings": [{"layer": "ast", "rule_id": ..., ...},
                  {"layer": "sharding", "severity": "error",
                   "message": ...}, ...]}
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

#: Version tag for the ``--json`` document; bump when the finding shape
#: changes (v2 added the envelope + per-finding ``layer``).
JSON_SCHEMA = "graftlint_findings_v2"


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mercury_tpu.lint",
        description="graftlint: JAX-hazard AST linter (Layer 1) + "
                    "jaxpr/HLO structural auditor (Layer 2) + "
                    "sharding & memory auditor (Layer 3) + "
                    "host-concurrency auditor (Layer C) + "
                    "cost/roofline & retrace auditor (Layer P) + "
                    "control-plane model checker (Layer S) + "
                    "state-schema conformance checker (Layer E)",
    )
    ap.add_argument("paths", nargs="*",
                    help="files/directories for Layer 1 (default: the "
                         "mercury_tpu package)")
    ap.add_argument("--layer",
                    choices=("ast", "metrics", "audit", "sharding",
                             "concurrency", "perf", "control", "state",
                             "all"),
                    default="ast")
    ap.add_argument("--select", action="append", default=None,
                    metavar="RULE",
                    help="restrict Layer 1 to these rule IDs/slugs "
                         "(repeatable)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the Layer 1 rule catalog and exit")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings (one document for "
                         "all layers run)")
    ap.add_argument("--plans", default=None,
                    help="comma-separated audit/sharding plans "
                         "(default: dp,zero,dp_bf16,sp,pp)")
    ap.add_argument("--budgets", default=None, metavar="PATH",
                    help="Layer 2 budgets.json to verify against / "
                         "regenerate")
    ap.add_argument("--shard-budgets", default=None, metavar="PATH",
                    help="Layer 3 shard_budgets.json to verify against "
                         "/ regenerate")
    ap.add_argument("--thread-manifest", default=None, metavar="PATH",
                    help="Layer C thread_manifest.json to verify "
                         "against / regenerate")
    ap.add_argument("--perf-budgets", default=None, metavar="PATH",
                    help="Layer P perf_budgets.json to verify against "
                         "/ regenerate")
    ap.add_argument("--control-plane", default=None, metavar="PATH",
                    help="Layer S control_plane.json to verify against "
                         "/ regenerate")
    ap.add_argument("--state-schema", default=None, metavar="PATH",
                    help="Layer E state_schema.json to verify against "
                         "/ regenerate")
    ap.add_argument("--regen", action="store_true",
                    help="re-measure and WRITE the budget file(s) instead "
                         "of verifying (review the diff before committing)")
    ap.add_argument("--diff-out", default=None, metavar="PATH",
                    help="write the budget diff to this file on mismatch "
                         "(CI artifact; with --layer all the sharding "
                         "diff goes to PATH.sharding)")
    args = ap.parse_args(argv)

    from mercury_tpu.lint.rules import RULES

    if args.list_rules:
        for rule in sorted(RULES.values(), key=lambda r: r.id):
            print(f"{rule.id} [{rule.slug}] {rule.summary}")
            print(f"    fix: {rule.hint}")
        return 0

    if args.regen and args.layer in ("ast", "all"):
        # One-stop atomic regen: re-measure every budget layer, then
        # commit all six goldens in a single all-or-nothing batch
        # (lint/golden.py::regen_all_goldens). Any measurement or
        # invariant failure aborts before a single committed file moves.
        from mercury_tpu.lint import golden
        from mercury_tpu.lint import audit as _audit

        plans = None
        if args.plans:
            plans = tuple(p.strip() for p in args.plans.split(","))
            unknown = [p for p in plans if p not in _audit.PLAN_NAMES]
            if unknown:
                print(f"unknown plan(s): {', '.join(unknown)} "
                      f"(known: {', '.join(_audit.PLAN_NAMES)})",
                      file=sys.stderr)
                return 2
        try:
            errors, warnings = golden.regen_all_goldens(
                plans=plans,
                budgets_path=args.budgets,
                shard_budgets_path=args.shard_budgets,
                manifest_path=args.thread_manifest,
                perf_budgets_path=args.perf_budgets,
                control_path=args.control_plane,
                state_schema_path=args.state_schema)
        except Exception as exc:  # nothing was committed — say so
            print(f"graftlint regen: aborted with no golden rewritten "
                  f"({type(exc).__name__}: {exc})", file=sys.stderr)
            return 2
        for line in warnings:
            print(f"warning: {line}")
        for line in errors:
            print(line)
        return 1 if errors else 0

    rc = 0
    json_findings: List[dict] = []

    def collect(layer: str, errors: List[str], warnings: List[str]) -> None:
        for line in warnings:
            json_findings.append(
                {"layer": layer, "severity": "warning", "message": line})
        for line in errors:
            json_findings.append(
                {"layer": layer, "severity": "error", "message": line})

    if args.layer in ("ast", "all"):
        from mercury_tpu.lint.engine import format_findings, lint_paths

        paths = args.paths or [_package_root()]
        findings = lint_paths(paths, select=args.select)
        if args.as_json:
            json_findings.extend(
                {"layer": "ast", "severity": "error", **f.__dict__}
                for f in findings)
        else:
            print(format_findings(findings))
        if findings:
            rc = 1

    if args.layer in ("metrics", "all"):
        from mercury_tpu.lint.metrics import run_metrics_check

        try:
            errors, warnings = run_metrics_check(paths=args.paths or None)
        except (OSError, ValueError) as exc:
            print(f"graftlint metrics: {exc}", file=sys.stderr)
            return 2
        if args.as_json:
            collect("metrics", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print("graftlint metrics: emitted keys == registry == "
                      "docs glossary")
        if errors:
            rc = 1

    if args.layer in ("control", "all"):
        from mercury_tpu.lint import control

        diff_out = args.diff_out
        if diff_out and args.layer == "all":
            diff_out = diff_out + ".control"
        try:
            errors, warnings = control.run_control_check(
                control_path=args.control_plane,
                regen=args.regen, diff_out=diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint control: control plane missing ({exc}) — "
                  f"run with --layer control --regen first",
                  file=sys.stderr)
            return 2
        except (OSError, ValueError) as exc:
            print(f"graftlint control: {exc}", file=sys.stderr)
            return 2
        if args.as_json:
            collect("control", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print("graftlint control: machine verified against "
                      "lint/control_plane.json; invariants "
                      "GLS01-GLS06 hold")
        if errors:
            rc = 1

    if args.layer in ("state", "all"):
        from mercury_tpu.lint import state as state_lint

        diff_out = args.diff_out
        if diff_out and args.layer == "all":
            diff_out = diff_out + ".state"
        try:
            errors, warnings = state_lint.run_state_check(
                state_schema_path=args.state_schema,
                regen=args.regen, diff_out=diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint state: state schema missing ({exc}) — "
                  f"run with --layer state --regen first",
                  file=sys.stderr)
            return 2
        except (OSError, ValueError) as exc:
            print(f"graftlint state: {exc}", file=sys.stderr)
            return 2
        if args.as_json:
            collect("state", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print("graftlint state: schema verified against "
                      "lint/state_schema.json; invariants "
                      "GLE01-GLE06 hold")
        if errors:
            rc = 1

    if args.layer in ("concurrency", "all"):
        from mercury_tpu.lint import concurrency

        diff_out = args.diff_out
        if diff_out and args.layer == "all":
            diff_out = diff_out + ".threads"
        try:
            errors, warnings = concurrency.run_concurrency_check(
                paths=args.paths or None,
                manifest_path=args.thread_manifest,
                regen=args.regen, diff_out=diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint concurrency: thread manifest missing "
                  f"({exc}) — run with --layer concurrency --regen "
                  "first", file=sys.stderr)
            return 2
        except (OSError, ValueError) as exc:
            print(f"graftlint concurrency: {exc}", file=sys.stderr)
            return 2
        if args.as_json:
            collect("concurrency", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print("graftlint concurrency: thread fleet verified "
                      "against lint/thread_manifest.json")
        if errors:
            rc = 1

    def _resolve_plans(known, what):
        plans = (tuple(p.strip() for p in args.plans.split(","))
                 if args.plans else known)
        unknown = [p for p in plans if p not in known]
        if unknown:
            print(f"unknown {what} plan(s): {', '.join(unknown)} "
                  f"(known: {', '.join(known)})", file=sys.stderr)
            return None
        return plans

    if args.layer in ("audit", "all"):
        from mercury_tpu.lint import audit

        plans = _resolve_plans(audit.PLAN_NAMES, "audit")
        if plans is None:
            return 2
        try:
            errors, warnings = audit.run_audit(
                plans=plans, budgets_path=args.budgets,
                regen=args.regen, diff_out=args.diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint audit: budgets file missing ({exc}) — "
                  "run with --regen first", file=sys.stderr)
            return 2
        if args.as_json:
            collect("audit", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print(f"graftlint audit: {len(plans)} plan(s) verified "
                      f"({', '.join(plans)})")
        if errors:
            rc = 1

    if args.layer in ("sharding", "all"):
        from mercury_tpu.lint import sharding

        plans = _resolve_plans(sharding.PLAN_NAMES, "sharding")
        if plans is None:
            return 2
        diff_out = args.diff_out
        if diff_out and args.layer == "all":
            diff_out = diff_out + ".sharding"
        try:
            errors, warnings = sharding.run_sharding_audit(
                plans=plans, budgets_path=args.shard_budgets,
                regen=args.regen, diff_out=diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint sharding: budgets file missing ({exc}) — "
                  "run with --layer sharding --regen first",
                  file=sys.stderr)
            return 2
        if args.as_json:
            collect("sharding", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print(f"graftlint sharding: {len(plans)} plan(s) "
                      f"verified ({', '.join(plans)})")
        if errors:
            rc = 1

    if args.layer in ("perf", "all"):
        from mercury_tpu.lint import perf

        plans = _resolve_plans(perf.PLAN_NAMES, "perf")
        if plans is None:
            return 2
        diff_out = args.diff_out
        if diff_out and args.layer == "all":
            diff_out = diff_out + ".perf"
        try:
            errors, warnings = perf.run_perf_audit(
                plans=plans, budgets_path=args.perf_budgets,
                regen=args.regen, diff_out=diff_out)
        except FileNotFoundError as exc:
            print(f"graftlint perf: budgets file missing ({exc}) — "
                  "run with --layer perf --regen first",
                  file=sys.stderr)
            return 2
        if args.as_json:
            collect("perf", errors, warnings)
        else:
            for line in warnings:
                print(f"warning: {line}")
            for line in errors:
                print(line)
            if not errors:
                print(f"graftlint perf: {len(plans)} plan(s) verified "
                      f"({', '.join(plans)})")
        if errors:
            rc = 1

    if args.as_json:
        print(json.dumps(
            {"schema": JSON_SCHEMA, "findings": json_findings}, indent=2))

    return rc


if __name__ == "__main__":
    sys.exit(main())
