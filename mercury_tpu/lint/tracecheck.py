"""graftlint Layer P runtime half: the retrace guard.

The static half (:mod:`mercury_tpu.lint.perf`) pins what the compiled
program costs; this module pins *how often it compiles*. A weak-type
flip (python float one step, ``np.float32`` the next), a shape-dependent
host branch, or an unhashable static argument silently turns one
executable into a compile-per-step treadmill — the profile looks fine,
the wall clock doesn't.

The harness builds each plan from the shared Layer 2 builder matrix,
then *executes* the step ``steps`` times on the CPU mesh while counting
jax trace/compile events:

- On jax builds with ``jax.monitoring``, one process-wide listener
  (installed via :func:`mercury_tpu.compat.register_compile_listener`)
  counts ``jaxpr_trace_duration`` / ``backend_compile_duration`` events
  and fans them out to the active :class:`CompileMonitor`\\ s.
- On legacy jax without it, the monitor falls back to polling the step
  function's jit cache (:func:`mercury_tpu.compat.jit_cache_size`):
  cache growth across steady-state calls IS a retrace, whoever caused
  it.

The first :data:`WARMUP_CALLS` calls are the *warmup*: call 1 traces
and compiles, and call 2 legitimately compiles once more on every plan
— the trainer places its initial state as uncommitted
``SingleDeviceSharding`` arrays, the step's output state comes back as
committed ``NamedSharding``, so the second call is the first one with
the steady-state placement. Calls 3..N are *steady state*, where the
committed expectation is zero. Every call also records the argument
signature — ``(shape, dtype, weak_type, sharding)`` per leaf — so when
steady state does compile, the finding names exactly which argument
leaf churned (or states that the signatures were identical, pointing
the finger at closure/global state).

Expectations live in the ``retrace`` section of the Layer P golden
(``lint/perf_budgets.json``): ``steady_compiles``/``steady_traces`` are
hard invariants (never demoted), ``warmup_*`` counts are warn-only
documentation of the recorded run. Run standalone as::

    python -m mercury_tpu.lint.tracecheck --plans dp,hs,async

The trainer exposes the same machinery for live runs:
``Trainer.arm_retrace_guard()`` attaches a monitor whose counters are
emitted as the ``lint/retrace_events`` / ``lint/compile_count`` metric
keys at every log step.
"""

from __future__ import annotations

import argparse
import sys
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mercury_tpu import compat
from mercury_tpu.lint.audit import PLAN_NAMES, _BUILDERS, ensure_cpu_devices

_TRACE_SUFFIX = "jaxpr_trace_duration"
_COMPILE_SUFFIX = "backend_compile_duration"

#: Calls whose trace/compile events count as warmup, not steady state:
#: call 1 primes, call 2 settles the state placement (see module doc).
WARMUP_CALLS = 2

_lock = threading.Lock()
_active: List["CompileMonitor"] = []
_listener_state: Optional[bool] = None  # None = not yet installed


def _dispatch(event: str) -> None:
    if event.endswith(_TRACE_SUFFIX):
        kind = "trace"
    elif event.endswith(_COMPILE_SUFFIX):
        kind = "compile"
    else:
        return
    with _lock:
        monitors = list(_active)
    for m in monitors:
        m._record(kind)


def _ensure_listener() -> bool:
    """Install the process-wide listener once; True when event counting
    is available on this jax build."""
    global _listener_state
    if _listener_state is None:
        _listener_state = compat.register_compile_listener(_dispatch)
    return _listener_state


class CompileMonitor:
    """Counts jax trace/compile events between ``start()`` and
    ``stop()``. Usable as a context manager; thread-safe (scorer-fleet
    threads compile too, and their events belong in the count)."""

    def __init__(self) -> None:
        self.traces = 0
        self.compiles = 0
        self.supported = _ensure_listener()

    def _record(self, kind: str) -> None:
        with _lock:
            if kind == "trace":
                self.traces += 1
            else:
                self.compiles += 1

    def start(self) -> "CompileMonitor":
        with _lock:
            if self not in _active:
                _active.append(self)
        return self

    def stop(self) -> None:
        with _lock:
            if self in _active:
                _active.remove(self)

    def snapshot(self) -> Tuple[int, int]:
        with _lock:
            return self.traces, self.compiles

    def __enter__(self) -> "CompileMonitor":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


# --------------------------------------------------------------------------
# argument signatures
# --------------------------------------------------------------------------

def _shard_desc(x) -> str:
    s = getattr(x, "sharding", None)
    if s is None:
        return ""
    spec = getattr(s, "spec", None)
    desc = type(s).__name__
    return f"{desc}({spec})" if spec is not None else desc


def _leaf_sig(x) -> Tuple[Tuple[int, ...], str, bool, str]:
    aval = getattr(x, "aval", None)
    if aval is not None and hasattr(aval, "shape"):
        return (tuple(aval.shape), str(aval.dtype),
                bool(getattr(aval, "weak_type", False)), _shard_desc(x))
    if isinstance(x, (bool, int, float, complex)):
        # python scalars enter traced code weakly typed — the classic
        # churn partner to a strongly-typed np scalar on the next call
        return ((), type(x).__name__, True, "")
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return tuple(shape), str(dtype), False, _shard_desc(x)
    return ((), type(x).__name__, False, "")


def signature_of(args) -> Dict[str, Tuple]:
    """``{leaf_path: (shape, dtype, weak_type, sharding)}`` over an
    argument pytree — the identity jax's jit cache keys on."""
    from jax.tree_util import keystr, tree_flatten_with_path

    leaves, _ = tree_flatten_with_path(args)
    return {keystr(path): _leaf_sig(leaf) for path, leaf in leaves}


def describe_churn(prev: Dict[str, Tuple], cur: Dict[str, Tuple],
                   max_lines: int = 6) -> List[str]:
    """Human-readable diff between two call signatures; empty when they
    are identical (churn came from closures/globals, not arguments)."""
    lines = []
    changed = 0
    for path in sorted(set(prev) | set(cur)):
        p, c = prev.get(path), cur.get(path)
        if p == c:
            continue
        changed += 1
        if len(lines) >= max_lines:
            continue

        def fmt(sig):
            if sig is None:
                return "<absent>"
            shape, dtype, weak, shard = sig
            out = f"{dtype}{list(shape)}"
            if weak:
                out += " weak"
            if shard:
                out += f" @{shard}"
            return out

        lines.append(f"arg{path}: {fmt(p)} -> {fmt(c)}")
    if changed > len(lines):
        lines.append(f"... and {changed - len(lines)} more churned "
                     "argument leaves")
    return lines


# --------------------------------------------------------------------------
# per-plan harness
# --------------------------------------------------------------------------

def _materialize(args: Tuple) -> Tuple:
    """Replace ShapeDtypeStruct templates (the host_stream pixel slab)
    with concrete host zeros so the step can execute. np arrays on
    purpose: device transfer of a host buffer never fires a compile
    event, so the prime count stays deterministic."""
    import numpy as np

    out = []
    for a in args:
        if type(a).__name__ == "ShapeDtypeStruct":
            out.append(np.zeros(a.shape, a.dtype))
        else:
            out.append(a)
    return tuple(out)


def _fresh_donated(args: Tuple, config: Dict[str, Any], state) -> Tuple:
    """Next call's arguments: thread the new state through slot 0 and
    re-materialize the donated streamed slab (host_stream donates arg 1
    alongside the state, so the consumed buffer cannot be reused)."""
    import numpy as np

    out = list(args)
    out[0] = state
    if config.get("data_placement") == "host_stream":
        slab = out[1]
        out[1] = np.zeros(slab.shape, slab.dtype)
    return tuple(out)


@dataclass
class RetraceMeasurement:
    plan: str
    steps: int = 0
    warmup_traces: int = 0
    warmup_compiles: int = 0
    steady_traces: int = 0
    steady_compiles: int = 0
    #: which call compiled in steady state, and what churned
    churn: List[str] = field(default_factory=list)
    #: monitor backend: "events" (jax.monitoring) or "jit-cache"
    backend: str = "events"

    def as_budget(self) -> Dict[str, Any]:
        return {
            "steps": self.steps,
            "warmup_calls": WARMUP_CALLS,
            "warmup_traces": self.warmup_traces,
            "warmup_compiles": self.warmup_compiles,
            "steady_traces": self.steady_traces,
            "steady_compiles": self.steady_compiles,
            "backend": self.backend,
        }


def measure_step_retraces(step_fn, args: Tuple, plan: str,
                          config: Dict[str, Any],
                          steps: int = 4) -> RetraceMeasurement:
    """Execute ``step_fn`` ``steps`` times, counting trace/compile
    events per call. The first :data:`WARMUP_CALLS` calls may compile;
    the rest must not."""
    m = RetraceMeasurement(plan=plan, steps=steps)
    args = _materialize(args)
    monitor = CompileMonitor()
    use_cache_poll = not monitor.supported
    if use_cache_poll:
        m.backend = "jit-cache"

    prev_sig = None
    with monitor:
        for call in range(steps):
            before = monitor.snapshot()
            cache_before = (compat.jit_cache_size(step_fn)
                            if use_cache_poll else -1)
            sig = signature_of(args)
            out = step_fn(*args)
            after = monitor.snapshot()
            traces = after[0] - before[0]
            compiles = after[1] - before[1]
            if use_cache_poll:
                cache_after = compat.jit_cache_size(step_fn)
                if cache_before >= 0 and cache_after > cache_before:
                    compiles += cache_after - cache_before
            if call < WARMUP_CALLS:
                m.warmup_traces += traces
                m.warmup_compiles += compiles
            else:
                m.steady_traces += traces
                m.steady_compiles += compiles
                if compiles or traces:
                    diff = describe_churn(prev_sig or {}, sig)
                    if diff:
                        m.churn.extend(
                            f"plan {plan} call {call + 1}: {line}"
                            for line in diff)
                    else:
                        m.churn.append(
                            f"plan {plan} call {call + 1}: argument "
                            "signatures identical to the previous call "
                            "— the retrace came from closure/global "
                            "state, not an argument")
            prev_sig = sig
            state = out[0] if isinstance(out, tuple) else out
            args = _fresh_donated(args, config, state)
    return m


def measure_plan_retraces(plan: str, steps: int = 4) -> RetraceMeasurement:
    step, args, config = _BUILDERS[plan]()
    try:
        return measure_step_retraces(step, args, plan, config,
                                     steps=steps)
    finally:
        closer = getattr(step, "close", None)
        if callable(closer):
            closer()


# --------------------------------------------------------------------------
# comparison against the committed expectations
# --------------------------------------------------------------------------

def compare_retraces(measurements: Sequence[RetraceMeasurement],
                     budgets: Dict[str, Any],
                     ) -> Tuple[List[str], List[str]]:
    """Diff measured retrace counts against the golden's ``retrace``
    section. Steady-state compile/trace counts are hard (a retrace
    treadmill is broken on any jax version); warmup counts are
    warn-only — they depend on which process-wide jnp/jit helper caches
    were already warm when the plan ran, so they document the recorded
    run rather than pin an invariant."""
    errors: List[str] = []
    warnings: List[str] = []
    expectations = budgets.get("retrace", {})
    for m in measurements:
        expected = expectations.get(m.plan)
        if expected is None:
            errors.append(
                f"plan {m.plan}: no committed retrace expectation — "
                "run --layer perf --regen and review the diff")
            continue
        want_sc = int(expected.get("steady_compiles", 0))
        want_st = int(expected.get("steady_traces", 0))
        if m.steady_compiles != want_sc or m.steady_traces != want_st:
            errors.append(
                f"plan {m.plan}: steady state re-entered the compiler "
                f"({m.steady_traces} trace(s), {m.steady_compiles} "
                f"compile(s) over calls {WARMUP_CALLS + 1}..{m.steps}; "
                f"expected {want_st}/{want_sc}) — one executable became "
                "a compile-per-step treadmill")
            errors.extend(f"  {line}" for line in m.churn)
        for key, got in (("warmup_traces", m.warmup_traces),
                         ("warmup_compiles", m.warmup_compiles)):
            want = int(expected.get(key, 0))
            if got != want:
                warnings.append(
                    f"plan {m.plan}: {key} recorded {want}, got {got} "
                    "(informational — warmup counts vary with which "
                    "process-wide helper caches were already warm)")
    return errors, warnings


def run_retrace_guard(plans: Sequence[str] = ("dp",),
                      budgets_path: Optional[str] = None,
                      steps: int = 4,
                      ) -> Tuple[List[str], List[str]]:
    """Drive each plan ``steps`` steps and verify the committed retrace
    expectations. Raises FileNotFoundError when the Layer P golden is
    missing (run ``--layer perf --regen`` first)."""
    from mercury_tpu.lint.perf import load_perf_budgets

    ensure_cpu_devices()
    budgets = load_perf_budgets(budgets_path)
    measurements = [measure_plan_retraces(p, steps=steps) for p in plans]
    return compare_retraces(measurements, budgets)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mercury_tpu.lint.tracecheck",
        description="graftlint Layer P retrace guard: execute each plan "
                    "N steps and assert steady-state compile count "
                    "matches lint/perf_budgets.json")
    ap.add_argument("--plans", default="dp",
                    help="comma-separated plans (default: dp; known: "
                         + ",".join(PLAN_NAMES))
    ap.add_argument("--steps", type=int, default=4,
                    help="calls per plan; the first 2 warm up (prime + "
                         "placement settle), the rest must not compile "
                         "(default: 4)")
    ap.add_argument("--budgets", default=None, metavar="PATH",
                    help="perf_budgets.json to verify against")
    args = ap.parse_args(argv)

    plans = tuple(p.strip() for p in args.plans.split(",") if p.strip())
    unknown = [p for p in plans if p not in PLAN_NAMES]
    if unknown:
        print(f"unknown plan(s): {', '.join(unknown)} "
              f"(known: {', '.join(PLAN_NAMES)})", file=sys.stderr)
        return 2
    try:
        errors, warnings = run_retrace_guard(
            plans, budgets_path=args.budgets, steps=args.steps)
    except FileNotFoundError as exc:
        print(f"graftlint tracecheck: perf budgets missing ({exc}) — "
              "run python -m mercury_tpu.lint --layer perf --regen "
              "first", file=sys.stderr)
        return 2
    for line in warnings:
        print(f"warning: {line}")
    for line in errors:
        print(line)
    if not errors:
        print(f"graftlint tracecheck: {len(plans)} plan(s) steady-state "
              f"clean ({', '.join(plans)})")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
