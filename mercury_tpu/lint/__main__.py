"""``python -m mercury_tpu.lint`` entry point."""

import sys

from mercury_tpu.lint.cli import main

sys.exit(main())
