"""graftlint: static analysis for the JAX hazards this codebase lives with.

Six layers, one entry point (``python -m mercury_tpu.lint``):

- **Layer 1** (:mod:`mercury_tpu.lint.rules`, :mod:`mercury_tpu.lint.engine`)
  is an AST rule engine over the package's own source with JAX-specific
  rules: PRNG-key reuse, host syncs inside traced functions, Python
  branches on tracer values, mutable default args, unordered iteration
  feeding pytree/array construction, use-after-donation, trace-time
  closure over mutable module globals, eager log formatting. Findings are
  suppressible inline with ``# graftlint: disable=RULE -- reason`` (the
  reason is mandatory — an unexplained suppression is itself a finding).
  Layer 1 is pure stdlib: it never imports jax, so it runs anywhere in
  milliseconds.

- **Layer 2** (:mod:`mercury_tpu.lint.audit`) traces the fused train step
  (and its ZeRO / bf16-scoring / sequence-parallel / pipeline-parallel
  variants) on CPU and checks *structural invariants of the traced
  program* as data: per-plan collective count/kind budgets, zero host
  callbacks, donation aliasing where configured, no f32 matmuls inside
  ``scoring_dtype=bf16`` regions, and a byte-identical jaxpr digest for
  ``telemetry=False`` against the committed seed digest. Budgets live in
  the committed ``lint/budgets.json`` golden file (regenerate with
  ``--regen``), so program drift is a reviewed diff, not a surprise.

- **Layer 3** (:mod:`mercury_tpu.lint.sharding`,
  :mod:`mercury_tpu.lint.memory`) AOT-lowers AND COMPILES every plan on
  the CPU mesh and audits the post-SPMD program: compiled collective
  counts attributed to the ``mercury_scoring`` / ``mercury_grad_sync``
  named scopes via HLO ``op_name`` metadata (no implicit resharding
  outside them), ``with_sharding_constraint`` coverage for every >1 MiB
  intermediate produced in ``parallel/{fsdp,tensor,sequence,pipeline}``
  GSPMD-auto regions, a monotone per-plan peak-buffer ratchet from
  ``compiled.memory_analysis()`` (±25% CPU-estimate tolerance), and a
  dataflow f32→bf16-scoring leak check (operand-origin walk, not just
  dot ops as in Layer 2). Goldens live in ``lint/shard_budgets.json``
  (``--layer sharding --regen``). New AST rules GL110–GL113 ride along
  in Layer 1 (unconstrained pjit output, bare ``device_put`` in hot
  modules, manual ``all_gather`` in auto regions, mesh-axis literals
  off the ``parallel/mesh.py`` registry).

- **Layer C** (:mod:`mercury_tpu.lint.concurrency`,
  :mod:`mercury_tpu.lint.racecheck`) audits the *host thread fleet* the
  traced program rides on: an AST pass over the hot threaded modules
  builds per-class thread-entry-point maps and infers each attribute's
  lock discipline, flagging GL120–GL125 (unguarded cross-thread state,
  queue put/get discipline, unjoined non-daemon threads, lock-order
  deadlocks, blocking calls under a lock, and threads/pools/queues not
  declared in the committed ``lint/thread_manifest.json`` —
  ``--layer concurrency --regen`` parity). The runtime side is a
  stdlib "TSan-lite": instrumented Lock/Queue wrappers plus a
  monkeypatching :class:`~mercury_tpu.lint.racecheck.RaceMonitor` that
  records cross-thread unsynchronized attribute access during stress
  tests, and a :class:`~mercury_tpu.lint.racecheck.ThreadLeakGuard`
  behind the conftest-wide thread-leak fixture. Pure stdlib, like
  Layer 1.

- **Layer P** (:mod:`mercury_tpu.lint.perf`,
  :mod:`mercury_tpu.lint.tracecheck`) treats the *cost* of the compiled
  program as a checked artifact: AOT ``cost_analysis()`` FLOPs/bytes
  attributed to the named scopes (``mercury_scoring``,
  ``mercury_grad_sync``, ``mercury_augmentation``, ``mercury_optimizer``,
  ``mercury_input_fuse``) with a per-plan ratchet in the committed
  ``lint/perf_budgets.json`` golden, a hard scoring-FLOPs-fraction
  ceiling, and an HLO fusion/precision scan (bf16→f32 upcasts inside the
  bf16 scoring region, copy/transpose churn in hot scopes, unfused
  elementwise chains in ``mercury_input_fuse``). The runtime side is a
  retrace guard: :class:`~mercury_tpu.lint.tracecheck.CompileMonitor`
  counts jaxpr traces and backend compiles via ``jax.monitoring``,
  drives each plan's step for N calls, and asserts the steady state
  compiles nothing (``python -m mercury_tpu.lint.tracecheck``). New AST
  rules GL130–GL133 ride along in Layer 1 (churned closure captures,
  shape-dependent branches, NumPy constants built per-trace, unhashable
  static args). ``--layer perf --regen`` rewrites the golden; a bare
  ``--regen`` regenerates all five goldens atomically via
  :mod:`mercury_tpu.lint.golden`.

- **Layer S** (:mod:`mercury_tpu.lint.control`,
  :mod:`mercury_tpu.lint.modelcheck`) treats the *control plane* — the
  supervisor's degradation ladder, restart budgets, SLO latches and
  recovery probe — as a checked artifact: an AST extractor over
  ``runtime/supervisor.py`` (plus the scorer-service, anomaly and
  fault modules) derives the reachable transition system, commits it
  to ``lint/control_plane.json`` (``--layer control --regen`` parity),
  and an exhaustive BFS model checker proves the GLS01–GLS06
  invariants on every regen and verify: uniform is reachable only
  stepwise, every degraded state can recover, no breach/recover
  oscillation without an SLO release, restart budgets are monotone
  within an episode, every modeled transition journals a registered
  event kind with a rooted parent contract, and levels move ±1 only.
  The runtime side is a journal-conformance replay
  (``python -m mercury_tpu.lint.control RUN_DIR``): it re-drives each
  host's ``events.h*.jsonl`` against the committed machine and flags
  transitions the model disallows — plus, with ``--coverage``, allowed
  transitions no chaos run has ever exercised. Pure stdlib, like
  Layers 1 and C.

See ``docs/LINT.md`` for the rule catalog and ``docs/DESIGN.md`` for the
audit invariants.
"""

from mercury_tpu.lint.engine import (
    Finding,
    format_findings,
    lint_paths,
    lint_source,
)
from mercury_tpu.lint.rules import RULES, Rule

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "format_findings",
    "lint_paths",
    "lint_source",
]
