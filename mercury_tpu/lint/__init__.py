"""graftlint: static analysis for the JAX hazards this codebase lives with.

Two layers, one entry point (``python -m mercury_tpu.lint``):

- **Layer 1** (:mod:`mercury_tpu.lint.rules`, :mod:`mercury_tpu.lint.engine`)
  is an AST rule engine over the package's own source with JAX-specific
  rules: PRNG-key reuse, host syncs inside traced functions, Python
  branches on tracer values, mutable default args, unordered iteration
  feeding pytree/array construction, use-after-donation, trace-time
  closure over mutable module globals, eager log formatting. Findings are
  suppressible inline with ``# graftlint: disable=RULE -- reason`` (the
  reason is mandatory — an unexplained suppression is itself a finding).
  Layer 1 is pure stdlib: it never imports jax, so it runs anywhere in
  milliseconds.

- **Layer 2** (:mod:`mercury_tpu.lint.audit`) traces the fused train step
  (and its ZeRO / bf16-scoring / sequence-parallel / pipeline-parallel
  variants) on CPU and checks *structural invariants of the traced
  program* as data: per-plan collective count/kind budgets, zero host
  callbacks, donation aliasing where configured, no f32 matmuls inside
  ``scoring_dtype=bf16`` regions, and a byte-identical jaxpr digest for
  ``telemetry=False`` against the committed seed digest. Budgets live in
  the committed ``lint/budgets.json`` golden file (regenerate with
  ``--regen``), so program drift is a reviewed diff, not a surprise.

See ``docs/LINT.md`` for the rule catalog and ``docs/DESIGN.md`` for the
audit invariants.
"""

from mercury_tpu.lint.engine import (
    Finding,
    format_findings,
    lint_paths,
    lint_source,
)
from mercury_tpu.lint.rules import RULES, Rule

__all__ = [
    "Finding",
    "Rule",
    "RULES",
    "format_findings",
    "lint_paths",
    "lint_source",
]
