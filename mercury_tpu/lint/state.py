"""graftlint Layer E: the state plane as an extracted, checked schema.

Mercury's correctness under preemption hinges on :class:`MercuryState`
surviving checkpoint and elastic resharding intact — the scoretable,
selection ledger, stream cursor and pending-selection ring all carry
hand-written reshard logic, and nothing *statically* guaranteed that a
newly added state field gets a reshard policy, a restore path and an
upgrade shim. Forgetting one is silent corruption. Layer E makes the
state plane explicit three ways, mirroring what Layer S did for the
control plane:

1. **Extract** (:func:`extract_state_facts`): an AST walk over
   ``train/state.py``, ``train/step.py``, ``train/checkpoint.py``,
   ``train/elastic.py`` and ``train/trainer.py`` pulls the structural
   facts the schema is built from — every ``MercuryState`` field with
   its shape-role (replicated / worker-sharded / rng-key, from the
   step's ``_state_specs``), its declared elastic policy
   (``train/state.py::ELASTIC_POLICIES``), the checkpoint lineage +
   upgrade shims (``train/checkpoint.py::STATE_SCHEMA_LINEAGE`` /
   ``UPGRADE_SHIMS``), and which ``elastic_restore`` replace kwarg /
   ``_carry_streamed_state`` ``extra[...]`` site / ``create_state``
   gated init / Trainer reprime handles it. Facts are semantic (no line
   numbers), so the golden only drifts on behavioral edits.
2. **Check + commit** (:func:`check_extraction`, :func:`state_doc`):
   static rules GLE01–GLE06 gate field-without-policy,
   policy-without-carry-site, restore paths that silently drop a field
   (the shim must name it), upgrade-shim lineage gaps, rng state
   resharded by copy instead of ``fold_in``, and checkpoint-manifest
   parity. The schema commits as ``lint/state_schema.json`` (schema
   ``graftlint_state_schema_v1``) with the shared ``--regen`` /
   ``--diff-out`` contract from ``lint/golden.py``, joining the
   all-or-nothing all-layer regen as the sixth golden. The doc carries
   a ``state_schema_sha`` digest over its fields + lineage; checkpoint
   manifests stamp that sha so restore can warn when a checkpoint
   predates the committed schema.
3. **Differential replay** (``python -m mercury_tpu.lint.state
   --differential``): the runtime half executes W=8 → W=4 → W=8
   round-trips per plan and asserts each policy's conformance contract
   — exact-carry fields bit-equal (GLE07), re-aggregate fields
   sum-preserving (GLE08, the sel_counts total invariant), re-seeded
   fields key-distinct (GLE09), cursors epoch-fraction-preserving
   (GLE10) — diffing per-leaf on failure and naming the violated
   policy by rule id.

The static half is stdlib-only (AST + JSON): the lint-state CI job runs
on a jax-free machine. Only ``--differential`` imports jax.
"""

from __future__ import annotations

import argparse
import ast
import hashlib
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from mercury_tpu.lint import golden

__all__ = [
    "STATE_SCHEMA", "POLICY_VOCAB", "extract_state_facts",
    "check_extraction", "state_doc", "schema_sha_of_facts",
    "default_state_schema_path", "run_state_check", "run_differential",
]

#: Golden schema tag; bump on any incompatible schema-shape change.
STATE_SCHEMA = "graftlint_state_schema_v1"

REGEN_HINT = "python -m mercury_tpu.lint --layer state --regen"

#: The modules the extractor walks, keyed by the short name facts use.
STATE_MODULES: Dict[str, str] = {
    "state": os.path.join("train", "state.py"),
    "step": os.path.join("train", "step.py"),
    "checkpoint": os.path.join("train", "checkpoint.py"),
    "elastic": os.path.join("train", "elastic.py"),
    "trainer": os.path.join("train", "trainer.py"),
}

#: The closed elastic-policy vocabulary (see the ``ELASTIC_POLICIES``
#: docstring in ``train/state.py`` for semantics). GLE01 rejects any
#: policy outside it.
POLICY_VOCAB = (
    "replicate", "reshard-exact", "re-aggregate", "re-seed",
    "cursor-fraction", "drop-on-shrink",
)

#: Policies whose carry site is a named ``replace()`` kwarg in
#: ``elastic_restore`` or an ``extra[...]`` assignment in
#: ``_carry_streamed_state`` (i.e. the field's checkpointed value flows
#: into the new state).
CARRIED_POLICIES = ("replicate", "reshard-exact", "re-aggregate",
                    "re-seed", "cursor-fraction")

#: ``create_state`` shape-argument names → schema dim symbols.
DIM_SYMBOLS: Dict[str, str] = {
    "n_workers": "W",
    "shard_len": "L",
    "stream_depth": "D",
    "stream_emit_size": "E",
    "stream_batch_size": "B",
    "pending_batch_size": "B",
    "cached_pool_size": "P",
}


def _package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def default_state_schema_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "state_schema.json")


# --------------------------------------------------------------------------
# AST fact extraction
# --------------------------------------------------------------------------

def _module_tree(key: str,
                 sources: Optional[Dict[str, str]] = None) -> ast.AST:
    rel = STATE_MODULES[key]
    if sources is not None and key in sources:
        return ast.parse(sources[key], filename=f"<fixture:{rel}>")
    path = os.path.join(_package_root(), rel)
    with open(path) as f:
        return ast.parse(f.read(), filename=path)


def _class_def(tree: ast.AST, name: str) -> Optional[ast.ClassDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == name:
            return node
    return None


def _function_def(tree: ast.AST, name: str) -> Optional[ast.FunctionDef]:
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _module_literal(tree: ast.AST, name: str) -> Optional[Any]:
    """Value of a module-level ``NAME = <literal>`` assignment."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        if not any(isinstance(t, ast.Name) and t.id == name
                   for t in node.targets):
            continue
        try:
            return ast.literal_eval(node.value)
        except (ValueError, SyntaxError):
            return None
    return None


def _dotted(node: ast.AST) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _ann_fields(cls: ast.ClassDef) -> List[Tuple[str, bool]]:
    """``(name, optional)`` per annotated field, declaration order.
    Optional = a default value is present (``= None`` in practice)."""
    out: List[Tuple[str, bool]] = []
    for stmt in cls.body:
        if isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target,
                                                          ast.Name):
            out.append((stmt.target.id, stmt.value is not None))
    return out


def _namedtuple_leaves(tree: ast.AST) -> Dict[str, List[str]]:
    """Leaf names of every module-level ``NamedTuple`` subclass."""
    out: Dict[str, List[str]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and any(
                (isinstance(b, ast.Name) and b.id == "NamedTuple")
                or (isinstance(b, ast.Attribute) and b.attr == "NamedTuple")
                for b in node.bases):
            out[node.name] = [n for n, _ in _ann_fields(node)]
    return out


def _spec_role(node: Optional[ast.AST]) -> Optional[str]:
    """Shape-role of one ``_state_specs`` kwarg expression: ``P()`` is
    replicated, ``P(axis)`` worker-sharded; constructor calls (EMAState,
    ShardStream) take the role of their leaves; ``A if flag else None``
    takes A's role; a genuinely two-armed conditional (ZeRO's opt_state)
    reports both."""
    if node is None:
        return None
    if isinstance(node, ast.Constant) and node.value is None:
        return None
    if isinstance(node, ast.IfExp):
        body = _spec_role(node.body)
        orelse = _spec_role(node.orelse)
        if orelse is None:
            return body
        if body == orelse:
            return body
        return f"{body}-or-{orelse}"
    if isinstance(node, ast.Call):
        name = _dotted(node.func)
        if name.split(".")[-1] == "P":
            return "worker-sharded" if node.args else "replicated"
        roles = {r for r in
                 ([_spec_role(a) for a in node.args]
                  + [_spec_role(k.value) for k in node.keywords])
                 if r is not None}
        if len(roles) == 1:
            return roles.pop()
        if roles:
            return "mixed"
    return "unknown"


def _state_spec_roles(step_tree: ast.AST) -> Dict[str, Optional[str]]:
    fn = _function_def(step_tree, "_state_specs")
    roles: Dict[str, Optional[str]] = {}
    if fn is None:
        return roles
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and _dotted(node.func).endswith("MercuryState")):
            for kw in node.keywords:
                if kw.arg is not None:
                    roles[kw.arg] = _spec_role(kw.value)
            break
    return roles


def _field_dims(create_fn: Optional[ast.FunctionDef]
                ) -> Dict[str, List[str]]:
    """Dim symbols per field from ``create_state``'s fresh-init
    assignments: Name ids inside tuple literals fed to array
    constructors (zeros/full/ones/broadcast_to), mapped through
    :data:`DIM_SYMBOLS`. Best-effort — fields whose shapes aren't
    literal tuples report no dims."""
    dims: Dict[str, List[str]] = {}
    if create_fn is None:
        return dims

    def tuple_dims(expr: ast.AST) -> List[str]:
        syms: List[str] = []
        for node in ast.walk(expr):
            if not (isinstance(node, ast.Call)
                    and _dotted(node.func).split(".")[-1]
                    in ("zeros", "ones", "full", "broadcast_to")):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Tuple) or (
                        isinstance(arg, ast.BinOp)
                        and isinstance(arg.op, ast.Add)):
                    for sub in ast.walk(arg):
                        if (isinstance(sub, ast.Name)
                                and sub.id in DIM_SYMBOLS):
                            syms.append(DIM_SYMBOLS[sub.id])
        seen: List[str] = []
        for s in syms:
            if s not in seen:
                seen.append(s)
        return seen

    for node in ast.walk(create_fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            got = tuple_dims(node.value)
            if got:
                dims.setdefault(node.targets[0].id, got)
    return dims


def _field_constructors(create_fn: Optional[ast.FunctionDef],
                        namedtuples: Dict[str, List[str]]
                        ) -> Dict[str, str]:
    """Field → NamedTuple constructor used in ``create_state`` (the
    annotation is ``Any`` for optional fields, so the constructor call
    is the extractable type evidence — GLE05 uses it to find fields
    that embed an ``rng`` leaf)."""
    out: Dict[str, str] = {}
    if create_fn is None:
        return out
    for node in ast.walk(create_fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)):
            ctor = _dotted(node.value.func).split(".")[-1]
            if ctor in namedtuples:
                out[node.targets[0].id] = ctor
    return out


def _gated_inits(create_fn: Optional[ast.FunctionDef]) -> List[str]:
    """Fields constructed under an ``if <flag>:`` in ``create_state`` —
    the fresh, topology-deterministic template init that drop-on-shrink
    fields fall back to after a reshard."""
    gated: List[str] = []
    if create_fn is None:
        return gated
    for node in ast.walk(create_fn):
        if not isinstance(node, ast.If):
            continue
        for sub in ast.walk(node):
            if (isinstance(sub, ast.Assign)
                    and len(sub.targets) == 1
                    and isinstance(sub.targets[0], ast.Name)):
                gated.append(sub.targets[0].id)
    return sorted(set(gated))


def _call_names(expr: ast.AST) -> List[str]:
    """Dotted names of every call inside ``expr`` (evidence of HOW a
    value was derived — ``jax.random.fold_in`` being the one GLE05
    cares about)."""
    names: List[str] = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = _dotted(node.func)
            if name:
                names.append(name)
    return sorted(set(names))


def _replace_kwargs(fn: Optional[ast.FunctionDef]
                    ) -> Tuple[Dict[str, List[str]], bool]:
    """The ``template.replace(...)`` carry site in ``elastic_restore``:
    field → call-name evidence (following one level of ``name = expr``
    dataflow inside the function), plus whether a ``**extra`` splat is
    present."""
    if fn is None:
        return {}, False
    assigns: Dict[str, List[str]] = {}
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)):
            assigns.setdefault(node.targets[0].id, []).extend(
                _call_names(node.value))
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "replace"):
            continue
        fields: Dict[str, List[str]] = {}
        splat = False
        for kw in node.keywords:
            if kw.arg is None:
                splat = True
                continue
            ev = list(_call_names(kw.value))
            if isinstance(kw.value, ast.Name):
                ev.extend(assigns.get(kw.value.id, []))
            fields[kw.arg] = sorted(set(ev))
        return fields, splat
    return {}, False


def _carry_extra(fn: Optional[ast.FunctionDef]) -> Dict[str, List[str]]:
    """``extra["<field>"] = ...`` assignments in
    ``_carry_streamed_state``: field → call-name evidence."""
    out: Dict[str, List[str]] = {}
    if fn is None:
        return out
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        tgt = node.targets[0]
        if (isinstance(tgt, ast.Subscript)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "extra"):
            sl = tgt.slice
            if isinstance(sl, ast.Index):  # py<3.9 compat shape
                sl = sl.value
            if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                ev = out.setdefault(sl.value, [])
                ev.extend(_call_names(node.value))
                out[sl.value] = sorted(set(ev))
    return out


def _string_constants(fn: ast.FunctionDef) -> List[str]:
    """Non-docstring string constants in ``fn``'s body — the names a
    shim declares (GLE03 requires the dropped field among them)."""
    doc = None
    if (fn.body and isinstance(fn.body[0], ast.Expr)
            and isinstance(fn.body[0].value, ast.Constant)
            and isinstance(fn.body[0].value.value, str)):
        doc = fn.body[0].value
    out: List[str] = []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, str) and node is not doc):
            out.append(node.value)
    return sorted(set(out))


def _shim_table(ckpt_tree: ast.AST
                ) -> Dict[str, Dict[str, Any]]:
    """``UPGRADE_SHIMS`` as ``"old->new" → {fn, names}`` where names are
    the string constants the shim function's body declares."""
    table: Dict[str, Dict[str, Any]] = {}
    fns = {node.name: node for node in ast.walk(ckpt_tree)
           if isinstance(node, ast.FunctionDef)}
    for node in ast.walk(ckpt_tree):
        if not (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name)
                        and t.id == "UPGRADE_SHIMS"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            continue
        for key, val in zip(node.value.keys, node.value.values):
            try:
                pair = ast.literal_eval(key)
            except (ValueError, SyntaxError):
                continue
            if not (isinstance(pair, tuple) and len(pair) == 2):
                continue
            fn_name = _dotted(val)
            fn = fns.get(fn_name)
            table["->".join(pair)] = {
                "fn": fn_name,
                "names": _string_constants(fn) if fn is not None else [],
            }
        break
    return table


def _raises_unknown_field(ckpt_tree: ast.AST) -> bool:
    """``apply_upgrade_shims`` raises a ValueError whose message speaks
    of unknown fields — the loud-failure half of GLE03."""
    fn = _function_def(ckpt_tree, "apply_upgrade_shims")
    if fn is None:
        return False
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Raise)
                and isinstance(node.exc, ast.Call)
                and _dotted(node.exc.func).endswith("ValueError")):
            continue
        text = ""
        for sub in ast.walk(node.exc):
            if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
                text += sub.value
        if "unknown" in text.lower():
            return True
    return False


def _manifest_keys(ckpt_tree: ast.AST) -> List[str]:
    fn = _function_def(ckpt_tree, "_write_manifest")
    if fn is None:
        return []
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "doc"
                        for t in node.targets)
                and isinstance(node.value, ast.Dict)):
            return sorted(k.value for k in node.value.keys
                          if isinstance(k, ast.Constant)
                          and isinstance(k.value, str))
    return []


def _mentions_string(fn: Optional[ast.FunctionDef], needle: str) -> bool:
    if fn is None:
        return False
    for node in ast.walk(fn):
        if (isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and needle in node.value):
            return True
    return False


def _reshard_begin_detail_keys(fn: Optional[ast.FunctionDef]) -> List[str]:
    """Keys of the ``detail={...}`` dict of the ``elastic/reshard_begin``
    journal emit in ``elastic_restore``."""
    if fn is None:
        return []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and "emit" in node.func.attr
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and node.args[0].value == "elastic/reshard_begin"):
            continue
        for kw in node.keywords:
            if kw.arg == "detail" and isinstance(kw.value, ast.Dict):
                return sorted(k.value for k in kw.value.keys
                              if isinstance(k, ast.Constant)
                              and isinstance(k.value, str))
    return []


def _calls_named(fn: Optional[ast.FunctionDef], needle: str) -> bool:
    if fn is None:
        return False
    for node in ast.walk(fn):
        if (isinstance(node, ast.Call)
                and needle in _dotted(node.func)):
            return True
    return False


def extract_state_facts(
        sources: Optional[Dict[str, str]] = None) -> Dict[str, Any]:
    """Walk the state-plane modules and return the structural facts the
    schema is built from. ``sources`` overrides module source text by
    :data:`STATE_MODULES` key (seeded-violation fixtures)."""
    state_tree = _module_tree("state", sources)
    step_tree = _module_tree("step", sources)
    ckpt_tree = _module_tree("checkpoint", sources)
    ela_tree = _module_tree("elastic", sources)
    trn_tree = _module_tree("trainer", sources)

    state_cls = _class_def(state_tree, "MercuryState")
    ann = _ann_fields(state_cls) if state_cls is not None else []
    field_order = [n for n, _ in ann]
    optional = {n: opt for n, opt in ann}
    policies = _module_literal(state_tree, "ELASTIC_POLICIES") or {}
    namedtuples = _namedtuple_leaves(state_tree)
    roles = _state_spec_roles(step_tree)
    create_fn = _function_def(state_tree, "create_state")
    dims = _field_dims(create_fn)
    constructors = _field_constructors(create_fn, namedtuples)

    fields: Dict[str, Dict[str, Any]] = {}
    for name in field_order:
        role = "rng-key" if name == "rng" else roles.get(name)
        fields[name] = {
            "optional": bool(optional.get(name)),
            "policy": policies.get(name),
            "role": role,
            "dims": dims.get(name, []),
        }

    lineage_lit = _module_literal(ckpt_tree, "STATE_SCHEMA_LINEAGE") or ()
    versions = [v for v, _ in lineage_lit]
    added = {v: sorted(f) for v, f in lineage_lit}
    head = _module_literal(ckpt_tree, "STATE_SCHEMA_VERSION")

    ela_restore = _function_def(ela_tree, "elastic_restore")
    replace_kw, extra_splat = _replace_kwargs(ela_restore)
    carry_extra = _carry_extra(
        _function_def(ela_tree, "_carry_streamed_state"))

    facts: Dict[str, Any] = {
        "modules": {k: STATE_MODULES[k].replace(os.sep, "/")
                    for k in sorted(STATE_MODULES)},
        "field_order": field_order,
        "fields": fields,
        "policies": {k: policies[k] for k in sorted(policies)},
        "namedtuple_leaves": {k: namedtuples[k]
                              for k in sorted(namedtuples)},
        "constructors": {k: constructors[k]
                         for k in sorted(constructors)},
        "carry": {
            "replace_kwargs": {k: replace_kw[k]
                               for k in sorted(replace_kw)},
            "extra_splat": extra_splat,
            "carry_extra": {k: carry_extra[k]
                            for k in sorted(carry_extra)},
            "gated_init": _gated_inits(create_fn),
            "reprime": {
                "pending_sel": _calls_named(
                    _function_def(trn_tree, "_recommit_state"),
                    "_stream_prime"),
            },
        },
        "lineage": {
            "versions": versions,
            "added": added,
            "head": head,
        },
        "shims": {
            "pairs": _shim_table(ckpt_tree),
            "unknown_field_raise": _raises_unknown_field(ckpt_tree),
        },
        "manifest": {
            "keys": _manifest_keys(ckpt_tree),
            "restore_checks_sha": _mentions_string(
                _function_def(ckpt_tree, "_restore_one"),
                "state_schema_sha"),
            "reshard_begin_detail": _reshard_begin_detail_keys(
                ela_restore),
        },
    }
    return facts


# --------------------------------------------------------------------------
# static gates (GLE01–GLE06)
# --------------------------------------------------------------------------

def check_extraction(facts: Dict[str, Any]) -> List[str]:
    """Hard gates on the extracted facts — the state-plane contract.
    Every finding names its rule id (GLE01–GLE06)."""
    errors: List[str] = []
    field_order: List[str] = facts["field_order"]
    policies: Dict[str, Optional[str]] = facts["policies"]

    if not field_order:
        errors.append("GLE01 state: MercuryState fields not extractable "
                      "from train/state.py")

    # GLE01: field ↔ policy parity, closed vocabulary.
    for name in field_order:
        pol = policies.get(name)
        if pol is None:
            errors.append(
                f"GLE01 state: MercuryState field {name!r} has no "
                f"ELASTIC_POLICIES entry — every state field must "
                f"declare its elastic policy (train/state.py)")
        elif pol not in POLICY_VOCAB:
            errors.append(
                f"GLE01 state: field {name!r} declares unknown policy "
                f"{pol!r} (vocabulary: {', '.join(POLICY_VOCAB)})")
    for name in sorted(set(policies) - set(field_order)):
        errors.append(
            f"GLE01 state: ELASTIC_POLICIES names {name!r}, which is "
            f"not a MercuryState field — stale entry")

    # GLE02: policy ↔ carry site.
    replace_kw = facts["carry"]["replace_kwargs"]
    carry_extra = facts["carry"]["carry_extra"]
    gated = set(facts["carry"]["gated_init"])
    for name in field_order:
        pol = policies.get(name)
        if pol in CARRIED_POLICIES:
            if name not in replace_kw and name not in carry_extra:
                errors.append(
                    f"GLE02 state: field {name!r} (policy {pol}) has no "
                    f"carry site — neither a replace() kwarg in "
                    f"elastic_restore nor an extra[...] assignment in "
                    f"_carry_streamed_state handles it")
        elif pol == "drop-on-shrink":
            if name in replace_kw or name in carry_extra:
                errors.append(
                    f"GLE02 state: field {name!r} declares "
                    f"drop-on-shrink but IS carried by the elastic "
                    f"restore — declare the real policy instead")
            if name not in gated:
                errors.append(
                    f"GLE02 state: drop-on-shrink field {name!r} has no "
                    f"gated fresh init in create_state — nothing "
                    f"rebuilds it for the new topology")
    if carry_extra and not facts["carry"]["extra_splat"]:
        errors.append(
            "GLE02 state: _carry_streamed_state builds extra[...] "
            "entries but elastic_restore's replace() has no **extra "
            "splat — carried fields would be silently discarded")
    if (policies.get("pending_sel") == "drop-on-shrink"
            and not facts["carry"]["reprime"].get("pending_sel")):
        errors.append(
            "GLE02 state: pending_sel is in-flight drop-on-shrink "
            "state but Trainer._recommit_state shows no _stream_prime "
            "call — the ring would restart cold instead of re-primed")

    # GLE03 + GLE04: lineage, shims, loud unknown-field failure.
    lineage = facts["lineage"]
    versions: List[str] = lineage["versions"]
    shims = facts["shims"]["pairs"]
    if not versions:
        errors.append("GLE04 state: STATE_SCHEMA_LINEAGE not "
                      "extractable from train/checkpoint.py")
    if versions and lineage["head"] != versions[-1]:
        errors.append(
            f"GLE04 state: STATE_SCHEMA_VERSION {lineage['head']!r} is "
            f"not the last lineage entry {versions[-1]!r} — the build "
            f"must write the newest schema")
    known_pairs = set()
    for old, new in zip(versions, versions[1:]):
        pair = f"{old}->{new}"
        known_pairs.add(pair)
        info = shims.get(pair)
        if info is None:
            errors.append(
                f"GLE04 state: lineage gap — no upgrade shim for "
                f"{pair}; checkpoints written at {old!r} cannot reach "
                f"HEAD ({versions[-1]!r})")
            continue
        for fld in lineage["added"].get(new, []):
            if fld not in info["names"]:
                errors.append(
                    f"GLE03 state: upgrade shim {info['fn']} ({pair}) "
                    f"does not name field {fld!r} as a string constant "
                    f"— a restore path that drops a field must say "
                    f"which field it drops")
    for pair in sorted(set(shims) - known_pairs):
        errors.append(
            f"GLE04 state: UPGRADE_SHIMS has entry {pair!r} that is "
            f"not a consecutive lineage pair")
    for ver, flds in sorted(lineage["added"].items()):
        for fld in flds:
            if field_order and fld not in field_order:
                errors.append(
                    f"GLE04 state: lineage version {ver!r} adds "
                    f"{fld!r}, which is not a MercuryState field")
    if not facts["shims"]["unknown_field_raise"]:
        errors.append(
            "GLE03 state: apply_upgrade_shims does not raise a loud "
            "ValueError on unknown checkpoint fields — a checkpoint "
            "from a newer schema would silently drop state")

    # GLE05: rng state must be re-seeded via fold_in, never copied.
    fields = facts["fields"]
    for name in field_order:
        if fields[name].get("role") == "rng-key":
            if policies.get(name) != "re-seed":
                errors.append(
                    f"GLE05 state: rng-key field {name!r} declares "
                    f"policy {policies.get(name)!r} — PRNG state must "
                    f"be re-seed (shared keys across workers break the "
                    f"sampler's independence)")
            ev = facts["carry"]["replace_kwargs"].get(name, [])
            if name in facts["carry"]["replace_kwargs"] and not any(
                    "fold_in" in e for e in ev):
                errors.append(
                    f"GLE05 state: rng-key field {name!r} is carried "
                    f"without fold_in ({ev or 'no call evidence'}) — "
                    f"resharding PRNG keys by copy replays the old "
                    f"draw sequence on the new topology")
    # A field whose NamedTuple embeds an rng leaf (pending_sel's raw
    # uint32 lookahead key) must re-derive it — drop-on-shrink reprime
    # or re-seed; a carried copy would replay the old key stream.
    for name, ctor in facts["constructors"].items():
        leaves = facts["namedtuple_leaves"].get(ctor, [])
        if "rng" in leaves and policies.get(name) not in (
                "drop-on-shrink", "re-seed"):
            errors.append(
                f"GLE05 state: field {name!r} ({ctor}) embeds an rng "
                f"leaf but declares policy {policies.get(name)!r} — "
                f"embedded key state must be re-derived, not copied")

    # GLE06: checkpoint-manifest parity.
    manifest = facts["manifest"]
    if "state_schema_sha" not in manifest["keys"]:
        errors.append(
            "GLE06 state: checkpoint manifest (_write_manifest) does "
            "not stamp state_schema_sha — restore cannot detect a "
            "checkpoint that predates the committed schema")
    if not manifest["restore_checks_sha"]:
        errors.append(
            "GLE06 state: _restore_one never references "
            "state_schema_sha — the manifest stamp is written but "
            "never checked on restore")
    if "state_schema_sha" not in manifest["reshard_begin_detail"]:
        errors.append(
            "GLE06 state: elastic/reshard_begin journal detail lacks "
            "state_schema_sha — the run report cannot tie a reshard "
            "to the schema it ran under")
    return errors


# --------------------------------------------------------------------------
# golden doc + verify / regen (the --layer state CLI contract)
# --------------------------------------------------------------------------

def schema_sha_of_facts(facts: Dict[str, Any]) -> str:
    """Digest over the schema-defining subset (fields + lineage) — NOT
    the golden file bytes, so the stamp is stable across provenance or
    carry-evidence churn and has no self-reference problem."""
    core = {"fields": facts["fields"], "lineage": facts["lineage"]}
    return hashlib.sha256(
        json.dumps(core, sort_keys=True).encode()).hexdigest()


def state_doc(facts: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """The committed golden document. Provenance carries only the regen
    command (no jax versions — the static half is stdlib-only and the
    golden must not drift on toolchain upgrades)."""
    if facts is None:
        facts = extract_state_facts()
    return {
        "schema": STATE_SCHEMA,
        "provenance": {"regenerate_with": REGEN_HINT},
        "state_schema_sha": schema_sha_of_facts(facts),
        "facts": facts,
    }


def _doc_diff(committed: Dict[str, Any],
              fresh: Dict[str, Any]) -> List[str]:
    lines: List[str] = []
    a = committed.get("facts", {})
    b = fresh.get("facts", {})
    for key in sorted(set(a) | set(b)):
        va, vb = a.get(key), b.get(key)
        if va != vb:
            lines.append(f"  facts.{key}: committed "
                         f"{json.dumps(va, sort_keys=True)[:200]} "
                         f"vs extracted "
                         f"{json.dumps(vb, sort_keys=True)[:200]}")
    sha_a = committed.get("state_schema_sha")
    sha_b = fresh.get("state_schema_sha")
    if sha_a != sha_b:
        lines.append(f"  state_schema_sha: committed {sha_a} vs "
                     f"extracted {sha_b}")
    if lines:
        lines.insert(0, "state schema drifted from committed golden "
                        f"(regenerate with {REGEN_HINT}):")
    return lines


def run_state_check(state_schema_path: Optional[str] = None,
                    regen: bool = False,
                    diff_out: Optional[str] = None,
                    ) -> Tuple[List[str], List[str]]:
    """Layer E entry: extract, gate (GLE01–GLE06), and verify (or
    ``--regen``) the committed state schema. Returns
    ``(errors, warnings)`` on the shared layer-CLI contract; raises
    FileNotFoundError when verifying with no committed golden (the CLI
    maps it to exit 2 + regen hint)."""
    path = state_schema_path or default_state_schema_path()
    facts = extract_state_facts()
    errors = check_extraction(facts)
    doc = state_doc(facts)
    warnings: List[str] = []
    if regen:
        golden.write_golden(path, doc)
        warnings.append(f"state schema written to {path}")
        return errors, warnings
    committed = golden.load_golden(path, STATE_SCHEMA, REGEN_HINT)
    diff = _doc_diff(committed, doc)
    if diff:
        errors.extend(diff)
        if diff_out:
            golden.write_diff_file(diff_out,
                                   "graftlint state-schema diff", diff)
    return errors, warnings


# --------------------------------------------------------------------------
# runtime half: differential reshard conformance (GLE07–GLE10)
# --------------------------------------------------------------------------

#: Differential plans: config knobs layered over the smoke base. The
#: scoretable plan exercises reshard-exact (table rows), re-aggregate
#: (sel_counts ledger) and cursor-fraction; the zero plan exercises the
#: ZeRO-1 reshard-exact optimizer chunks.
DIFFERENTIAL_PLANS: Dict[str, Dict[str, Any]] = {
    "scoretable": {"sampler": "scoretable", "refresh_size": 8},
    "zero": {"zero_sharding": True},
}


def _diff_cfg(world: int, workdir: str, plan: Dict[str, Any]):
    from mercury_tpu.config import TrainConfig

    base = dict(
        model="smallcnn", dataset="synthetic", world_size=world,
        batch_size=8, presample_batches=2, num_epochs=1,
        steps_per_epoch=4, eval_every=0, log_every=0,
        compute_dtype="float32", seed=0, checkpoint_dir=workdir,
    )
    base.update(plan)
    return TrainConfig(**base)


def _run_steps(trainer, n: int) -> None:
    for _ in range(n):
        trainer.state, _ = trainer.train_step(
            trainer.state, trainer._step_x, trainer._step_y,
            trainer.dataset.shard_indices)


def _global_table(trainer, state, w: int):
    """Per-sample (global) score map + selection-count totals for a
    ``[W, L]`` run — the reshard-invariant views GLE07/GLE08 compare."""
    import numpy as np

    from mercury_tpu.train.elastic import _shard_index_matrix

    sidx = _shard_index_matrix(trainer, w)
    n = int(np.asarray(trainer.dataset.y_train).size)
    scores = counts = None
    if state.scoretable is not None:
        flat = np.full((n,), np.nan, np.float32)
        flat[sidx.reshape(-1)] = np.asarray(
            state.scoretable.scores, np.float32).reshape(-1)
        scores = flat
    if state.sel_counts is not None:
        tot = np.zeros((n,), np.int64)
        np.add.at(tot, sidx.reshape(-1),
                  np.asarray(state.sel_counts, np.int64).reshape(-1))
        counts = tot
    return sidx, scores, counts


def _flat_moments(state, w: int, n_params: int):
    import jax
    import numpy as np

    out = []
    for leaf in jax.tree_util.tree_leaves(state.opt_state):
        a = np.asarray(leaf)
        if a.ndim >= 2 and a.shape[0] == w:
            out.append(a.reshape(w * a.shape[1], -1)[:n_params])
    return out


def _check_hop(findings: List[str], plan: str, hop: str,
               t_old, s_old, w_old: int, t_new, w_new: int) -> None:
    """Policy-conformance checks for one reshard hop: every violated
    invariant is reported with its rule id and the offending leaf."""
    import jax
    import numpy as np

    s_new = t_new.state

    def flag(rule: str, leaf: str, msg: str) -> None:
        findings.append(f"{rule} [{plan}] {hop}: {leaf}: {msg}")

    # GLE07 exact carry: params / batch_stats bit-equal per leaf.
    for what in ("params", "batch_stats"):
        old_l, treedef = jax.tree_util.tree_flatten_with_path(
            getattr(s_old, what))
        new_l = jax.tree_util.tree_leaves(getattr(s_new, what))
        for (kp, a), b in zip(old_l, new_l):
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                flag("GLE07", what + jax.tree_util.keystr(kp),
                     "exact-carry leaf not bit-equal across reshard")
    # GLE07 exact carry: optimizer moments (ZeRO chunks re-flattened).
    if t_new.config.zero_sharding:
        from mercury_tpu.utils.tree import tree_flatten_to_vector

        pvec, _ = tree_flatten_to_vector(s_new.params)
        want = _flat_moments(s_old, w_old, int(pvec.size))
        got = _flat_moments(s_new, w_new, int(pvec.size))
        for i, (a, b) in enumerate(zip(want, got)):
            if not np.array_equal(a, b):
                flag("GLE07", f"opt_state.moment[{i}]",
                     "ZeRO moment vector not bit-equal after re-chunk")
    else:
        for i, (a, b) in enumerate(zip(
                jax.tree_util.tree_leaves(s_old.opt_state),
                jax.tree_util.tree_leaves(s_new.opt_state))):
            if np.shape(a) == np.shape(b) and not np.array_equal(
                    np.asarray(a), np.asarray(b)):
                flag("GLE07", f"opt_state.leaf[{i}]",
                     "replicated optimizer leaf changed across reshard")

    old_sidx, old_scores, old_counts = _global_table(t_old, s_old, w_old)
    new_sidx, new_scores, new_counts = _global_table(t_new, s_new, w_new)
    # GLE07 exact carry: scoretable rows the old run owned carry
    # bit-equal into the new partition.
    if old_scores is not None and new_scores is not None:
        owned = np.zeros(old_scores.shape, bool)
        owned[old_sidx.reshape(-1)] = True
        bad = np.flatnonzero(
            owned & (new_scores != old_scores)
            & ~(np.isnan(new_scores) & np.isnan(old_scores)))
        if bad.size:
            flag("GLE07", "scoretable.scores",
                 f"{bad.size} carried per-sample rows not bit-equal "
                 f"(first: sample {int(bad[0])}, "
                 f"{old_scores[bad[0]]!r} -> {new_scores[bad[0]]!r})")
    # GLE08 re-aggregate: the ledger's global total is invariant.
    if old_counts is not None and new_counts is not None:
        if int(old_counts.sum()) != int(new_counts.sum()):
            flag("GLE08", "sel_counts",
                 f"global selection total not preserved: "
                 f"{int(old_counts.sum())} -> {int(new_counts.sum())}")
    # GLE08 re-aggregate: EMA warm start equals the old workers' mean.
    ema_want = float(np.mean(np.asarray(s_old.ema.value)))
    ema_got = np.asarray(s_new.ema.value)
    if not np.allclose(ema_got, ema_want, rtol=1e-5):
        flag("GLE08", "ema.value",
             f"warm start != old mean ({ema_want} vs {ema_got[:4]})")
    # GLE09 re-seed: new keys pairwise distinct and distinct from every
    # checkpointed key (a copy would replay the old draw sequence).
    def key_rows(rng):
        try:
            data = jax.random.key_data(rng)
        except (TypeError, AttributeError):
            data = rng  # raw uint32 key data under legacy jax
        arr = np.asarray(data)
        return [bytes(row.tobytes()) for row in arr]

    old_keys = set(key_rows(s_old.rng))
    new_keys = key_rows(s_new.rng)
    if len(set(new_keys)) != len(new_keys):
        flag("GLE09", "rng", "restored worker keys are not pairwise "
                             "distinct (copied key state)")
    for i, kb in enumerate(new_keys):
        if kb in old_keys:
            flag("GLE09", f"rng[{i}]",
                 "restored key equals a checkpointed key — re-seed "
                 "must fold_in, not copy")
    # GLE10 cursor-fraction: epoch fraction preserved to 1/L_new.
    l_old = int(np.shape(s_old.stream.perm)[1])
    l_new = int(np.shape(s_new.stream.perm)[1])
    frac_old = float(np.mean(np.asarray(s_old.stream.cursor,
                                        np.float64))) / max(l_old, 1)
    frac_new = float(np.mean(np.asarray(s_new.stream.cursor,
                                        np.float64))) / max(l_new, 1)
    if abs(frac_new - frac_old) > 1.5 / max(l_new, 1) + 1e-9:
        flag("GLE10", "stream.cursor",
             f"epoch fraction not preserved: {frac_old:.4f} -> "
             f"{frac_new:.4f} (tolerance 1.5/L_new)")


def run_differential(plans: Sequence[str] = ("scoretable", "zero"),
                     steps: int = 4, w_hi: int = 8, w_lo: int = 4,
                     workdir: Optional[str] = None) -> List[str]:
    """Execute the W=hi → W=lo → W=hi round-trip per plan and return
    policy-conformance findings (empty = conformant). Requires jax (and
    ``w_hi`` CPU devices — see :func:`main`'s XLA_FLAGS setup)."""
    import shutil
    import tempfile

    from mercury_tpu.parallel.mesh import host_cpu_mesh
    from mercury_tpu.train.trainer import Trainer

    findings: List[str] = []
    root = workdir or tempfile.mkdtemp(prefix="graftlint_state_diff_")
    try:
        for plan in plans:
            knobs = DIFFERENTIAL_PLANS[plan]
            d1 = os.path.join(root, plan, "hi")
            d2 = os.path.join(root, plan, "lo")
            os.makedirs(d1, exist_ok=True)
            os.makedirs(d2, exist_ok=True)

            t1 = Trainer(_diff_cfg(w_hi, d1, knobs),
                         mesh=host_cpu_mesh(w_hi))
            _run_steps(t1, steps)
            t1.save()
            s1 = t1.state

            t2 = Trainer(_diff_cfg(w_lo, d2, knobs),
                         mesh=host_cpu_mesh(w_lo))
            t2.restore_elastic(d1)
            _check_hop(findings, plan, f"W={w_hi}->W={w_lo}",
                       t1, s1, w_hi, t2, w_lo)
            s2 = t2.state
            t2.save()

            t3 = Trainer(_diff_cfg(w_hi, d2, knobs),
                         mesh=host_cpu_mesh(w_hi))
            t3.restore_elastic()
            _check_hop(findings, plan, f"W={w_lo}->W={w_hi}",
                       t2, s2, w_lo, t3, w_hi)
    finally:
        if workdir is None:
            shutil.rmtree(root, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# module CLI
# --------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m mercury_tpu.lint.state",
        description="graftlint Layer E: state-schema golden verify "
                    "(static, stdlib-only) or --differential reshard "
                    "conformance (requires jax).")
    ap.add_argument("--state-schema", default=None, metavar="PATH",
                    help="state_schema.json to verify against / "
                         "regenerate")
    ap.add_argument("--regen", action="store_true",
                    help="re-extract and WRITE the golden instead of "
                         "verifying")
    ap.add_argument("--diff-out", default=None, metavar="PATH",
                    help="write the schema diff to this file on "
                         "mismatch (CI artifact)")
    ap.add_argument("--differential", action="store_true",
                    help="run the W=8->4->8 reshard round-trips and "
                         "check policy conformance (GLE07-GLE10)")
    ap.add_argument("--plans", default=None,
                    help="comma-separated differential plans "
                         "(default: scoretable,zero)")
    ap.add_argument("--steps", type=int, default=4,
                    help="train steps before the first save "
                         "(differential)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    if args.differential:
        # 8 virtual CPU devices before jax initializes; idempotent when
        # conftest/CI already set it.
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8"
            ).strip()
        plans = (tuple(p.strip() for p in args.plans.split(","))
                 if args.plans else tuple(DIFFERENTIAL_PLANS))
        unknown = [p for p in plans if p not in DIFFERENTIAL_PLANS]
        if unknown:
            print(f"unknown differential plan(s): {', '.join(unknown)} "
                  f"(known: {', '.join(DIFFERENTIAL_PLANS)})",
                  file=sys.stderr)
            return 2
        findings = run_differential(plans=plans, steps=args.steps)
        if args.as_json:
            print(json.dumps({"schema": "graftlint_findings_v2",
                              "findings": [
                                  {"layer": "state",
                                   "severity": "error", "message": f}
                                  for f in findings]}, indent=2))
        else:
            for line in findings:
                print(line)
            if not findings:
                print(f"graftlint state: differential reshard "
                      f"conformant ({', '.join(plans)}; GLE07-GLE10)")
        return 1 if findings else 0

    try:
        errors, warnings = run_state_check(
            state_schema_path=args.state_schema,
            regen=args.regen, diff_out=args.diff_out)
    except FileNotFoundError as exc:
        print(f"graftlint state: state schema missing ({exc}) — run "
              f"with --regen first", file=sys.stderr)
        return 2
    except (OSError, ValueError) as exc:
        print(f"graftlint state: {exc}", file=sys.stderr)
        return 2
    if args.as_json:
        print(json.dumps({"schema": "graftlint_findings_v2",
                          "findings": (
                              [{"layer": "state", "severity": "warning",
                                "message": w} for w in warnings]
                              + [{"layer": "state", "severity": "error",
                                  "message": e} for e in errors])},
                         indent=2))
    else:
        for line in warnings:
            print(f"warning: {line}")
        for line in errors:
            print(line)
        if not errors:
            print("graftlint state: schema verified against "
                  "lint/state_schema.json; GLE01-GLE06 hold")
    return 1 if errors else 0


if __name__ == "__main__":
    sys.exit(main())
