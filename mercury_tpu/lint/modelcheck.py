"""Exhaustive explorer for the Layer S control-plane machine.

``lint/control.py`` extracts the supervisor's transition system into an
explicit product graph (ladder level × restart-budget bucket × SLO latch
set × probe-pin flag — a few dozen states, a few hundred edges). This
module walks ALL of it and proves the six named invariants as hard lint
gates; a controller that drives the ladder automatically (ROADMAP item
3) lands behind these proofs:

- **GLS01 uniform-absorbing** — the only edges that lower the ladder
  are successful recovery probes, so under a persistent fault (no probe
  can succeed) every level — uniform in particular — is absorbing.
- **GLS02 recoverability** — every reachable state has a path to a
  level-0 (async) state: no degraded corner is a dead end once the
  fault clears and the latches release.
- **GLS03 no-oscillation** — no cycle both recovers and re-breaches
  without passing an SLO release: formally, any strongly connected
  component containing a recover-emitting edge and a breach-emitting
  edge must contain a release-emitting edge. The rising-edge latch
  makes this structural (a breach flips a latch bit that only a release
  flips back); remove the latch and this gate fails.
- **GLS04 budget-monotone** — restart-budget buckets only move up their
  order within an episode; the single sanctioned reset is the probe
  climb into level 0 (full recovery).
- **GLS05 journal-kind registry + parent closure** — every kind any
  edge emits is in ``obs/registry.py::EVENT_KINDS``, and the per-kind
  parent contract is closed and rooted: from any episode kind, walking
  allowed parents reaches a root (a kind allowed to start a chain), so
  every degrade episode forms one connected chain in the event DAG.
- **GLS06 levels-step-by-one** — every edge changes the level by at
  most one, and a degrade/recover emission implies exactly +1/-1.

Stdlib-only, like the rest of the layer: the model check runs on the
committed golden without jax, in the same CI job that verifies it.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Set

__all__ = ["check_invariants"]


def _registered_kinds() -> Dict[str, str]:
    from mercury_tpu.lint.metrics import load_event_registry

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return load_event_registry(os.path.join(root, "obs", "registry.py"))


def _sccs(nodes: List[str],
          adj: Dict[str, List[str]]) -> List[Set[str]]:
    """Kosaraju strongly-connected components, iterative (the product
    graph is small, but recursion limits are not a failure mode a lint
    gate should have)."""
    visited: Set[str] = set()
    order: List[str] = []
    for start in nodes:
        if start in visited:
            continue
        stack = [(start, iter(adj.get(start, [])))]
        visited.add(start)
        while stack:
            node, it = stack[-1]
            advanced = False
            for nxt in it:
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, iter(adj.get(nxt, []))))
                    advanced = True
                    break
            if not advanced:
                stack.pop()
                order.append(node)
    radj: Dict[str, List[str]] = {}
    for src, dsts in adj.items():
        for dst in dsts:
            radj.setdefault(dst, []).append(src)
    comps: List[Set[str]] = []
    assigned: Set[str] = set()
    for start in reversed(order):
        if start in assigned:
            continue
        comp = {start}
        assigned.add(start)
        stack2 = [start]
        while stack2:
            node = stack2.pop()
            for prev in radj.get(node, []):
                if prev not in assigned:
                    assigned.add(prev)
                    comp.add(prev)
                    stack2.append(prev)
        comps.append(comp)
    return comps


def check_invariants(machine: Dict[str, Any],
                     registered: Optional[Dict[str, str]] = None,
                     ) -> List[str]:
    """BFS/SCC-explore the machine and return one error line per
    violated invariant instance (empty = all six proved)."""
    errors: List[str] = []
    states: List[Dict[str, Any]] = machine.get("states", [])
    edges: List[Dict[str, Any]] = machine.get("edges", [])
    levels: List[str] = machine.get("levels", [])
    buckets: List[str] = machine.get("buckets", [])
    if not states or not edges or not levels:
        return ["GLS00 control: machine is empty — extraction produced "
                "no states/edges"]
    ids = {s["id"] for s in states}
    lv = {s["id"]: int(s["level"]) for s in states}
    bk = {s["id"]: s["bucket"] for s in states}
    border = {b: i for i, b in enumerate(buckets)}
    if machine.get("initial") not in ids:
        errors.append("GLS00 control: initial state "
                      f"{machine.get('initial')!r} not in the state set")
    dangling = [e for e in edges
                if e["from"] not in ids or e["to"] not in ids]
    for e in dangling[:5]:
        errors.append(f"GLS00 control: edge {e['input']} references an "
                      f"unknown state ({e['from']} -> {e['to']})")
    if dangling:
        return errors

    deg_kinds = {k for k, r in machine.get("kind_rules", {}).items()
                 if r.get("delta") == 1}
    rec_kinds = {k for k, r in machine.get("kind_rules", {}).items()
                 if r.get("delta") == -1}
    breach_kinds = {k for k, r in machine.get("kind_rules", {}).items()
                    if r.get("latch") in ("set", "none")
                    and k.endswith("breach")}
    release_kinds = {k for k, r in machine.get("kind_rules", {}).items()
                     if r.get("latch") == "clear"}

    # GLS01: only successful probes descend the ladder — uniform (and
    # every level) is absorbing while the fault keeps probes failing.
    for e in edges:
        if lv[e["to"]] < lv[e["from"]] and e["input"] != "probe_ok":
            errors.append(
                f"GLS01 control: {e['input']} lowers the ladder "
                f"({e['from']} -> {e['to']}) — only probe_ok may "
                f"descend, so uniform stays absorbing under a "
                f"persistent fault")

    # GLS02: every reachable state can get back to async (level 0).
    radj: Dict[str, List[str]] = {}
    for e in edges:
        radj.setdefault(e["to"], []).append(e["from"])
    canreach = {s["id"] for s in states if lv[s["id"]] == 0}
    frontier = list(canreach)
    while frontier:
        node = frontier.pop()
        for prev in radj.get(node, []):
            if prev not in canreach:
                canreach.add(prev)
                frontier.append(prev)
    for s in states:
        if s["id"] not in canreach:
            errors.append(
                f"GLS02 control: state {s['id']} has no path back to "
                f"async — a degraded corner would be permanent even "
                f"after the fault clears")

    # GLS03: no recover→re-breach cycle without a latch release. The
    # SCC form is sound: a breach edge inside an SCC flips a latch bit
    # that only a release edge flips back, so a latched machine always
    # carries the release inside the component; a latch-free machine
    # (the oscillation fixture) has the recover+breach component with
    # no release edge and fails here.
    adj: Dict[str, List[str]] = {}
    for e in edges:
        adj.setdefault(e["from"], []).append(e["to"])
    comp_of: Dict[str, int] = {}
    comps = _sccs(sorted(ids), adj)
    for i, comp in enumerate(comps):
        for node in comp:
            comp_of[node] = i
    internal: Dict[int, Dict[str, bool]] = {}
    for e in edges:
        ci = comp_of[e["from"]]
        if ci != comp_of[e["to"]]:
            continue
        slot = internal.setdefault(ci, {"recover": False,
                                        "breach": False,
                                        "release": False})
        emits = set(e.get("emits", []))
        if emits & rec_kinds:
            slot["recover"] = True
        if emits & breach_kinds:
            slot["breach"] = True
        if emits & release_kinds:
            slot["release"] = True
    for ci, slot in sorted(internal.items()):
        if slot["recover"] and slot["breach"] and not slot["release"]:
            sample = sorted(comps[ci])[:3]
            errors.append(
                f"GLS03 control: oscillation — a cycle through "
                f"{sample} both recovers and re-breaches without an "
                f"SLO release (the rising-edge latch is missing or "
                f"bypassed)")

    # GLS04: budget buckets are monotone within an episode; the only
    # reset is the probe climb into level 0.
    for e in edges:
        if border.get(bk[e["to"]], 0) < border.get(bk[e["from"]], 0):
            full_recovery = (e["input"] == "probe_ok"
                             and lv[e["to"]] == 0
                             and bk[e["to"]] == buckets[0])
            if not full_recovery:
                errors.append(
                    f"GLS04 control: {e['input']} lowers the restart "
                    f"bucket ({e['from']} -> {e['to']}) outside a full "
                    f"recovery — budgets must be monotone within an "
                    f"episode")

    # GLS05: every emitted kind is registered, and the parent contract
    # is closed + rooted so each episode is one connected chain.
    if registered is None:
        registered = _registered_kinds()
    emitted: Set[str] = set()
    for e in edges:
        emitted.update(e.get("emits", []))
    for kind in sorted(emitted - set(registered)):
        errors.append(f"GLS05 control: edge-emitted kind {kind!r} is "
                      f"not in obs/registry.py::EVENT_KINDS")
    parents: Dict[str, List[Optional[str]]] = machine.get("parents", {})
    for kind in sorted(emitted - set(parents)):
        errors.append(f"GLS05 control: emitted kind {kind!r} has no "
                      f"parent contract — its episode chain would be "
                      f"disconnected")
    for kind, allowed in sorted(parents.items()):
        for p in allowed:
            if p is not None and p not in parents:
                errors.append(
                    f"GLS05 control: {kind} allows parent {p!r} which "
                    f"is not a modeled kind — the chain would dangle")
    # Rootedness: walking allowed parents from any kind must reach a
    # kind that may start a chain (None allowed) without dead-ending.
    rooted: Set[str] = {k for k, allowed in parents.items()
                        if None in allowed}
    changed = True
    while changed:
        changed = False
        for kind, allowed in parents.items():
            if kind in rooted:
                continue
            if any(p in rooted for p in allowed if p is not None):
                rooted.add(kind)
                changed = True
    for kind in sorted(set(parents) - rooted):
        errors.append(
            f"GLS05 control: {kind} cannot reach an episode root "
            f"through its allowed parents — the degrade-episode chain "
            f"is not connected")

    # GLS06: levels change by ±1 only; a degrade/recover emission
    # implies exactly that step.
    for e in edges:
        delta = lv[e["to"]] - lv[e["from"]]
        if abs(delta) > 1:
            errors.append(
                f"GLS06 control: {e['input']} moves the ladder by "
                f"{delta:+d} ({e['from']} -> {e['to']}) — levels "
                f"change by one at a time")
        emits = set(e.get("emits", []))
        if emits & deg_kinds and delta != 1:
            errors.append(
                f"GLS06 control: {e['input']} emits a degrade but "
                f"moves the ladder by {delta:+d} "
                f"({e['from']} -> {e['to']})")
        if emits & rec_kinds and delta != -1:
            errors.append(
                f"GLS06 control: {e['input']} emits a recover but "
                f"moves the ladder by {delta:+d} "
                f"({e['from']} -> {e['to']})")
    return errors
