"""graftlint Layer 3, memory half: compiled-memory profiles and
constraint-coverage of large intermediates.

Two measurements feed the sharding auditor (:mod:`mercury_tpu.lint.
sharding`):

- **Compiled memory profile** — :func:`memory_profile` reads
  ``compiled.memory_analysis()`` (XLA's ``CompiledMemoryStats``) into a
  plain dict of byte counts plus a derived ``peak_estimate_in_bytes``.
  The committed per-plan values act as a *monotone ratchet*: a measured
  profile may not exceed the recorded one by more than
  :data:`DEFAULT_TOLERANCE`. The tolerance exists because the numbers
  come from the **CPU** backend standing in for TPU — buffer assignment
  differs across backends and XLA releases, so the budget catches
  regressions of the "accidentally materialized the gathered score
  table" magnitude (x2..xW), not byte-exact layout shifts. Shrinking
  past the tolerance is a *warning* nudging a ``--regen`` so the
  ratchet tightens.
- **Constraint coverage** — :func:`unconstrained_large_intermediates`
  walks a traced jaxpr and reports every intermediate larger than
  :data:`MIN_CONSTRAINED_BYTES` whose producing equation lives in one of
  the GSPMD-partitioned ``parallel/`` modules but is neither produced by
  nor consumed by a ``sharding_constraint``. Interiors of ``shard_map``
  are exempt: they are manual SPMD — GSPMD propagation never sees them,
  so a constraint there would be meaningless.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.lint.memory")

MIB = 1024 ** 2

#: >1 MiB intermediates in GSPMD-auto regions must carry an explicit
#: with_sharding_constraint (ISSUE 4 invariant).
MIN_CONSTRAINED_BYTES = MIB

#: CPU-estimate tolerance for the per-plan memory ratchet (see module
#: docstring): measured ≤ recorded × (1 + tol) or the audit fails.
DEFAULT_TOLERANCE = 0.25

#: The GSPMD-partitioned modules whose large intermediates must be
#: explicitly constrained. shard_map-interior code (sequence/pipeline
#: bodies) is exempted by context, not by path.
HOT_PARALLEL_MODULES = (
    "parallel/fsdp.py",
    "parallel/tensor.py",
    "parallel/sequence.py",
    "parallel/pipeline.py",
)

#: CompiledMemoryStats fields recorded per plan.
MEMORY_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "alias_size_in_bytes",
    "generated_code_size_in_bytes",
)

#: Fields the ratchet compares (generated code size is recorded for
#: provenance but too noisy across XLA builds to gate on).
COMPARED_FIELDS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
    "peak_estimate_in_bytes",
)


def format_bytes(n: int) -> str:
    """'3.2 MiB' — human-readable byte counts for diff messages."""
    value = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(value) < 1024.0 or unit == "GiB":
            return (f"{int(value)} {unit}" if unit == "B"
                    else f"{value:.1f} {unit}")
        value /= 1024.0
    return f"{n} B"


def memory_profile(compiled) -> Dict[str, Any]:
    """``compiled.memory_analysis()`` as a plain dict of byte counts.

    When the backend provides no memory analysis (older jaxlib / exotic
    backends) the profile degrades to a NAMED ``{"unavailable": reason}``
    entry instead of silently vanishing: the auto-planner must be able to
    distinguish "no data" (plan stays feasible, decision records the gap)
    from "fits the budget". The ratchet (:func:`compare_memory`) treats
    an unavailable profile as no-data, so healthy-jaxlib regens are
    byte-identical to before.
    """
    try:
        stats = compiled.memory_analysis()
    except Exception as exc:
        reason = f"{type(exc).__name__}: {exc}"
        _log.warning("memory_analysis() unavailable on this backend: %s",
                     reason)
        return {"unavailable": reason}
    if stats is None:
        _log.warning("memory_analysis() returned None on this backend")
        return {"unavailable": "memory_analysis() returned None"}
    out: Dict[str, int] = {}
    for name in MEMORY_FIELDS:
        value = getattr(stats, name, None)
        if value is not None:
            out[name] = int(value)
    if {"argument_size_in_bytes", "output_size_in_bytes",
            "temp_size_in_bytes"} <= out.keys():
        # Live-at-peak upper bound: args + outputs + temps, minus buffers
        # aliased away by donation.
        out["peak_estimate_in_bytes"] = (
            out["argument_size_in_bytes"] + out["output_size_in_bytes"]
            + out["temp_size_in_bytes"]
            - out.get("alias_size_in_bytes", 0))
    return out


def compare_memory(plan: str, recorded: Dict[str, Any],
                   measured: Dict[str, Any],
                   tolerance: float = DEFAULT_TOLERANCE,
                   ) -> Tuple[List[str], List[str]]:
    """Monotone ratchet: ``(errors, warnings)`` against the committed
    per-plan profile. Growth past ``tolerance`` is an error; shrinking
    past it is a warning (regenerate so the ratchet tightens). An
    ``unavailable`` profile on either side is no-data: nothing to gate."""
    errors: List[str] = []
    warnings: List[str] = []
    if (not recorded or not measured
            or "unavailable" in recorded or "unavailable" in measured):
        return errors, warnings
    for name in COMPARED_FIELDS:
        want, got = recorded.get(name), measured.get(name)
        if want is None or got is None:
            continue
        if got > want * (1.0 + tolerance):
            errors.append(
                f"  memory[{name}]: {format_bytes(got)} exceeds budget "
                f"{format_bytes(want)} by more than the {tolerance:.0%} "
                "CPU-estimate tolerance — a buffer got bigger")
        elif want and got < want * (1.0 - tolerance):
            warnings.append(
                f"  memory[{name}]: {format_bytes(got)} is under budget "
                f"{format_bytes(want)} by more than {tolerance:.0%} — "
                "regenerate to ratchet the budget down")
    return errors, warnings


# --------------------------------------------------------------------------
# constraint coverage of large intermediates
# --------------------------------------------------------------------------

def _sub_jaxprs(params: Dict[str, Any]) -> Iterator[Any]:
    for value in params.values():
        values = value if isinstance(value, (list, tuple)) else (value,)
        for v in values:
            if hasattr(v, "eqns"):           # Jaxpr
                yield v
            elif hasattr(v, "jaxpr"):        # ClosedJaxpr
                yield v.jaxpr


def iter_eqns_with_context(jaxpr, manual: bool = False,
                           ) -> Iterator[Tuple[Any, bool]]:
    """``(eqn, in_manual_region)`` pairs for every equation, recursing
    into sub-jaxprs. ``in_manual_region`` is True inside any ``shard_map``
    body (including partial-manual ones) — GSPMD does not propagate
    shardings there."""
    if hasattr(jaxpr, "jaxpr"):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn, manual
        sub_manual = manual or eqn.primitive.name == "shard_map"
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns_with_context(sub, sub_manual)


def user_frame(eqn) -> Optional[Tuple[str, int]]:
    """``(file_name, line)`` of the first non-jax frame in the equation's
    traceback, or None. jax-internal frames (site-packages, the jax tree
    itself) lead the raw traceback and are skipped."""
    si = getattr(eqn, "source_info", None)
    tb = getattr(si, "traceback", None)
    frames = getattr(tb, "frames", None)
    if not frames:
        return None
    for frame in frames:
        fname = getattr(frame, "file_name", "") or ""
        norm = fname.replace(os.sep, "/")
        if "site-packages" in norm or "/jax/" in norm \
                or norm.endswith("/jax") or not norm:
            continue
        line = getattr(frame, "start_line",
                       getattr(frame, "line_num", 0)) or 0
        return fname, int(line)
    return None


def _aval_bytes(var) -> int:
    aval = getattr(var, "aval", None)
    size = getattr(aval, "size", None)
    dtype = getattr(aval, "dtype", None)
    if size is None or dtype is None:
        return 0
    try:
        return int(size) * int(dtype.itemsize)
    except Exception:
        return 0


def unconstrained_large_intermediates(
    closed,
    modules: Sequence[str] = HOT_PARALLEL_MODULES,
    min_bytes: int = MIN_CONSTRAINED_BYTES,
) -> List[str]:
    """Messages for every >``min_bytes`` intermediate produced in one of
    ``modules`` (path-suffix match on the producing frame) inside a
    GSPMD-auto region that neither is, nor directly feeds, a
    ``sharding_constraint`` equation."""
    norm_modules = tuple(m.replace(os.sep, "/") for m in modules)

    constrained: set = set()          # vars covered by a constraint
    candidates: List[Tuple[Any, str, int, int]] = []
    for eqn, manual in iter_eqns_with_context(closed):
        name = eqn.primitive.name
        if name == "sharding_constraint":
            for v in list(eqn.invars) + list(eqn.outvars):
                if hasattr(v, "count"):   # Var (Literals are unhashable)
                    constrained.add(v)
            continue
        if manual:
            continue
        # Structural/no-compute primitives never materialize a new buffer
        # worth constraining on their own.
        if name in ("pjit", "closed_call", "custom_vjp_call",
                    "custom_jvp_call", "scan", "while", "cond",
                    "shard_map", "broadcast_in_dim", "squeeze",
                    "reshape", "convert_element_type", "transpose"):
            continue
        frame = user_frame(eqn)
        if frame is None:
            continue
        fname = frame[0].replace(os.sep, "/")
        if not any(fname.endswith(m) for m in norm_modules):
            continue
        for v in eqn.outvars:
            nbytes = _aval_bytes(v)
            if nbytes >= min_bytes:
                candidates.append((v, fname, frame[1], nbytes))
                break  # one report per equation

    out: List[str] = []
    for v, fname, line, nbytes in candidates:
        if v in constrained:
            continue
        aval = v.aval
        eqn_desc = f"{aval.dtype}{list(aval.shape)}"
        short = "/".join(fname.split("/")[-2:])
        out.append(
            f"{short}:{line}: {eqn_desc} intermediate "
            f"({format_bytes(nbytes)}) in a GSPMD-auto region has no "
            "with_sharding_constraint — its layout is whatever "
            "propagation picks")
    return out
