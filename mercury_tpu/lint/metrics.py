"""graftlint Layer M: metric-key registry auditor (pure stdlib).

Every metric tag the training path emits must exist in the central
registry (``mercury_tpu/obs/registry.py::METRIC_KEYS``) and be
documented in the ``docs/API.md`` metric-key glossary — otherwise
dashboards silently accumulate unexplained streams and the glossary
rots. This layer closes the loop statically:

- **error** — a ``category/name`` string literal in the package that is
  not a registered key (typo, or a new metric added without registering
  and documenting it);
- **error** — a registered key with no backticked mention in
  ``docs/API.md`` (registered but undocumented);
- **warning** — a registered key never seen as a literal in the package
  (dead registry entry, or a key built only via f-strings — e.g. the
  ``{train,test}/eval_*`` family, constructed from a split prefix).

**GLM04** applies the same three-way parity contract to control-plane
event kinds: every first-argument literal of a ``*journal*.emit(...)``
call must be registered in ``obs/registry.py::EVENT_KINDS`` and carry a
backticked entry in ``docs/OBSERVABILITY.md``'s kind catalog; a
registered kind never emitted is a warning. Journal-emit first
arguments are *excluded* from the metric-key scan — ``supervisor/…``
event kinds share the slash grammar with metric keys, and the receiver
name (anything containing ``journal``) is what disambiguates the two
planes statically.

Like Layer 1 this never imports the package under lint (the registry is
read by AST ``literal_eval`` of its source), so it runs on CI machines
with no jax installed.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

#: A metric tag: one of the registered categories, a slash, a snake_case
#: name — optionally one more ``/segment`` (the ``host/{min,max,spread}/*``
#: and ``prof/scope_frac/*`` families are two levels deep). Anything
#: matching this shape in package source is treated as an emitted metric
#: key and checked against the registry.
KEY_RE = re.compile(
    r"^(train|test|sampler|sampler_dist|perf|time|data|obs|anomaly|host"
    r"|prof|scorer|threads|lint|fault|supervisor|checkpoint|plan)"
    r"/[a-z0-9_]+(/[a-z0-9_]+)?$")

#: Backticked tokens in the docs, brace families included
#: (``sampler/table_age_{min,mean,max}``). No newlines inside a token,
#: and fenced ``` blocks are stripped first — a code fence would pair a
#: stray backtick with the rest of the document.
_DOC_TOKEN_RE = re.compile(r"`([^`\n]+)`")
_FENCE_RE = re.compile(r"^```.*?^```[^\S\n]*$", re.M | re.S)
_BRACE_RE = re.compile(r"\{([^{}]+)\}")

#: A control-plane event kind: exactly ``subsystem/name`` (obs/events.py
#: schema). Only literals at journal-emit call sites are judged against
#: this, so the broad shape cannot false-positive on paths or metrics.
EVENT_KIND_RE = re.compile(r"^[a-z0-9_]+/[a-z0-9_]+$")

#: Files whose key literals are definitional, not emissions: the
#: registry itself and Layer S's control-plane model (``control.py``
#: names journal kinds in its parent/rule tables, never emits them).
_SKIP_FILES = frozenset({"registry.py", "control.py", "modelcheck.py"})


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _default_registry_path() -> str:
    return os.path.join(_repo_root(), "mercury_tpu", "obs", "registry.py")


def _default_docs_path() -> str:
    return os.path.join(_repo_root(), "docs", "API.md")


def _default_event_docs_path() -> str:
    return os.path.join(_repo_root(), "docs", "OBSERVABILITY.md")


def _load_literal(path: str, name: str) -> Dict[str, str]:
    """A module-level pure-literal dict from SOURCE — no import of the
    package (and thus no jax) is needed; fails loudly if missing or not
    a literal."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            targets = [node.target.id]
        if name in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError(f"no {name} literal found in {path}")


def load_registry(path: str) -> Dict[str, str]:
    """``METRIC_KEYS`` from the registry module's source."""
    return _load_literal(path, "METRIC_KEYS")


def load_event_registry(path: str) -> Dict[str, str]:
    """``EVENT_KINDS`` (the control-plane event-kind registry) from the
    registry module's source. A registry module without one is treated
    as an empty registry (journal emissions against it are then GLM04
    errors), so metric-only registries stay valid."""
    try:
        return _load_literal(path, "EVENT_KINDS")
    except ValueError:
        return {}


def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def _receiver_name(func: ast.AST) -> str:
    """Dotted receiver of an ``x.y.emit`` attribute chain, best-effort
    (``self._journal.emit`` -> ``self._journal``)."""
    parts: List[str] = []
    node = func
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _journal_emit_args(tree: ast.AST) -> Dict[int, ast.Constant]:
    """``id(node) -> node`` for every first-positional-argument string
    Constant of a journal-emission call — the static signature every
    producer call site follows: the called attribute contains ``emit``
    and the full dotted callable name contains ``journal``
    (``self._journal.emit(...)``, ``journal.emit(...)``, or a wrapper
    like ``self._journal_emit(...)``)."""
    out: Dict[int, ast.Constant] = {}
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and "emit" in node.func.attr
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            continue
        if "journal" in _receiver_name(node.func).lower():
            out[id(node.args[0])] = node.args[0]
    return out


def _kind_compare_args(tree: ast.AST) -> Dict[int, ast.Constant]:
    """String Constants compared against a ``kind`` expression
    (``e.get("kind") == "supervisor/degrade"``, ``kind != "fault/fired"``)
    — the *consumer*-side dual of :func:`_journal_emit_args`: event-kind
    filters in journal readers, not metric emissions."""
    def mentions_kind(node: ast.AST) -> bool:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and "kind" in sub.id.lower():
                return True
            if isinstance(sub, ast.Attribute) and "kind" in sub.attr.lower():
                return True
            if (isinstance(sub, ast.Constant)
                    and isinstance(sub.value, str) and sub.value == "kind"):
                return True
        return False

    out: Dict[int, ast.Constant] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Compare):
            continue
        operands = [node.left] + list(node.comparators)
        consts = [o for o in operands
                  if isinstance(o, ast.Constant) and isinstance(o.value, str)]
        if consts and any(mentions_kind(o) for o in operands
                          if not isinstance(o, ast.Constant)):
            out.update({id(c): c for c in consts})
    return out


def emitted_keys(paths: List[str]) -> Dict[str, List[Tuple[str, int]]]:
    """``key -> [(file, line), ...]`` for every plain string literal in
    ``paths`` matching :data:`KEY_RE`. Constants inside f-strings are
    skipped: a JoinedStr fragment is a key *prefix*, not a key, and
    judging it would false-positive on every dynamic tag. Journal-emit
    first arguments are skipped too — those are event kinds (GLM04's
    plane), not metric keys, even when the subsystem prefix collides
    with a metric category — as are kind-comparison literals in journal
    consumers (the same plane, read side)."""
    found: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_py_files(paths):
        if os.path.basename(path) in _SKIP_FILES:
            continue
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            continue  # Layer 1 reports unparseable files
        skip = {id(c) for node in ast.walk(tree)
                if isinstance(node, ast.JoinedStr)
                for c in ast.walk(node)}
        skip |= set(_journal_emit_args(tree))
        skip |= set(_kind_compare_args(tree))
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in skip
                    and KEY_RE.match(node.value)):
                found.setdefault(node.value, []).append(
                    (path, node.lineno))
    return found


def emitted_event_kinds(paths: List[str]
                        ) -> Dict[str, List[Tuple[str, int]]]:
    """``kind -> [(file, line), ...]`` for every journal-emit first
    argument in ``paths`` (the GLM04 emission census)."""
    found: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_py_files(paths):
        if os.path.basename(path) in _SKIP_FILES:
            continue
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            continue
        for const in _journal_emit_args(tree).values():
            found.setdefault(const.value, []).append(
                (path, const.lineno))
    return found


def _documented_tokens(docs_path: str, pattern) -> Set[str]:
    """Backticked tokens in the docs file matching ``pattern``, with
    ``{a,b,c}`` families expanded."""
    with open(docs_path) as f:
        text = _FENCE_RE.sub("", f.read())
    keys: Set[str] = set()
    for token in _DOC_TOKEN_RE.findall(text):
        m = _BRACE_RE.search(token)
        variants = ([_BRACE_RE.sub(alt, token, count=1)
                     for alt in m.group(1).split(",")]
                    if m else [token])
        keys.update(v for v in variants if pattern.match(v))
    return keys


def documented_keys(docs_path: str) -> Set[str]:
    """Metric keys mentioned in backticks anywhere in the docs file."""
    return _documented_tokens(docs_path, KEY_RE)


def documented_event_kinds(docs_path: str) -> Set[str]:
    """Event kinds mentioned in backticks in the event docs file."""
    return _documented_tokens(docs_path, EVENT_KIND_RE)


def run_metrics_check(paths: List[str] = None,
                      registry_path: str = None,
                      docs_path: str = None,
                      event_docs_path: str = None
                      ) -> Tuple[List[str], List[str]]:
    """The Layer M audit; returns ``(errors, warnings)`` of formatted
    finding lines (the Layer 2/3 CLI contract)."""
    registry_path = registry_path or _default_registry_path()
    docs_path = docs_path or _default_docs_path()
    event_docs_path = event_docs_path or _default_event_docs_path()
    if not paths:
        paths = [os.path.join(_repo_root(), "mercury_tpu")]
    registry = load_registry(registry_path)
    emitted = emitted_keys(paths)
    documented = documented_keys(docs_path)

    errors: List[str] = []
    warnings: List[str] = []
    root = _repo_root()
    for key in sorted(emitted):
        if key not in registry:
            f, line = emitted[key][0]
            errors.append(
                f"{os.path.relpath(f, root)}:{line}: GLM01 metric key "
                f"{key!r} is not in obs/registry.py::METRIC_KEYS "
                f"({len(emitted[key])} use(s)) — register and document "
                "it, or fix the typo")
    for key in sorted(registry):
        if key not in documented:
            errors.append(
                f"{os.path.relpath(docs_path, root)}: GLM02 registered "
                f"metric key {key!r} has no backticked entry in the "
                "docs — add it to the metric-key glossary")
        if key not in emitted:
            warnings.append(
                f"GLM03 registered metric key {key!r} never appears as "
                "a literal in the package (f-string-built or dead "
                "entry)")

    # GLM04: event-kind parity — emitted ⊆ EVENT_KINDS ⊆ documented.
    kinds = load_event_registry(registry_path)
    emitted_kinds = emitted_event_kinds(paths)
    documented_kinds = documented_event_kinds(event_docs_path)
    for kind in sorted(emitted_kinds):
        if kind not in kinds:
            f, line = emitted_kinds[kind][0]
            errors.append(
                f"{os.path.relpath(f, root)}:{line}: GLM04 event kind "
                f"{kind!r} is not in obs/registry.py::EVENT_KINDS "
                f"({len(emitted_kinds[kind])} emit(s)) — register and "
                "document it, or fix the typo")
    for kind in sorted(kinds):
        if kind not in documented_kinds:
            errors.append(
                f"{os.path.relpath(event_docs_path, root)}: GLM04 "
                f"registered event kind {kind!r} has no backticked "
                "entry in the event-kind catalog — add it")
        if kind not in emitted_kinds:
            warnings.append(
                f"GLM04 registered event kind {kind!r} is never "
                "emitted by a journal call site (dead registry entry)")
    return errors, warnings
