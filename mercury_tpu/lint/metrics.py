"""graftlint Layer M: metric-key registry auditor (pure stdlib).

Every metric tag the training path emits must exist in the central
registry (``mercury_tpu/obs/registry.py::METRIC_KEYS``) and be
documented in the ``docs/API.md`` metric-key glossary — otherwise
dashboards silently accumulate unexplained streams and the glossary
rots. This layer closes the loop statically:

- **error** — a ``category/name`` string literal in the package that is
  not a registered key (typo, or a new metric added without registering
  and documenting it);
- **error** — a registered key with no backticked mention in
  ``docs/API.md`` (registered but undocumented);
- **warning** — a registered key never seen as a literal in the package
  (dead registry entry, or a key built only via f-strings — e.g. the
  ``{train,test}/eval_*`` family, constructed from a split prefix).

Like Layer 1 this never imports the package under lint (the registry is
read by AST ``literal_eval`` of its source), so it runs on CI machines
with no jax installed.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Set, Tuple

#: A metric tag: one of the registered categories, a slash, a snake_case
#: name — optionally one more ``/segment`` (the ``host/{min,max,spread}/*``
#: and ``prof/scope_frac/*`` families are two levels deep). Anything
#: matching this shape in package source is treated as an emitted metric
#: key and checked against the registry.
KEY_RE = re.compile(
    r"^(train|test|sampler|sampler_dist|perf|time|data|obs|anomaly|host"
    r"|prof|scorer|threads|lint|fault|supervisor|checkpoint)"
    r"/[a-z0-9_]+(/[a-z0-9_]+)?$")

#: Backticked tokens in the docs, brace families included
#: (``sampler/table_age_{min,mean,max}``). No newlines inside a token,
#: and fenced ``` blocks are stripped first — a code fence would pair a
#: stray backtick with the rest of the document.
_DOC_TOKEN_RE = re.compile(r"`([^`\n]+)`")
_FENCE_RE = re.compile(r"^```.*?^```[^\S\n]*$", re.M | re.S)
_BRACE_RE = re.compile(r"\{([^{}]+)\}")

#: Files whose key literals are definitional, not emissions.
_SKIP_FILES = frozenset({"registry.py"})


def _repo_root() -> str:
    return os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _default_registry_path() -> str:
    return os.path.join(_repo_root(), "mercury_tpu", "obs", "registry.py")


def _default_docs_path() -> str:
    return os.path.join(_repo_root(), "docs", "API.md")


def load_registry(path: str) -> Dict[str, str]:
    """``METRIC_KEYS`` from the registry module's SOURCE — the dict is a
    pure literal (enforced here by failing loudly if it is not), so no
    import of the package (and thus no jax) is needed."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets
                       if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name):
            targets = [node.target.id]
        if "METRIC_KEYS" in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise ValueError(f"no METRIC_KEYS literal found in {path}")


def _iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            out.extend(os.path.join(dirpath, f)
                       for f in sorted(filenames) if f.endswith(".py"))
    return out


def emitted_keys(paths: List[str]) -> Dict[str, List[Tuple[str, int]]]:
    """``key -> [(file, line), ...]`` for every plain string literal in
    ``paths`` matching :data:`KEY_RE`. Constants inside f-strings are
    skipped: a JoinedStr fragment is a key *prefix*, not a key, and
    judging it would false-positive on every dynamic tag."""
    found: Dict[str, List[Tuple[str, int]]] = {}
    for path in _iter_py_files(paths):
        if os.path.basename(path) in _SKIP_FILES:
            continue
        try:
            with open(path) as f:
                tree = ast.parse(f.read(), filename=path)
        except SyntaxError:
            continue  # Layer 1 reports unparseable files
        skip = {id(c) for node in ast.walk(tree)
                if isinstance(node, ast.JoinedStr)
                for c in ast.walk(node)}
        for node in ast.walk(tree):
            if (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)
                    and id(node) not in skip
                    and KEY_RE.match(node.value)):
                found.setdefault(node.value, []).append(
                    (path, node.lineno))
    return found


def documented_keys(docs_path: str) -> Set[str]:
    """Keys mentioned in backticks anywhere in the docs file, with
    ``{a,b,c}`` families expanded."""
    with open(docs_path) as f:
        text = _FENCE_RE.sub("", f.read())
    keys: Set[str] = set()
    for token in _DOC_TOKEN_RE.findall(text):
        m = _BRACE_RE.search(token)
        variants = ([_BRACE_RE.sub(alt, token, count=1)
                     for alt in m.group(1).split(",")]
                    if m else [token])
        keys.update(v for v in variants if KEY_RE.match(v))
    return keys


def run_metrics_check(paths: List[str] = None,
                      registry_path: str = None,
                      docs_path: str = None
                      ) -> Tuple[List[str], List[str]]:
    """The Layer M audit; returns ``(errors, warnings)`` of formatted
    finding lines (the Layer 2/3 CLI contract)."""
    registry_path = registry_path or _default_registry_path()
    docs_path = docs_path or _default_docs_path()
    if not paths:
        paths = [os.path.join(_repo_root(), "mercury_tpu")]
    registry = load_registry(registry_path)
    emitted = emitted_keys(paths)
    documented = documented_keys(docs_path)

    errors: List[str] = []
    warnings: List[str] = []
    root = _repo_root()
    for key in sorted(emitted):
        if key not in registry:
            f, line = emitted[key][0]
            errors.append(
                f"{os.path.relpath(f, root)}:{line}: GLM01 metric key "
                f"{key!r} is not in obs/registry.py::METRIC_KEYS "
                f"({len(emitted[key])} use(s)) — register and document "
                "it, or fix the typo")
    for key in sorted(registry):
        if key not in documented:
            errors.append(
                f"{os.path.relpath(docs_path, root)}: GLM02 registered "
                f"metric key {key!r} has no backticked entry in the "
                "docs — add it to the metric-key glossary")
        if key not in emitted:
            warnings.append(
                f"GLM03 registered metric key {key!r} never appears as "
                "a literal in the package (f-string-built or dead "
                "entry)")
    return errors, warnings
