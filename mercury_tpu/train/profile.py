"""Per-segment timing + profiler hooks.

Capability parity with the reference's manual wall-clock instrumentation
(``pytorch_collab.py:129-178``): the five named segments — ``step_time``
(whole step), ``ff_time`` (train forward), ``bp_time`` (backward),
``is_time`` (importance scoring), ``sync_time`` (gradient allreduce) —
printed every 100 steps. Known reference defect (not replicated): its
``is_time`` brackets a commented-out line so the logged value is ~0 while
the real scoring cost lands elsewhere (``:139-142``, SURVEY.md §5).

A fused XLA step has no host-visible internal boundaries, so segment
attribution here times **separately-jitted sub-programs** with
device fences — comparable numbers, honestly labeled as estimates. The
parts-vs-fused relationship is DATA, not an invariant: segment overlap
inside the fused program pushes the sum above the whole, while fused-only
work no segment isolates (augmentation, gathers, the draw) pushes it
below — the measured ratio per platform is recorded by
``benchmarks/profile_validation.py``.

For real kernel-level traces use :func:`trace` (``jax.profiler`` wrapper),
the TPU-native answer to the reference's ``time.time()`` pairs.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict

import jax
import jax.numpy as jnp
from jax import lax
from mercury_tpu.compat import shard_map
from jax.sharding import PartitionSpec as P

from mercury_tpu.sampling.importance import per_sample_loss, reweighted_loss


def _timeit(fn: Callable[[], jax.Array], iters: int) -> float:
    """Median-of-iters wall time of ``fn`` with device fences.

    The fence is a device→host fetch (``np.asarray``), not
    ``block_until_ready`` — the latter has been observed returning early
    on the tunneled-chip platform."""
    import numpy as np

    np.asarray(fn())  # compile / warm
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        np.asarray(fn())
        times.append(time.perf_counter() - t0)
    times.sort()
    return times[len(times) // 2]


def timing_breakdown(trainer, iters: int = 10) -> Dict[str, float]:
    """Estimate the reference's five timing segments for ``trainer``'s
    config (seconds, median of ``iters``).

    Segments: ``is`` (scoring forward over the candidate pool), ``ff``
    (train forward on the selected batch), ``bp`` (forward+backward minus
    ``ff``), ``sync`` (gradient-pytree pmean over the mesh), ``step`` (the
    real fused step). Keys mirror ``pytorch_collab.py:170-178``.
    """
    cfg = trainer.config
    ds = trainer.dataset
    model = trainer.model
    mesh = trainer.mesh
    axis = cfg.mesh_axis
    params = trainer.state.params
    batch_stats = trainer.state.batch_stats

    pool = ds.gather_batch(jnp.arange(cfg.candidate_pool_size) % ds.n_train)
    batch = ds.gather_batch(jnp.arange(cfg.batch_size) % ds.n_train)

    def _fwd(images, labels):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            logits, _ = model.apply(variables, images, train=True,
                                    mutable=["batch_stats"])
        else:
            logits = model.apply(variables, images, train=True)
        return per_sample_loss(logits, labels)

    # BN may psum over the mesh axis — run segments under a trivial
    # shard_map so the axis is bound (replicated inputs, same math).
    # Each sub-program is wrapped ONCE: a fresh jit(shard_map(...)) per
    # timed call would retrace every iteration and the "segment time"
    # would measure tracing, not compute (the bug behind the round-4
    # ff>fused artifact rows).
    def _wrap(fn):
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=P(), out_specs=P(),
                                 check_vma=False))

    def score_fn(images, labels):
        return jnp.sum(_fwd(images, labels))

    def train_fwd_fn(images, labels):
        return jnp.sum(_fwd(images, labels))

    def fwd_bwd_fn(images, labels):
        def loss_fn(p):
            variables = {"params": p}
            if batch_stats:
                variables["batch_stats"] = batch_stats
                logits, _ = model.apply(variables, images, train=True,
                                        mutable=["batch_stats"])
            else:
                logits = model.apply(variables, images, train=True)
            losses = per_sample_loss(logits, labels)
            return reweighted_loss(losses, jnp.ones_like(losses))

        grads = jax.grad(loss_fn)(params)
        return jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.sum(b), grads, jnp.zeros(())
        )

    def sync_fn():
        meaned = jax.tree_util.tree_map(lambda x: lax.pmean(x, axis), params)
        return jax.tree_util.tree_reduce(
            lambda a, b: a + jnp.sum(b), meaned, jnp.zeros(())
        )

    score_j = _wrap(score_fn)
    train_fwd_j = _wrap(train_fwd_fn)
    fwd_bwd_j = _wrap(fwd_bwd_fn)
    sync_j = _wrap(sync_fn)
    is_t = _timeit(lambda: score_j(pool.image, pool.label), iters)
    ff_t = _timeit(lambda: train_fwd_j(batch.image, batch.label), iters)
    fb_t = _timeit(lambda: fwd_bwd_j(batch.image, batch.label), iters)
    sync_t = _timeit(lambda: sync_j(), iters)

    def fused():
        state, metrics = trainer.train_step(
            trainer.state, trainer._step_x, trainer._step_y, ds.shard_indices
        )
        trainer.state = state
        return metrics["train/loss"]

    step_t = _timeit(fused, iters)

    return {
        "step_time": step_t,
        "ff_time": ff_t,
        "bp_time": max(fb_t - ff_t, 0.0),
        # Raw forward+backward median: bp_time is fb−ff clamped at 0, so
        # a contended host can zero it (two noisy medians); fb_time keeps
        # the degenerate case diagnosable in recorded artifacts.
        "fb_time": fb_t,
        "is_time": is_t,
        "sync_time": sync_t,
    }


@contextlib.contextmanager
def trace(log_dir: str):
    """``jax.profiler`` trace context — kernel-level TPU traces viewable in
    TensorBoard/Perfetto; the TPU-native replacement for host
    ``time.time()`` bracketing."""
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
