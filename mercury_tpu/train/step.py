"""The fused SPMD Mercury train step.

One jitted ``shard_map`` program per step does everything the reference's hot
loop does across Python/gloo boundaries (``pytorch_collab.py:119-199`` —
SURVEY.md §3.2): pull presample candidates, score them (10 inference
forwards in the reference — here **one batched forward** over the whole
pool), EMA-smooth, draw the train batch with replacement, compute the
unbiased reweighted loss, backprop, allreduce gradients, and apply the
optimizer — with the collectives (gradient pmean ≡ ``average_gradients``
``:236-249``, importance-stat psum = north-star extension) fused in-graph by
XLA. The compute/communication overlap the reference only gestures at in
commented-out thread code (``:154-156``) falls out for free: XLA schedules
the ICI collectives asynchronously against independent compute.

Per-worker divergence (the whole point of Mercury on non-IID data: each
worker scores its *own* Dirichlet shard) lives on the mesh's data axis:
shard index rows, presample streams, EMAs, and RNG keys are ``[W]``-stacked
and sharded; params/optimizer state are replicated.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mercury_tpu.config import TrainConfig
from mercury_tpu.data.pipeline import ShardStream, augment_batch, next_pool, normalize_images
from mercury_tpu.obs.diagnostics import (
    clip_fraction,
    ema_drift,
    ess_fraction,
    global_grad_norm,
    table_age_summary,
)
from mercury_tpu.obs.sampler_health import (
    SCORE_HIST_HI,
    SCORE_HIST_LO,
    WEIGHT_HIST_HI,
    WEIGHT_HIST_LO,
    hist_keys,
    log_bin_histogram,
)
from mercury_tpu.parallel.collectives import allreduce_mean_tree
from mercury_tpu.sampling.importance import (
    EMAState,
    draw_with_replacement,
    ema_update,
    importance_probs,
    per_sample_grad_norm_bound,
    per_sample_loss,
    pool_mean,
    reweighted_loss,
    select_from_pool,
)
from mercury_tpu.sampling.scoretable import (
    ScoreTableState,
    advance_cursor,
    decay_scores,
    refresh_window,
    scatter_mean,
    table_draw_inverse_cdf,
    table_probs,
    table_refresh_draw,
)
from mercury_tpu.train.state import (
    CachedPool,
    MercuryState,
    PendingBatch,
    PendingSelection,
)

from mercury_tpu.compat import (MODERN_JAX, axis_size, donate_argnums,
                                shard_map)


def _state_specs(
    axis: str, has_groupwise: bool = False, has_pending: bool = False,
    zero_sharding: bool = False, has_cached_pool: bool = False,
    has_scoretable: bool = False, has_pending_sel: bool = False,
    has_sel_counts: bool = False,
) -> MercuryState:
    """PartitionSpec pytree-prefix for :class:`MercuryState`: model state
    replicated, per-worker sampler state sharded along the data axis;
    optimizer state sharded too under ZeRO-1 (each worker owns its chunk's
    moments)."""
    return MercuryState(
        step=P(),
        params=P(),
        batch_stats=P(),
        opt_state=P(axis) if zero_sharding else P(),
        ema=EMAState(value=P(axis), count=P(axis)),
        stream=ShardStream(perm=P(axis), cursor=P(axis)),
        rng=P(axis),
        groupwise=P(axis) if has_groupwise else None,
        pending=P(axis) if has_pending else None,
        cached_pool=P(axis) if has_cached_pool else None,
        scoretable=P(axis) if has_scoretable else None,
        pending_sel=P(axis) if has_pending_sel else None,
        sel_counts=P(axis) if has_sel_counts else None,
    )


def mercury_state_out_shardings(
    mesh: Mesh, axis: str, params_sh, opt_sh,
    has_groupwise: bool = False, has_pending: bool = False,
    has_cached_pool: bool = False, has_scoretable: bool = False,
    has_pending_sel: bool = False, has_sel_counts: bool = False,
) -> Tuple[MercuryState, Any]:
    """Output shardings pinning the post-step state layout under partial-
    auto meshes (dp×tp): without this, GSPMD is free to re-replicate the
    tensor-parallel params on every step's output, silently discarding the
    TP memory/compute split. ``params_sh``/``opt_sh`` are the committed
    input sharding trees; everything else follows :func:`_state_specs`."""
    from jax.sharding import NamedSharding

    def n(spec):
        return NamedSharding(mesh, spec)

    state_sh = MercuryState(
        step=n(P()),
        params=params_sh,
        batch_stats=n(P()),
        opt_state=opt_sh,
        ema=EMAState(value=n(P(axis)), count=n(P(axis))),
        stream=ShardStream(perm=n(P(axis)), cursor=n(P(axis))),
        # Legacy jax rejects a tiled out_sharding on a PRNG key array
        # under a partial-manual mesh (the hidden [..., 2] payload dim is
        # missing from the tile assignment at validation). Replicating the
        # tiny [W]-key leaf sidesteps the bug; shard_map re-slices it per
        # worker on the next step's entry either way.
        rng=n(P(axis)) if MODERN_JAX else n(P()),
        groupwise=n(P(axis)) if has_groupwise else None,
        pending=n(P(axis)) if has_pending else None,
        cached_pool=n(P(axis)) if has_cached_pool else None,
        scoretable=n(P(axis)) if has_scoretable else None,
        # Raw uint32 key data (train/state.py PendingSelection) — no PRNG
        # key leaf, so the tiled sharding is safe on legacy jax too.
        pending_sel=n(P(axis)) if has_pending_sel else None,
        sel_counts=n(P(axis)) if has_sel_counts else None,
    )
    return state_sh, n(P())


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    config: TrainConfig,
    mesh: Mesh,
    mean: np.ndarray,
    std: np.ndarray,
    scan_steps: int = 1,
    state_out_shardings=None,
    scoring_model=None,
    io_constraints: bool = True,
) -> Callable[..., Tuple[MercuryState, Dict[str, jax.Array]]]:
    """Build the jitted train step.

    Returns ``step_fn(state, x_train, y_train, shard_indices) →
    (new_state, metrics)`` where ``x_train``/``y_train`` are the full
    device-resident train arrays (replicated) and ``shard_indices`` is the
    ``[W, L]`` per-worker index matrix (sharded over the data axis).

    With ``scan_steps > 1`` the returned function advances ``scan_steps``
    steps per call — the step body wrapped in ``lax.scan`` inside the same
    ``shard_map`` program, so one host dispatch covers the whole chunk and
    each metric comes back as a ``[scan_steps]`` array.

    ``scoring_model`` (optional) is a second module with identical params
    structure but a different compute dtype (``config.scoring_dtype``);
    when given, the candidate-scoring forward runs through it instead of
    ``model`` — the IS reweight divides by the realized probabilities, so
    a lower-precision scorer reranks candidates without biasing the loss.

    SHARDING CONTRACT (enforced by graftlint Layer 3, ``lint/
    sharding.py`` — see docs/LINT.md): the step's inputs are pinned with
    ``with_sharding_constraint`` before they enter the shard_map —
    ``x_train``/``y_train`` to the data spec (``P(axis)`` when
    ``data_placement`` shards them, else replicated ``P()``) and
    ``shard_indices`` to ``P(axis)`` — so a caller handing in foreign
    layouts pays one visible reshard here instead of GSPMD quietly
    rewriting layouts inside the step. ``io_constraints=False`` drops
    the pins (the per-plan ``sharding_constraints`` budget in
    ``lint/shard_budgets.json`` then fails — that is the point).
    """
    axis = config.mesh_axis
    use_is = config.use_importance_sampling
    pool_size = config.candidate_pool_size if use_is else config.batch_size
    batch_size = config.batch_size
    stat_axis = axis if (use_is and config.sync_importance_stats) else None
    # In-graph telemetry is gated at TRACE time: with telemetry=False every
    # diagnostic below is simply never traced, so the compiled program is
    # identical to the seed step (no reliance on XLA DCE — verified by
    # benchmarks/telemetry_overhead.py comparing jaxprs).
    telemetry = bool(config.telemetry)

    # Mesh axes beyond the data axis (e.g. the "model" axis of a dp×tp
    # mesh) are left to GSPMD: the step is manual-SPMD over `axis` only,
    # and XLA partitions the forwards/backwards over the auto axes per the
    # params' committed shardings (transformer_tp_shardings). This is how
    # the flagship IS algorithm composes with tensor parallelism — the
    # scoring forward, draw, reweighted backward, and stat psum all run
    # TP-sharded without any change to the body below.
    auto_axes = [a for a in mesh.axis_names if a != axis]
    tp_active = any(mesh.shape[a] > 1 for a in auto_axes)
    if tp_active and config.zero_sharding:
        raise ValueError(
            "zero_sharding flattens params to a vector, which would force "
            "an all-gather of the sharded params; use fsdp_parallel or "
            "plain allreduce when a second mesh axis shards the params"
        )
    # int8 wire compression composes with TP/FSDP via the per-leaf path:
    # the flattened collective would force an all-gather of the sharded
    # leaves, so under an active auto axis each leaf is compressed in its
    # natural shape, wire-chunked along a dim the auto axes don't claim
    # (parallel/collectives.py compressed_pmean_tree_sharded — closes the
    # round-3 int8×TP rejection).
    sharded_param_specs = None
    if state_out_shardings is not None:
        sharded_param_specs = jax.tree_util.tree_map(
            lambda s: s.spec, state_out_shardings[0].params
        )

    use_pallas = config.use_pallas
    if use_pallas is None:  # auto: Mosaic kernels on real TPU only
        from mercury_tpu.ops import on_tpu

        use_pallas = on_tpu()
    if use_pallas and config.label_smoothing != 0.0:
        raise ValueError("use_pallas requires label_smoothing == 0")
    if config.sampler not in ("pool", "groupwise", "scoretable"):
        raise ValueError(f"unknown sampler {config.sampler!r}")
    if config.grad_compression not in ("none", "stochastic", "int8"):
        raise ValueError(f"unknown grad_compression {config.grad_compression!r}")
    compress_grads = config.grad_compression == "stochastic"
    int8_allreduce = config.grad_compression == "int8"
    if tp_active and int8_allreduce and sharded_param_specs is None:
        raise ValueError(
            "grad_compression='int8' under an active auto mesh axis needs "
            "state_out_shardings (per-leaf PartitionSpecs): without them "
            "the wire chunker picks the largest dim, which may be the "
            "GSPMD-sharded one — silently forcing the all-gather the "
            "per-leaf path exists to avoid; pass state_out_shardings "
            "(Trainer does) or drop grad_compression"
        )
    use_groupwise = use_is and config.sampler == "groupwise"
    use_scoretable = use_is and config.sampler == "scoretable"
    pipelined = use_is and config.pipelined_scoring
    zero = config.zero_sharding
    if pipelined and config.sampler != "pool":
        # Measured justification for this cut (round-3 ladder,
        # BASELINE.md): pipelined overlap recovered ~2% on chip even for
        # the pool sampler — the scoring cost is FLOPs, not exposed
        # latency — so a groupwise/scoretable pipeline's ceiling is the
        # same ~2%, and those samplers already shrink the scoring cost.
        raise ValueError(
            "pipelined_scoring requires sampler='pool', got "
            f"{config.sampler!r}"
        )
    cadence = int(config.score_refresh_every)
    if cadence < 1:
        raise ValueError(
            f"score_refresh_every must be >= 1, got {cadence}"
        )
    use_cadence = use_is and cadence > 1
    if use_cadence and config.sampler != "pool":
        raise ValueError(
            "score_refresh_every > 1 requires sampler='pool' (the "
            f"{config.sampler!r} sampler already persists scores across "
            "steps)"
        )
    if use_cadence and pipelined:
        raise ValueError(
            "score_refresh_every > 1 does not compose with "
            "pipelined_scoring: cadence already removes the per-step "
            "scoring forward the pipeline overlaps"
        )
    refresh_size = int(config.refresh_size)
    if use_scoretable:
        if refresh_size < 1:
            raise ValueError(
                f"refresh_size must be >= 1, got {refresh_size}"
            )
        if not 0.0 <= config.table_decay <= 1.0:
            raise ValueError(
                f"table_decay must be in [0, 1], got {config.table_decay}"
            )
    if config.scoring_dtype is not None and not use_is:
        raise ValueError(
            "scoring_dtype only affects the candidate-scoring forward; "
            "set use_importance_sampling=True (or drop scoring_dtype)"
        )
    if config.refresh_mode not in ("sync", "async"):
        raise ValueError(f"unknown refresh_mode {config.refresh_mode!r}")
    # Async refresh: the round-robin scoring forward moves OFF the step and
    # onto the host scorer fleet (sampling/scorer_fleet.py) — the traced
    # branches below simply omit it, so the compiled hot program carries
    # zero scoring FLOPs/collectives (the graftlint `async` plan budgets
    # pin this down).
    async_refresh = use_scoretable and config.refresh_mode == "async"
    if config.refresh_mode == "async" and not use_scoretable:
        raise ValueError(
            "refresh_mode='async' requires sampler='scoretable' with "
            "use_importance_sampling=True (the scorer fleet refreshes the "
            "persistent score table; the pool/groupwise samplers have no "
            f"table to stream into) — got sampler={config.sampler!r}, "
            f"use_importance_sampling={use_is}"
        )
    if async_refresh:
        if int(config.scorer_workers) < 1:
            raise ValueError(
                f"scorer_workers must be >= 1, got {config.scorer_workers}"
            )
        if int(config.snapshot_every) < 1:
            raise ValueError(
                f"snapshot_every must be >= 1, got {config.snapshot_every}"
            )
        if float(config.scorer_throttle_s) < 0:
            raise ValueError(
                "scorer_throttle_s must be >= 0, got "
                f"{config.scorer_throttle_s}"
            )
    if config.scorer_backend not in ("host", "device"):
        raise ValueError(
            "scorer_backend must be 'host' or 'device', got "
            f"{config.scorer_backend!r}"
        )
    if not async_refresh:
        # Backend/tenancy knobs only mean something under the async
        # scorer — a silently-ignored scorer_backend='device' on a sync
        # run would read as the device scorer being in play.
        if config.scorer_backend != "host":
            raise ValueError(
                "scorer_backend='device' requires refresh_mode='async' "
                "with sampler='scoretable' (the device scorer program "
                "feeds the async chunk queue; the sync path scores "
                "in-graph) — got refresh_mode="
                f"{config.refresh_mode!r}, sampler={config.sampler!r}"
            )
        if int(config.scorer_tenants) != 1:
            raise ValueError(
                "scorer_tenants requires refresh_mode='async' with "
                "sampler='scoretable' (tenancy is a property of the "
                f"scorer service) — got scorer_tenants="
                f"{config.scorer_tenants}"
            )

    if config.importance_score not in ("loss", "grad_norm"):
        raise ValueError(
            f"unknown importance_score {config.importance_score!r}"
        )
    # Selection-count ledger (obs/sampler_health.py): rides alongside the
    # scoretable, trace-gated with the rest of the telemetry — with
    # telemetry=False the state carries no ledger and the program is the
    # seed's, byte-identical (Layer-2/3 digest-enforced).
    use_ledger = use_scoretable and telemetry
    probe_every = int(config.variance_probe_every)
    if probe_every < 0:
        raise ValueError(
            f"variance_probe_every must be >= 0, got {probe_every}"
        )
    # Grad-variance probe (sampler_dist/var_ratio): one extra
    # scoring-model pass over the trained microbatch every probe_every
    # steps. Trace-gated like the ledger; meaningless without IS weights.
    use_probe = telemetry and probe_every > 0 and use_is
    if use_probe and scan_steps > 1:
        raise ValueError(
            "variance_probe_every > 0 requires scan_steps == 1: scanned "
            "chunks mean their metrics, which would blend the probe's "
            "-1.0 off-step sentinel into the ratio"
        )
    if config.data_placement not in ("replicated", "sharded", "host_stream"):
        raise ValueError(
            f"unknown data_placement {config.data_placement!r}"
        )
    # "sharded": x_train/y_train arrive as [W, L, ...] per-worker shard
    # rows sharded P(axis) — each device holds only its own worker's
    # samples, and gathers are shard-local (slots index the row directly).
    data_sharded = config.data_placement == "sharded"
    # "host_stream": the pixel arrays never enter the graph. The step's
    # second input is the [W, S, ...] uint8 rows the host pipeline
    # pre-gathered for THIS step (selected `prefetch_depth` steps ago by
    # the step itself), and the step emits the NEXT selection's global
    # indices as a third, non-donated output (out_specs P(axis)) for the
    # host to gather while the intervening steps run. See hs_body below
    # and data/stream.py.
    host_stream = config.data_placement == "host_stream"
    depth = int(config.prefetch_depth)
    if host_stream:
        if depth < 1:
            raise ValueError(f"prefetch_depth must be >= 1, got {depth}")
        if pipelined:
            raise ValueError(
                "host_stream already pipelines selection (the lookahead "
                "draw); pipelined_scoring does not compose with it"
            )
        if use_cadence:
            raise ValueError(
                "host_stream requires score_refresh_every == 1: the "
                "cached-pool cadence redraws from slots whose rows were "
                "never streamed"
            )
        if use_groupwise:
            raise ValueError(
                "host_stream supports sampler='pool'|'scoretable' (and "
                "the uniform baseline); the groupwise window draw depends "
                "on post-update scores and cannot be drawn ahead"
            )
        if scan_steps > 1:
            raise ValueError(
                "host_stream requires scan_steps == 1: each step consumes "
                "one host-prefetched batch and emits the next indices — a "
                "scanned chunk would need the streamed batches mid-graph"
            )
        if auto_axes:
            raise ValueError(
                "host_stream requires a data-only mesh (no tensor/fsdp "
                "axis); drop tensor_parallel/fsdp_parallel"
            )
    fused_input = bool(config.fused_input)
    if fused_input:
        if config.augmentation != "noniid":
            raise ValueError(
                "fused_input fuses the noniid crop/flip augmentation into "
                "the ingest kernel (ops.augment_normalize_pallas); set "
                f"augmentation='noniid' (got {config.augmentation!r})"
            )
        if config.cutout:
            raise ValueError(
                "fused_input does not fuse cutout; set cutout=False"
            )
    # scoring_dtype="bfloat16" end-to-end: scorer-only ingest sites (rows
    # whose images are never reused for training) emit bf16 directly —
    # with fused_input the kernel's final cast, so the scoring forward is
    # bf16 from uint8 to score with no f32 activation round trip.
    scoring_bf16 = config.scoring_dtype == "bfloat16"
    # Streamed rows per worker per step: the candidate pool for the pool
    # sampler (selection happens in-step on the streamed rows), the
    # refresh window + the pre-drawn train batch for the scoretable one —
    # train rows only under async refresh (the fleet scores its own
    # windows host-side, so no refresh rows ever cross the stream).
    emit_size = (batch_size if async_refresh
                 else (refresh_size + batch_size) if use_scoretable
                 else pool_size)

    def _loss_per_sample(logits, labels):
        if use_pallas:
            from mercury_tpu.ops import per_sample_nll_pallas

            return per_sample_nll_pallas(logits, labels)
        return per_sample_loss(logits, labels, config.label_smoothing)

    def _score_per_sample(logits, labels):
        """Candidate scorer: what the pool forward's logits become scores
        by. Training losses always use ``_loss_per_sample`` — the IS
        reweighting is score-agnostic, so any scorer stays unbiased."""
        if config.importance_score == "grad_norm":
            return per_sample_grad_norm_bound(
                logits, labels, config.label_smoothing
            )
        return _loss_per_sample(logits, labels)

    def _pool_loss_metric(pool_logits, labels, score_avg):
        """Keep the ``train/pool_loss`` metric a true mean CE even when the
        SCORES are gradient norms (the EMA still smooths the score
        statistic — that's the selection math); comparing pool-loss curves
        across score modes must compare the same quantity."""
        if config.importance_score == "grad_norm":
            return pool_mean(_loss_per_sample(pool_logits, labels), stat_axis)
        return score_avg

    def _apply_train(params, batch_stats, images, keep_stats: bool):
        """Train-mode forward. ``keep_stats=False`` (the scoring pass) uses
        batch statistics for normalization but discards the running-stat
        update — the clean version of the reference's quirk where
        ``update_samples``'s no_grad forwards still mutate BN running means
        (``pytorch_collab.py:101`` runs the net in train mode).

        Returns ``(logits, new_stats, aux)`` where ``aux`` is the sum of
        any sowed ``"losses"`` collection entries (the MoE router's
        load-balancing loss; 0.0 for models that sow nothing)."""
        variables = {"params": params}
        mutable = ["losses"]
        if batch_stats:
            variables["batch_stats"] = batch_stats
            mutable = ["batch_stats", "losses"]
        logits, new_model_state = model.apply(
            variables, images, train=True, mutable=mutable
        )
        from mercury_tpu.utils.tree import sum_sowed_losses

        aux = sum_sowed_losses(new_model_state)
        if batch_stats and keep_stats:
            new_stats = new_model_state["batch_stats"]
        else:
            new_stats = batch_stats
        return logits, new_stats, aux

    def _augment(key, images):
        # mercury_augmentation anchors the augmentation ops' op_name
        # metadata for offline device-time attribution
        # (obs/profile_parse.py). Named scopes live in source_info only —
        # the pretty-printed jaxpr (and so Layer-2 digests) is unchanged.
        if config.augmentation == "noniid":
            with jax.named_scope("mercury_augmentation"):
                return augment_batch(key, images, use_cutout=config.cutout)
        if config.augmentation == "iid":
            from mercury_tpu.data.transforms import augment_batch_iid

            with jax.named_scope("mercury_augmentation"):
                return augment_batch_iid(key, images)
        if config.augmentation != "none":
            raise ValueError(f"unknown augmentation {config.augmentation!r}")
        return images

    def _ingest(key, raw, out_dtype=None):
        """Raw rows → augmented normalized images: THE ingest boundary —
        every sampler path funnels its pixel rows through here. Unfused,
        it is the ``normalize_images`` + ``_augment`` HLO chain; with
        ``config.fused_input`` it is one Pallas VMEM pass
        (``ops.augment_normalize_pallas``, ``mercury_input_fuse`` scope)
        that consumes ``key`` identically, so trajectories are
        bit-identical at f32 (test-enforced, tests/test_ops.py).
        ``out_dtype`` (the bf16 scoring ingest) is applied as the LAST op
        on both paths, so the fused/unfused agreement survives the cast."""
        if fused_input:
            if raw.dtype != jnp.uint8:
                raise ValueError(
                    "fused_input ingests raw uint8 rows (the kernel owns "
                    f"the /255 dequant); got {raw.dtype}"
                )
            from mercury_tpu.ops import augment_normalize_pallas

            return augment_normalize_pallas(
                key, raw, mean, std,
                out_dtype=(jnp.float32 if out_dtype is None else out_dtype),
            )
        imgs = _augment(key, normalize_images(raw, mean, std))
        if out_dtype is not None:
            imgs = imgs.astype(out_dtype)
        return imgs

    def _select(k_sel, pool_losses, ema):
        """EMA update + score→normalize→draw, returning
        ``(selected, scaled_probs, new_ema, avg_pool_loss)`` — shared by the
        inline and pipelined paths (Pallas or jax-native)."""
        if use_pallas:
            from mercury_tpu.ops import score_and_draw_pallas

            avg = pool_mean(pool_losses, stat_axis)
            new_ema = ema_update(ema, avg, config.ema_alpha)
            _, selected, scaled = score_and_draw_pallas(
                k_sel, pool_losses, new_ema.value, batch_size, config.is_alpha
            )
            return selected, scaled, new_ema, avg
        sel = select_from_pool(
            k_sel, pool_losses, ema, batch_size,
            is_alpha=config.is_alpha, ema_alpha=config.ema_alpha,
            axis_name=stat_axis,
        )
        return sel.selected, sel.scaled_probs, sel.ema, sel.avg_pool_loss

    def score_rows(state, raw, labs, ka, reuse_images=True):
        """Augment → inference-mode scoring forward over already-gathered
        rows — the pool-scoring core shared by the device-resident
        ``score_slots`` prologue and the host-stream body (whose rows
        arrive pre-gathered from the host pipeline). Callers wrap the
        call in the ``mercury_scoring`` named scope the jaxpr auditor
        anchors on (one scope per call site — nesting would rename the
        anchor). ``reuse_images=False`` marks scorer-only sites (the
        returned images are discarded, e.g. scoretable refresh windows):
        with ``scoring_dtype="bfloat16"`` those ingest straight to bf16 —
        uint8 → bf16 score, no f32 activation round trip. Returns
        ``(imgs, pool_logits, scores)``."""
        scorer_only = not reuse_images and scoring_bf16
        imgs = _ingest(
            ka, raw, out_dtype=jnp.bfloat16 if scorer_only else None
        )
        if scoring_model is None:
            pool_logits, _, _ = _apply_train(
                state.params, state.batch_stats, imgs, False
            )
        else:
            # Same params, lower-precision compute (scoring_dtype) —
            # scores only rank candidates, and the reweight divides by
            # the realized probs, so this stays unbiased. The forward's
            # input is pre-cast to the scoring dtype (a no-op when the
            # ingest already emitted bf16) so the activations never
            # materialize at f32; the returned imgs keep the training
            # precision when the caller reuses them.
            s_in = imgs.astype(jnp.bfloat16) if scoring_bf16 else imgs
            variables = {"params": state.params}
            mutable = ["losses"]
            if state.batch_stats:
                variables["batch_stats"] = state.batch_stats
                mutable = ["batch_stats", "losses"]
            pool_logits, _ = scoring_model.apply(
                variables, s_in, train=True, mutable=mutable
            )
            pool_logits = pool_logits.astype(jnp.float32)
        return imgs, pool_logits, _score_per_sample(pool_logits, labs)

    def probe_var_ratio(state, sel_images, sel_labels, scaled_probs):
        """Grad-variance probe (``sampler_dist/var_ratio``, the
        1803.00942 gate signal, observe-only): every ``probe_every``-th
        step, ONE extra scoring-model pass over the just-trained
        microbatch yields per-example grad-norm bounds ``g_i``; with the
        batch drawn from ``p`` and ``scaled_probs_i = N·p_i``,
        ``pool_mean((g/(N·p))²)`` estimates the IS gradient estimator's
        second moment and ``pool_mean(g²/(N·p))`` the uniform one (same
        unbiased reweighting as the loss). Their ratio follows
        ``benchmarks/grad_variance.py``'s convention: < 1 ⇔ IS is
        winning. Uses PRE-update params (``state`` is the input state) —
        the distribution the draw actually came from. Off-cadence steps
        return the -1.0 sentinel every consumer ignores."""

        def run(_):
            with jax.named_scope("mercury_variance_probe"):
                if scoring_model is None:
                    logits, _, _ = _apply_train(
                        state.params, state.batch_stats, sel_images, False
                    )
                else:
                    s_in = (sel_images.astype(jnp.bfloat16)
                            if scoring_bf16 else sel_images)
                    variables = {"params": state.params}
                    mutable = ["losses"]
                    if state.batch_stats:
                        variables["batch_stats"] = state.batch_stats
                        mutable = ["batch_stats", "losses"]
                    logits, _ = scoring_model.apply(
                        variables, s_in, train=True, mutable=mutable
                    )
                g = per_sample_grad_norm_bound(
                    logits.astype(jnp.float32), sel_labels,
                    config.label_smoothing,
                )
            sp = jnp.maximum(scaled_probs.astype(jnp.float32), 1e-30)
            # Pool the moments across workers BEFORE the ratio (a pmean
            # of per-worker ratios is not the global ratio);
            # obs/sampler_health.variance_probe_ratio is the single-host
            # reference the tests cross-validate against.
            m_is = pool_mean(jnp.square(g / sp), stat_axis)
            m_unif = pool_mean(jnp.square(g) / sp, stat_axis)
            return m_is / jnp.maximum(m_unif, 1e-30)

        # Cadence on the POST-increment step: metric records carry
        # state.step + 1, so this makes the probe land on the records
        # whose step is a multiple of probe_every — aligning with
        # log_every (set probe_every to a multiple of it), instead of
        # emitting the sentinel one record off forever.
        return lax.cond(
            (state.step + 1) % probe_every == 0, run,
            lambda _: jnp.full((), -1.0, jnp.float32), operand=None,
        )

    def train_update(state, rng, sel_images, sel_labels, scaled_probs):
        """The train back-end — the second half of the fused step, split
        from the per-sampler selection front-ends so the host-stream body
        (which consumes a batch selected ``prefetch_depth`` steps ago)
        shares it verbatim with the device-resident paths: reweighted
        fwd/bwd, optional gradient compression, the gradient collective
        (plain allreduce or ZeRO-1 reduce-scatter/all-gather, int8 wire
        variants), optimizer apply, and the BN-stat sync. Returns a dict
        with the new model/optimizer state, the train logits (the
        scoretable write-back re-scores them for free), and the
        replicated loss/acc reductions."""
        # fold_in (not a 9-way split) so the eight existing streams — and
        # every recorded seeded trajectory — are unchanged by the
        # compression feature's existence.
        k_quant = jax.random.fold_in(rng, 0x71)  # graftlint: disable=GL101 -- deliberate sentinel stream: fold_in(rng, 0x71) is disjoint from the 8-way split, preserving recorded trajectories

        # --- train forward/backward with the unbiased IS reweighting
        # mean(loss_i/(N·p_i)) (:132-148) --------------------------------
        def loss_fn(params):
            logits, new_bs, aux = _apply_train(
                params, state.batch_stats, sel_images, True
            )
            losses = _loss_per_sample(logits, sel_labels)
            total = reweighted_loss(losses, scaled_probs)
            if config.moe_experts is not None:
                # Switch load-balancing term (sowed by the MoE blocks).
                total = total + config.moe_aux_weight * aux
            return total, (logits, new_bs, aux)

        (loss, (logits, new_batch_stats, moe_aux)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)

        # --- optional quantization: each worker stochastically quantizes
        # its local gradient (independent keys); the mean across workers
        # stays unbiased — the live version of the reference's dead-code
        # experiment (util.py:65-70; "sparse rate", pytorch_collab.py:184).
        # Estimator semantics only: the psum below still moves dense
        # tensors (see TrainConfig.grad_compression).
        sparse_rate = jnp.ones((), jnp.float32)
        if compress_grads:
            from mercury_tpu.utils.quantize import sparsity, stochastic_quantize

            leaves, treedef = jax.tree_util.tree_flatten(grads)
            qkeys = jax.random.split(k_quant, len(leaves))
            leaves = [stochastic_quantize(k, g) for k, g in zip(qkeys, leaves)]
            grads = jax.tree_util.tree_unflatten(treedef, leaves)
            total = float(sum(g.size for g in leaves))
            sparse_rate = sum(sparsity(g) * (g.size / total) for g in leaves)

        loss_mean = lax.pmean(loss, axis)
        correct = lax.psum(
            jnp.sum((jnp.argmax(logits, -1) == sel_labels).astype(jnp.float32)), axis
        )
        count = lax.psum(jnp.asarray(batch_size, jnp.float32), axis)

        grad_norm = None
        if zero:
            # --- ZeRO-1: reduce-scatter the flattened gradient (each worker
            # receives the mean of its 1/W chunk — reduce-scatter +
            # all-gather IS the ring allreduce, util.py:280-324, so the
            # collective volume matches average_gradients :236-249), update
            # only that chunk's optimizer state, all-gather the updates.
            # With grad_compression="int8", BOTH wire phases move int8
            # payloads (per-chunk scales, stochastic rounding — unbiased):
            # the gradient reduce-scatter and the update all-gather, 4×
            # fewer bytes each (parallel/collectives.py).
            from mercury_tpu.utils.tree import (
                pad_to_chunks,
                tree_flatten_to_vector,
            )

            w = axis_size(axis)
            opt_chunk = jax.tree_util.tree_map(lambda x: x[0], state.opt_state)
            gvec, unravel = tree_flatten_to_vector(grads)
            if int8_allreduce:
                from mercury_tpu.parallel.collectives import (
                    compressed_all_gather,
                    compressed_psum_scatter_mean,
                )

                kz = jax.random.fold_in(rng, 0x72)  # graftlint: disable=GL101 -- deliberate sentinel stream 0x72 for int8 grad compression, disjoint from the 8-way split and 0x71
                kz1, kz2 = jax.random.split(kz)
                # mercury_grad_sync scopes anchor the jaxpr auditor's
                # per-region collective budgets (lint/audit.py).
                with jax.named_scope("mercury_grad_sync"):
                    gchunk = compressed_psum_scatter_mean(
                        pad_to_chunks(gvec, w), axis, kz1
                    )
            else:
                with jax.named_scope("mercury_grad_sync"):
                    gchunk = (
                        lax.psum_scatter(pad_to_chunks(gvec, w), axis) / w
                    )
            if telemetry:
                # The chunks partition the full mean-gradient vector (the
                # pad is zeros), so psum of the per-chunk square-sums is the
                # exact global norm² — one scalar on the wire.
                grad_norm = jnp.sqrt(lax.psum(
                    jnp.sum(jnp.square(gchunk.astype(jnp.float32))), axis
                ))
            pvec, _ = tree_flatten_to_vector(state.params)
            pchunk = pad_to_chunks(pvec, w)[lax.axis_index(axis)]
            # mercury_optimizer: profiler-attribution anchor for the
            # optimizer update (obs/profile_parse.py); digest-invisible.
            with jax.named_scope("mercury_optimizer"):
                updates_chunk, new_opt_chunk = tx.update(
                    gchunk, opt_chunk, pchunk)
            if int8_allreduce:
                with jax.named_scope("mercury_grad_sync"):
                    uvec = compressed_all_gather(updates_chunk, axis, kz2)[
                        : gvec.size
                    ]
            else:
                with jax.named_scope("mercury_grad_sync"):
                    uvec = lax.all_gather(
                        updates_chunk, axis, tiled=True
                    )[: gvec.size]
            with jax.named_scope("mercury_optimizer"):
                new_params = optax.apply_updates(state.params,
                                                 unravel(uvec))
            new_opt_state = jax.tree_util.tree_map(
                lambda x: x[None], new_opt_chunk
            )
        else:
            # --- gradient allreduce (≡ average_gradients, :236-249) in-graph
            if int8_allreduce:
                # int8 on the wire, both phases (collectives.py); unbiased.
                if tp_active:
                    # Per-leaf, shape-preserving compression: the wire
                    # chunking avoids the dims TP/FSDP shard, so the
                    # grads stay sharded through both phases.
                    from mercury_tpu.parallel.collectives import (
                        compressed_pmean_tree_sharded,
                    )

                    with jax.named_scope("mercury_grad_sync"):
                        grads = compressed_pmean_tree_sharded(
                            grads, axis, axis_size(axis),
                            # graftlint: disable=GL101 -- same deliberate 0x72 sentinel stream as the ZeRO branch (mutually exclusive at trace time)
                            jax.random.fold_in(rng, 0x72),
                            specs=sharded_param_specs,
                        )
                else:
                    from mercury_tpu.parallel.collectives import (
                        compressed_allreduce_mean_tree,
                    )

                    with jax.named_scope("mercury_grad_sync"):
                        grads = compressed_allreduce_mean_tree(
                            grads, axis, axis_size(axis),
                            # graftlint: disable=GL101 -- same deliberate 0x72 sentinel stream as the ZeRO branch (mutually exclusive at trace time)
                            jax.random.fold_in(rng, 0x72),
                        )
            else:
                with jax.named_scope("mercury_grad_sync"):
                    grads = allreduce_mean_tree(grads, axis)
            if telemetry:
                # Post-allreduce: already the worker-mean gradient, so the
                # norm is identical on every worker (replicated output).
                grad_norm = global_grad_norm(grads)
            with jax.named_scope("mercury_optimizer"):
                updates, new_opt_state = tx.update(
                    grads, state.opt_state, state.params
                )
                new_params = optax.apply_updates(state.params, updates)

        # Keep replicated BN stats replicated: under synced BN they already
        # agree; under local BN we average the running stats across workers
        # (normalization still used local batch stats this step).
        if new_batch_stats:
            new_batch_stats = allreduce_mean_tree(new_batch_stats, axis)

        return dict(
            loss_mean=loss_mean, acc=correct / count, logits=logits,
            moe_aux=moe_aux, sparse_rate=sparse_rate, grad_norm=grad_norm,
            new_params=new_params, new_batch_stats=new_batch_stats,
            new_opt_state=new_opt_state,
        )

    def body(state: MercuryState, x_train, y_train, shard_indices):
        # Leading axis inside shard_map is this device's single worker row.
        if data_sharded:
            x_loc, y_loc = x_train[0], y_train[0]

            def gather_train(slots):
                return x_loc[slots], y_loc[slots]
        else:
            def gather_train(slots):
                gidx = shard_indices[0][slots]
                return x_train[gidx], y_train[gidx]

        rng = state.rng[0]
        (k_stream, k_aug, k_sel, k_aug2, k_boot_stream, k_boot_aug,
         k_boot_sel, k_next) = jax.random.split(rng, 8)

        groupwise = None
        new_pending = None
        stream = ShardStream(perm=state.stream.perm[0], cursor=state.stream.cursor[0])
        ema = EMAState(value=state.ema.value[0], count=state.ema.count[0])

        # Per-path sampler-health scalars (obs/diagnostics.py). Each branch
        # overwrites these with its own measurement; the uniform baseline
        # keeps the zeros (nothing is scored, nothing can clip or drift).
        if telemetry:
            clip_frac = jnp.zeros((), jnp.float32)
            drift = jnp.zeros((), jnp.float32)

        def score_slots(slots, ka, reuse_images=True):
            """Gather → augment → inference-mode scoring forward — the
            pool-scoring prologue shared by the inline, pipelined,
            cadence, and groupwise IS paths (one definition so a change
            to scoring cannot drift between them). The whole prologue
            runs under the ``mercury_scoring`` named scope — the jaxpr
            auditor (``mercury_tpu/lint/audit.py``) keys per-region
            checks (e.g. bf16-scoring dot dtypes) on this anchor.
            ``reuse_images`` forwards to ``score_rows`` (False at
            scorer-only sites: bf16 ingest under scoring_dtype)."""
            with jax.named_scope("mercury_scoring"):
                raw, labs = gather_train(slots)
                imgs, pool_logits, scores = score_rows(
                    state, raw, labs, ka, reuse_images=reuse_images
                )
                return imgs, labs, pool_logits, scores

        if pipelined:
            # --- pipelined scoring: train on the batch selected last step,
            # score the NEXT pool with the same (pre-update) params — the
            # two chains are independent, so XLA overlaps the scoring
            # forward with the gradient collective. Reference dataflow:
            # update_samples for t+1 runs before optimizer.step
            # (pytorch_collab.py:158-164). --------------------------------
            def score_next(stream, ema, ks, ka, ksel):
                stream, slots = next_pool(stream, ks, pool_size)
                imgs, labs, pool_logits, pool_losses = score_slots(slots, ka)
                ema_prev = ema.value
                selected, scaled, ema, avg = _select(ksel, pool_losses, ema)
                pend = PendingBatch(
                    images=imgs[selected], labels=labs[selected],
                    scaled_probs=scaled,
                )
                tel = ()
                if telemetry:
                    # Clip/drift of the pool scored THIS step (the one
                    # trained next step) — the pipeline's live scoring work.
                    tel = (
                        clip_fraction(pool_losses, ema.value, config.is_alpha),
                        ema_drift(avg, ema_prev),
                    )
                return stream, ema, pend, _pool_loss_metric(
                    pool_logits, labs, avg
                ), tel

            stored = jax.tree_util.tree_map(lambda x: x[0], state.pending)

            # Step 0 primes the pending batch in-graph (≡ the epoch-prologue
            # update_samples call, pytorch_collab.py:125).
            def boot(args):
                s, e = args
                return score_next(s, e, k_boot_stream, k_boot_aug, k_boot_sel)

            def keep(args):
                s, e = args
                tel = ()
                if telemetry:
                    tel = (jnp.zeros((), jnp.float32),
                           jnp.zeros((), jnp.float32))
                return s, e, stored, jnp.zeros((), jnp.float32), tel

            stream, ema, current, _, _ = lax.cond(
                state.step == 0, boot, keep, (stream, ema)
            )
            sel_images, sel_labels = current.images, current.labels
            scaled_probs = current.scaled_probs
            stream, ema, new_pending, avg_pool_loss, tel = score_next(
                stream, ema, k_stream, k_aug, k_sel
            )
            if telemetry:
                clip_frac, drift = tel
        elif use_cadence:
            # --- score-refresh cadence: every K-th step stream + score a
            # fresh pool and cache its normalized importance distribution;
            # the K-1 steps in between redraw from the cache (fresh
            # multinomial draws ≡ pytorch_collab.py:114, fresh
            # augmentation) and skip the scoring forward entirely — the
            # dominant per-step IS cost amortizes by K. The 1/(N·p)
            # reweight uses the cached probs the batch was actually drawn
            # from, so the estimator stays unbiased for those scores. ----
            cached = jax.tree_util.tree_map(lambda x: x[0], state.cached_pool)
            # Telemetry carry through the cond: the refresh branch measures,
            # the reuse branch returns these zeros — clip/drift read 0 on
            # cache-hit steps (no scoring happened, nothing to measure).
            tel0 = ()
            if telemetry:
                tel0 = (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))

            def refresh(args):
                stream, ema, _, _ = args
                stream, slots = next_pool(stream, k_stream, pool_size)
                _, labs, pool_logits, pool_losses = score_slots(
                    slots, k_aug, reuse_images=False
                )
                avg = pool_mean(pool_losses, stat_axis)
                ema_prev = ema.value
                ema = ema_update(ema, avg, config.ema_alpha)
                probs = importance_probs(
                    pool_losses, ema.value, config.is_alpha
                )
                pool = CachedPool(
                    slots=slots.astype(jnp.int32),
                    probs=probs,
                    pool_loss=_pool_loss_metric(pool_logits, labs, avg),
                )
                tel = ()
                if telemetry:
                    tel = (
                        clip_fraction(pool_losses, ema.value, config.is_alpha),
                        ema_drift(avg, ema_prev),
                    )
                return stream, ema, pool, tel

            def reuse(args):
                return args

            stream, ema, cached, tel = lax.cond(
                state.step % cadence == 0, refresh, reuse,
                (stream, ema, cached, tel0),
            )
            if telemetry:
                clip_frac, drift = tel
            selected = draw_with_replacement(k_sel, cached.probs, batch_size)
            scaled_probs = cached.probs[selected] * pool_size
            sel_raw, sel_labels = gather_train(cached.slots[selected])
            sel_images = _ingest(k_aug2, sel_raw)
            avg_pool_loss = cached.pool_loss
            new_cached = cached
        elif use_scoretable:
            # --- score-table sampler: a device-resident [L] float32 score
            # over THIS worker's whole shard. Each step (a) refreshes only
            # `refresh_size` entries — a round-robin window, so every slot
            # is rescored within ceil(L/R) steps — via one small scoring
            # forward, (b) age-decays the rest toward the EMA mean
            # (staleness-aware smoothing: an entry untouched for k steps
            # has shrunk by decay^k toward the pool-typical score), and
            # (c) draws the train batch from the FULL shard's distribution
            # in one fused normalize→CDF→draw kernel. Scoring FLOPs per
            # step drop from pool_size to refresh_size while the draw sees
            # every sample — vs. the pool sampler's fresh-320 window.
            table = jax.tree_util.tree_map(lambda x: x[0], state.scoretable)
            if async_refresh:
                # --- refresh_mode="async": no refresh window, no scoring
                # forward, no mercury_scoring scope — the scorer fleet
                # refreshed the table between dispatches. The in-graph work
                # is decay → normalize → draw only; the post-train
                # write-back below still re-scores the trained batch for
                # free (those logits exist either way).
                if use_pallas:
                    from mercury_tpu.ops import table_refresh_draw_pallas

                    # Dummy-slot sentinel: "refresh" slot 0 with its own
                    # decayed value — scatter_mean writes back the number
                    # the decay already produced, a no-op — so the SAME
                    # fused decay→scatter→normalize→draw kernel serves the
                    # async step with no scoring forward attached and no
                    # second kernel to maintain.
                    sent = (ema.value
                            + (table.scores[0].astype(jnp.float32)
                               - ema.value) * config.table_decay)[None]
                    new_scores, _, selected, scaled_probs = (
                        table_refresh_draw_pallas(
                            k_sel, table.scores,
                            jnp.zeros((1,), jnp.int32), sent,
                            ema.value, batch_size,
                            alpha=config.is_alpha, decay=config.table_decay,
                        )
                    )
                else:
                    new_scores = decay_scores(
                        table.scores.astype(jnp.float32), ema.value,
                        config.table_decay,
                    )
                    probs = table_probs(
                        new_scores, ema.value, config.is_alpha
                    )
                    # Inverse-CDF, not categorical: a [B, L] Gumbel field
                    # is B·L threefry draws — at shard scale that alone
                    # would cost more than the scoring forward we just
                    # removed (measured ~5 ms at L≈3k on CPU).
                    selected = table_draw_inverse_cdf(
                        k_sel, probs, batch_size
                    )
                    scaled_probs = probs[selected] * new_scores.shape[0]
                # No refresh forward → no pool-loss measurement this step;
                # the EMA update moves post-train (see the write-back).
                avg_pool_loss = jnp.zeros((), jnp.float32)
            else:
                refresh_slots = refresh_window(table, refresh_size)
                _, r_labels, r_logits, r_scores = score_slots(
                    refresh_slots, k_aug, reuse_images=False
                )
                score_avg = pool_mean(r_scores, stat_axis)
                ema_prev = ema.value
                ema = ema_update(ema, score_avg, config.ema_alpha)
                if use_pallas:
                    from mercury_tpu.ops import table_refresh_draw_pallas

                    new_scores, _, selected, scaled_probs = (
                        table_refresh_draw_pallas(
                            k_sel, table.scores, refresh_slots, r_scores,
                            ema.value, batch_size,
                            alpha=config.is_alpha, decay=config.table_decay,
                        )
                    )
                else:
                    new_scores, _, selected, scaled_probs = (
                        table_refresh_draw(
                            k_sel, table.scores, refresh_slots, r_scores,
                            ema.value, batch_size,
                            alpha=config.is_alpha, decay=config.table_decay,
                        )
                    )
                avg_pool_loss = _pool_loss_metric(
                    r_logits, r_labels, score_avg
                )
            sel_raw, sel_labels = gather_train(selected)
            sel_images = _ingest(k_aug2, sel_raw)
            table_scores_predraw = new_scores
            table_selected = selected
            if telemetry:
                # Clip over the FULL refreshed (async: decayed) table — the
                # distribution the draw actually normalizes.
                clip_frac = clip_fraction(
                    new_scores, ema.value, config.is_alpha
                )
                if not async_refresh:
                    # Cursor staleness from the round-robin window
                    # (pre-advance: this window is age 0); under async the
                    # fleet owns the sweep, so ages live host-side
                    # (sampler/score_staleness_* via ScorerFleet.stats) and
                    # drift moves to the post-train EMA update below.
                    drift = ema_drift(score_avg, ema_prev)
                    age_min, age_mean, age_max = table_age_summary(
                        table.cursor, table.scores.shape[0], refresh_size
                    )
        else:
            if use_groupwise:
                # Sliding-window refresh over the shard (util.py:114-138):
                # the next `pool_size` slots in order, wrapping — no shuffle.
                from mercury_tpu.sampling.groupwise import (
                    draw as gw_draw,
                    update_importance,
                    window_indices,
                )

                groupwise = jax.tree_util.tree_map(lambda x: x[0], state.groupwise)
                slots = window_indices(groupwise, pool_size)
            else:
                # Shuffled wrapping presample stream (≡ Trainer.get_next over
                # the presampling loader, :74-82).
                stream, slots = next_pool(stream, k_stream, pool_size)

            if use_is:
                # --- importance scoring: ONE batched inference forward over
                # the pool (≡ the 10-iteration no_grad loop, :95-106),
                # batch-stat normalization, running-stat updates discarded --
                # Groupwise discards the scored images (drawn slots are
                # re-gathered below), so its scoring pass is scorer-only.
                images, labels, pool_logits, pool_losses = score_slots(
                    slots, k_aug, reuse_images=not use_groupwise
                )
                if use_groupwise:
                    # Persist scores into the shard-wide importance array,
                    # tag the new generation, draw from it with the +mean
                    # shift (util.py:133-153). Drawn slots are re-gathered
                    # and re-augmented (the sampler re-loads by index, as
                    # the reference's does via get_slice, util.py:123).
                    groupwise = update_importance(groupwise, slots, pool_losses)
                    sel_slots, scaled_probs = gw_draw(groupwise, k_sel, batch_size)
                    sel_raw, sel_labels = gather_train(sel_slots)
                    sel_images = _ingest(k_aug2, sel_raw)
                    score_avg = pool_mean(pool_losses, stat_axis)
                    ema_prev = ema.value
                    ema = ema_update(ema, score_avg, config.ema_alpha)
                    avg_pool_loss = _pool_loss_metric(
                        pool_logits, labels, score_avg
                    )
                else:
                    ema_prev = ema.value
                    selected, scaled_probs, ema, score_avg = _select(
                        k_sel, pool_losses, ema
                    )
                    avg_pool_loss = _pool_loss_metric(
                        pool_logits, labels, score_avg
                    )
                    sel_images = images[selected]
                    sel_labels = labels[selected]
                if telemetry:
                    clip_frac = clip_fraction(
                        pool_losses, ema.value, config.is_alpha
                    )
                    drift = ema_drift(score_avg, ema_prev)
            else:
                # Uniform baseline: consume the freshly streamed batch
                # directly — the stream is a shuffled without-replacement
                # epoch pass, i.e. standard shuffled-loader SGD — with unit
                # IS weights so loss/(N·p) = loss. (pool_size == batch_size
                # here, so no scoring forward and no wasted gather.)
                raw, sel_labels = gather_train(slots)
                sel_images = _ingest(k_aug, raw)[:batch_size]
                sel_labels = sel_labels[:batch_size]
                scaled_probs = jnp.ones((batch_size,), jnp.float32)
                avg_pool_loss = jnp.zeros((), jnp.float32)

        upd = train_update(state, rng, sel_images, sel_labels, scaled_probs)
        logits = upd["logits"]
        if telemetry:
            grad_norm = upd["grad_norm"]
        if use_probe:
            var_ratio = probe_var_ratio(
                state, sel_images, sel_labels, scaled_probs
            )

        new_scoretable = state.scoretable
        new_sel_counts = state.sel_counts
        if use_scoretable:
            # Free write-back: the train forward's logits re-score the
            # just-trained slots for zero extra FLOPs (they fall out of the
            # backward pass anyway); with-replacement duplicates average.
            train_scores = _score_per_sample(
                logits.astype(jnp.float32), sel_labels
            )
            if async_refresh:
                # With no refresh forward, the EMA mean (decay target and
                # smoothing anchor) comes from the trained batch itself,
                # reweighted back to the uniform-mean estimate:
                # E[score_i/(L·p_i)] = mean_L(score) — the same unbiased
                # identity the loss reweighting rests on — so the EMA
                # tracks the SHARD-typical score, not the importance-tilted
                # batch mean, at zero extra FLOPs.
                score_avg = pool_mean(train_scores / scaled_probs, stat_axis)
                ema_prev = ema.value
                ema = ema_update(ema, score_avg, config.ema_alpha)
                if telemetry:
                    drift = ema_drift(score_avg, ema_prev)
            new_table = ScoreTableState(
                scores=scatter_mean(
                    table_scores_predraw, table_selected, train_scores
                ),
                # Async: the fleet owns the round-robin sweep — the
                # in-graph cursor stays put.
                cursor=(table.cursor if async_refresh
                        else advance_cursor(table, refresh_size)),
            )
            new_scoretable = jax.tree_util.tree_map(
                lambda x: x[None], new_table
            )
            if use_ledger:
                # Selection-count ledger: the drawn batch IS the trained
                # batch on this path, so counting at train time counts
                # every draw exactly once (with-replacement duplicates
                # add once per occurrence).
                new_sel_counts = (
                    state.sel_counts[0].at[table_selected].add(1)
                )[None]
            if telemetry:
                # Global (psum'd) histogram of the post-refresh table —
                # the distribution the NEXT draw normalizes. Per-bin
                # scalars: the async writer means any vector.
                score_hist = lax.psum(
                    log_bin_histogram(
                        new_table.scores, SCORE_HIST_LO, SCORE_HIST_HI
                    ),
                    axis,
                )

        new_state = MercuryState(
            step=state.step + 1,
            params=upd["new_params"],
            batch_stats=upd["new_batch_stats"],
            opt_state=upd["new_opt_state"],
            ema=EMAState(value=ema.value[None], count=ema.count[None]),
            stream=ShardStream(perm=stream.perm[None], cursor=stream.cursor[None]),
            rng=k_next[None],
            groupwise=(
                jax.tree_util.tree_map(lambda x: x[None], groupwise)
                if use_groupwise else state.groupwise
            ),
            pending=(
                jax.tree_util.tree_map(lambda x: x[None], new_pending)
                if pipelined else state.pending
            ),
            cached_pool=(
                jax.tree_util.tree_map(lambda x: x[None], new_cached)
                if use_cadence else state.cached_pool
            ),
            scoretable=new_scoretable,
            pending_sel=state.pending_sel,
            sel_counts=new_sel_counts,
        )
        metrics = {
            "train/loss": upd["loss_mean"],
            "train/acc": upd["acc"],
            "train/pool_loss": lax.pmean(avg_pool_loss, axis),
            "train/sparse_rate": lax.pmean(upd["sparse_rate"], axis),
            "train/moe_aux": lax.pmean(upd["moe_aux"], axis),
        }
        if telemetry:
            metrics["sampler/ess"] = lax.pmean(
                ess_fraction(scaled_probs), axis
            )
            metrics["sampler/clip_frac"] = lax.pmean(clip_frac, axis)
            metrics["sampler/ema_drift"] = lax.pmean(drift, axis)
            metrics["train/grad_norm"] = grad_norm
            if use_scoretable and not async_refresh:
                # Cursor-derived, identical on every worker (the cursors
                # advance in lockstep from the same init). Async has no
                # in-graph cursor motion — staleness is tracked host-side
                # (sampler/score_staleness_*).
                metrics["sampler/table_age_min"] = age_min
                metrics["sampler/table_age_mean"] = age_mean
                metrics["sampler/table_age_max"] = age_max
            if use_is:
                # Per-batch IS-weight histogram (scaled_probs = N·p, the
                # reweight's divisor), psum'd global.
                w_hist = lax.psum(
                    log_bin_histogram(
                        scaled_probs, WEIGHT_HIST_LO, WEIGHT_HIST_HI
                    ),
                    axis,
                )
                for i, k in enumerate(hist_keys("w_hist")):
                    metrics[k] = w_hist[i]
            if use_scoretable:
                for i, k in enumerate(hist_keys("score_hist")):
                    metrics[k] = score_hist[i]
            if use_probe:
                metrics["sampler_dist/var_ratio"] = lax.pmean(
                    var_ratio, axis
                )
        return new_state, metrics

    def hs_body(state: MercuryState, x_stream, y_train, shard_indices):
        """Host-stream step: train on the batch whose indices were drawn
        ``prefetch_depth`` steps ago (the front of the ``PendingSelection``
        ring — its pixel rows arrive pre-gathered in ``x_stream``), then
        draw the selection for step t+depth and emit its GLOBAL indices as
        a third, non-donated output for the host prefetch pipeline. The
        lookahead draw for step u consumes the same key positions of
        rng_u's 8-way split that the device-resident body would consume AT
        step u (``sel_ks[0]``/``sel_ks[2]``), carried in ``psel.rng`` — so
        uniform and pool selections (param-independent draws) are
        bit-identical to ``replicated``, while the scoretable draw sees a
        depth-step-stale table (the ``pipelined_scoring`` trade, one step
        deeper); the carried draw-time ``scaled_probs`` keep the IS
        reweighting unbiased either way."""
        # x_stream: [1, S, ...] — this worker's pre-gathered rows for the
        # ring front (scoretable: refresh window rows ‖ train rows).
        xs = x_stream[0]
        rng = state.rng[0]
        (k_stream, k_aug, k_sel, k_aug2, k_boot_stream, k_boot_aug,
         k_boot_sel, k_next) = jax.random.split(rng, 8)

        stream = ShardStream(perm=state.stream.perm[0],
                             cursor=state.stream.cursor[0])
        ema = EMAState(value=state.ema.value[0], count=state.ema.count[0])
        psel = jax.tree_util.tree_map(lambda x: x[0], state.pending_sel)
        # rng_{t+depth}'s split — the lookahead draw's key material.
        sel_ks = jax.random.split(jax.random.wrap_key_data(psel.rng), 8)
        front = psel.slots[0]

        if telemetry:
            clip_frac = jnp.zeros((), jnp.float32)
            drift = jnp.zeros((), jnp.float32)

        if use_scoretable:
            table = jax.tree_util.tree_map(lambda x: x[0], state.scoretable)
            if async_refresh:
                # Async: the stream carries ONLY the train rows (the fleet
                # owns the refresh sweep host-side — no refresh rows ever
                # cross the pipeline, no in-graph scoring forward). The
                # table still age-decays; the EMA update moves post-train.
                train_slots = front
                refreshed = decay_scores(
                    table.scores.astype(jnp.float32), ema.value,
                    config.table_decay,
                )
                sel_labels = y_train[shard_indices[0][train_slots]]
                sel_images = _ingest(k_aug2, xs)
                scaled_probs = psel.scaled_probs[0]
                avg_pool_loss = jnp.zeros((), jnp.float32)
            else:
                # Streamed layout: rows 0:R are the step-t refresh window
                # (deterministic round-robin — drawn without the table),
                # rows R: are the train rows selected depth steps ago.
                refresh_slots = front[:refresh_size]
                train_slots = front[refresh_size:]
                with jax.named_scope("mercury_scoring"):
                    r_labels = y_train[shard_indices[0][refresh_slots]]
                    _, r_logits, r_scores = score_rows(
                        state, xs[:refresh_size], r_labels, k_aug,
                        reuse_images=False,
                    )
                score_avg = pool_mean(r_scores, stat_axis)
                ema_prev = ema.value
                ema = ema_update(ema, score_avg, config.ema_alpha)
                # Same decay → refresh-scatter as table_refresh_draw; the
                # draw half ran depth steps ago, so only the table update
                # remains.
                refreshed = scatter_mean(
                    decay_scores(
                        table.scores.astype(jnp.float32), ema.value,
                        config.table_decay,
                    ),
                    refresh_slots, r_scores,
                )
                sel_labels = y_train[shard_indices[0][train_slots]]
                sel_images = _ingest(k_aug2, xs[refresh_size:])
                scaled_probs = psel.scaled_probs[0]
                avg_pool_loss = _pool_loss_metric(
                    r_logits, r_labels, score_avg
                )
                if telemetry:
                    drift = ema_drift(score_avg, ema_prev)
                    age_min, age_mean, age_max = table_age_summary(
                        table.cursor, table.scores.shape[0], refresh_size
                    )
        elif use_is:
            # Pool sampler: the streamed rows ARE the candidate pool drawn
            # depth steps ago with rng_t's stream key; scoring + selection
            # happen in-step with rng_t's k_aug/k_sel — bit-identical to
            # the device-resident inline path.
            labs = y_train[shard_indices[0][front]]
            with jax.named_scope("mercury_scoring"):
                imgs, pool_logits, pool_losses = score_rows(
                    state, xs, labs, k_aug
                )
            ema_prev = ema.value
            selected, scaled_probs, ema, score_avg = _select(
                k_sel, pool_losses, ema
            )
            avg_pool_loss = _pool_loss_metric(pool_logits, labs, score_avg)
            sel_images = imgs[selected]
            sel_labels = labs[selected]
            if telemetry:
                clip_frac = clip_fraction(
                    pool_losses, ema.value, config.is_alpha
                )
                drift = ema_drift(score_avg, ema_prev)
        else:
            # Uniform baseline (pool_size == batch_size): consume the
            # streamed rows directly, unit IS weights.
            sel_labels = y_train[shard_indices[0][front]][:batch_size]
            sel_images = _ingest(k_aug, xs)[:batch_size]
            scaled_probs = jnp.ones((batch_size,), jnp.float32)
            avg_pool_loss = jnp.zeros((), jnp.float32)

        upd = train_update(state, rng, sel_images, sel_labels, scaled_probs)
        logits = upd["logits"]
        if telemetry:
            grad_norm = upd["grad_norm"]
        if use_probe:
            var_ratio = probe_var_ratio(
                state, sel_images, sel_labels, scaled_probs
            )

        # --- lookahead draw for step t+depth -----------------------------
        next_scaled = jnp.ones((batch_size,), jnp.float32)
        new_scoretable = state.scoretable
        new_sel_counts = state.sel_counts
        if use_scoretable:
            # Write-back first (train logits re-score the trained slots),
            # then draw from the freshest table this host can have.
            train_scores = _score_per_sample(
                logits.astype(jnp.float32), sel_labels
            )
            if async_refresh:
                # Post-train EMA from the reweighted trained batch — the
                # same unbiased mean_L estimate as the device-resident
                # async body (see there) — BEFORE the lookahead normalize
                # so the next draw smooths against the freshest mean.
                score_avg = pool_mean(train_scores / scaled_probs, stat_axis)
                ema_prev = ema.value
                ema = ema_update(ema, score_avg, config.ema_alpha)
                if telemetry:
                    drift = ema_drift(score_avg, ema_prev)
            table_after = scatter_mean(refreshed, train_slots, train_scores)
            n_slots = table_after.shape[0]
            probs_next = table_probs(table_after, ema.value, config.is_alpha)
            if async_refresh:
                # Inverse-CDF draw, matching the device-resident async
                # body: categorical's [B, L] Gumbel field would put the
                # removed scoring forward's cost right back on the step.
                next_sel = table_draw_inverse_cdf(
                    sel_ks[2], probs_next, batch_size
                )
            else:
                next_sel = draw_with_replacement(
                    sel_ks[2], probs_next, batch_size
                ).astype(jnp.int32)
            next_scaled = probs_next[next_sel] * n_slots
            if async_refresh:
                # No window rows in the stream — the lookahead emits the
                # train draw only, and the cursor stays put (the fleet
                # owns the sweep).
                next_slots = next_sel
            else:
                # The refresh window for step t+depth is
                # cursor-deterministic: depth more R-sized round-robin
                # advances from here.
                next_window = (
                    (table.cursor + depth * refresh_size
                     + jnp.arange(refresh_size)) % n_slots
                ).astype(jnp.int32)
                next_slots = jnp.concatenate([next_window, next_sel])
            new_table = ScoreTableState(
                scores=table_after,
                cursor=(table.cursor if async_refresh
                        else advance_cursor(table, refresh_size)),
            )
            new_scoretable = jax.tree_util.tree_map(
                lambda x: x[None], new_table
            )
            if use_ledger:
                # Ledger counts at TRAIN time (the ring front consumed
                # this step), not at draw time — so the counts equal the
                # examples actually trained on and the in-flight ring is
                # not yet counted. tests/test_sampler_health.py pins this
                # against a host-side ring replay.
                new_sel_counts = (
                    state.sel_counts[0].at[train_slots].add(1)
                )[None]
            if telemetry:
                # Clip over the table the NEXT draw normalizes (the
                # freshest distribution this step produced).
                clip_frac = clip_fraction(
                    table_after, ema.value, config.is_alpha
                )
                score_hist = lax.psum(
                    log_bin_histogram(
                        table_after, SCORE_HIST_LO, SCORE_HIST_HI
                    ),
                    axis,
                )
        else:
            # Uniform/pool: the draw is param-independent, so running it
            # depth steps early with rng_{t+depth}'s stream key reproduces
            # the device-resident sequence exactly.
            stream, next_slots = next_pool(stream, sel_ks[0], emit_size)
            next_slots = next_slots.astype(jnp.int32)

        new_psel = PendingSelection(
            slots=jnp.concatenate([psel.slots[1:], next_slots[None]], 0),
            scaled_probs=jnp.concatenate(
                [psel.scaled_probs[1:], next_scaled[None]], 0
            ),
            rng=jax.random.key_data(sel_ks[7]),
        )
        # Global ids for the host gather — the pipeline's only view of the
        # draw (slots are shard-local; the host indexes the global array).
        next_gidx = shard_indices[0][next_slots][None]

        new_state = MercuryState(
            step=state.step + 1,
            params=upd["new_params"],
            batch_stats=upd["new_batch_stats"],
            opt_state=upd["new_opt_state"],
            ema=EMAState(value=ema.value[None], count=ema.count[None]),
            stream=ShardStream(perm=stream.perm[None],
                               cursor=stream.cursor[None]),
            rng=k_next[None],
            groupwise=state.groupwise,
            pending=state.pending,
            cached_pool=state.cached_pool,
            scoretable=new_scoretable,
            pending_sel=jax.tree_util.tree_map(
                lambda x: x[None], new_psel
            ),
            sel_counts=new_sel_counts,
        )
        metrics = {
            "train/loss": upd["loss_mean"],
            "train/acc": upd["acc"],
            "train/pool_loss": lax.pmean(avg_pool_loss, axis),
            "train/sparse_rate": lax.pmean(upd["sparse_rate"], axis),
            "train/moe_aux": lax.pmean(upd["moe_aux"], axis),
        }
        if telemetry:
            metrics["sampler/ess"] = lax.pmean(
                ess_fraction(scaled_probs), axis
            )
            metrics["sampler/clip_frac"] = lax.pmean(clip_frac, axis)
            metrics["sampler/ema_drift"] = lax.pmean(drift, axis)
            metrics["train/grad_norm"] = grad_norm
            if use_scoretable and not async_refresh:
                metrics["sampler/table_age_min"] = age_min
                metrics["sampler/table_age_mean"] = age_mean
                metrics["sampler/table_age_max"] = age_max
            if use_is:
                w_hist = lax.psum(
                    log_bin_histogram(
                        scaled_probs, WEIGHT_HIST_LO, WEIGHT_HIST_HI
                    ),
                    axis,
                )
                for i, k in enumerate(hist_keys("w_hist")):
                    metrics[k] = w_hist[i]
            if use_scoretable:
                for i, k in enumerate(hist_keys("score_hist")):
                    metrics[k] = score_hist[i]
            if use_probe:
                metrics["sampler_dist/var_ratio"] = lax.pmean(
                    var_ratio, axis
                )
        return new_state, metrics, next_gidx

    if host_stream:
        fn = hs_body
    elif scan_steps > 1:
        def chunk(state, x_train, y_train, shard_indices):
            def scan_body(s, _):
                return body(s, x_train, y_train, shard_indices)

            return lax.scan(scan_body, state, None, length=scan_steps)

        fn = chunk
    else:
        fn = body

    specs = _state_specs(axis, has_groupwise=use_groupwise,
                         has_pending=pipelined, zero_sharding=zero,
                         has_cached_pool=use_cadence,
                         has_scoretable=use_scoretable,
                         has_pending_sel=host_stream,
                         has_sel_counts=use_ledger)
    smap_kw = {}
    if auto_axes:
        # Manual over the data axis only; GSPMD handles the rest.
        smap_kw["axis_names"] = frozenset({axis})
    raw_rng = bool(auto_axes) and not MODERN_JAX
    if raw_rng:
        # Legacy partial-manual lowering rejects PRNG key leaves in the
        # body's out_specs (the hidden [..., 2] payload dim is missing
        # from the tile assignment — see compat.MODERN_JAX). Carry the
        # rng across the shard_map boundary as raw uint32 and rewrap it
        # just inside/outside; P(axis) prefixes the extra dim fine.
        inner_fn = fn

        def fn(state, x_train, y_train, shard_indices):
            state = state.replace(rng=jax.random.wrap_key_data(state.rng))
            new_state, metrics = inner_fn(
                state, x_train, y_train, shard_indices)
            return new_state.replace(
                rng=jax.random.key_data(new_state.rng)), metrics

    # host_stream: x is the per-worker streamed rows ([W, S, ...] — sharded
    # like the indices that drew them) while y stays the replicated label
    # table the in-graph gathers index; the third output is the next
    # selection's global indices, one row per worker.
    x_spec = P(axis) if (data_sharded or host_stream) else P()
    y_spec = P(axis) if data_sharded else P()
    out_specs_t = (specs, P(), P(axis)) if host_stream else (specs, P())
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(specs, x_spec, y_spec, P(axis)),
        out_specs=out_specs_t,
        check_vma=False,
        **smap_kw,
    )
    if raw_rng:
        inner_sharded = sharded

        def sharded(state, x_train, y_train, shard_indices):
            state = state.replace(rng=jax.random.key_data(state.rng))
            new_state, metrics = inner_sharded(
                state, x_train, y_train, shard_indices)
            return new_state.replace(
                rng=jax.random.wrap_key_data(new_state.rng)), metrics

    if io_constraints:
        from jax.sharding import NamedSharding

        # SHARDING CONTRACT (see docstring): pin the data inputs' layouts
        # at the step boundary, outside the shard_map, so any caller-side
        # layout drift surfaces as one explicit reshard here — not as
        # GSPMD rewrites inside the program. Layer 3 budgets these
        # constraint ops per plan (lint/shard_budgets.json).
        x_ns = NamedSharding(mesh, x_spec)
        y_ns = NamedSharding(mesh, y_spec)
        idx_ns = NamedSharding(mesh, P(axis))
        constrained_inner = sharded

        def sharded(state, x_train, y_train, shard_indices):
            x_train = jax.lax.with_sharding_constraint(x_train, x_ns)
            y_train = jax.lax.with_sharding_constraint(y_train, y_ns)
            shard_indices = jax.lax.with_sharding_constraint(
                shard_indices, idx_ns)
            return constrained_inner(state, x_train, y_train,
                                     shard_indices)

    jit_kw = {}
    if state_out_shardings is not None:
        jit_kw["out_shardings"] = state_out_shardings
    # host_stream also donates the streamed slab (arg 1): the rows are
    # consumed by this step only (trainer pops, dispatches, drops — see
    # Trainer._host_stream_step), and without the donation the slab stays
    # live across the whole step, blocking the H2D-for-t+1 / compute
    # overlap the lookahead exists to buy. The non-donated next_gidx
    # output never aliases it (int32 [W, S] vs uint8 rows), so the
    # PendingSelection outputs no longer pin the buffer. Layer-3's
    # memory_analysis() ratchet + the Layer-2 donation-consistency check
    # (lint/audit.py) pin this down per plan. donate_argnums is the
    # compat shim: () on legacy jax (persistent-cache aliasing bug).
    donated = donate_argnums(0, 1) if host_stream else donate_argnums(0)
    return jax.jit(sharded, donate_argnums=donated, **jit_kw)


def make_host_stream_prime(config: TrainConfig, mesh: Mesh):
    """Cold-start primer for ``data_placement="host_stream"``: one jitted
    shard_map that draws the first ``prefetch_depth`` selections UNIFORMLY
    (the reference's cold start — the table/scores don't exist yet),
    advancing the per-worker rng/stream chains exactly as ``hs_body``'s
    lookahead would have, and fills the ``PendingSelection`` ring.

    Returns ``prime(state, shard_indices) -> (state, gidx)`` with ``gidx``
    ``[depth, W, S]`` int32 global indices — one prefetch push per ring
    slot. For uniform/pool samplers the primed draws are the exact draws
    ``replicated`` would make at steps 0..depth-1 (``next_pool`` with each
    step's stream key), so trajectories match from step 0; the scoretable
    sampler primes with uniform-with-replacement draws plus the
    deterministic round-robin refresh windows (unit ``scaled_probs`` keep
    step 0..depth-1 unbiased)."""
    axis = config.mesh_axis
    depth = int(config.prefetch_depth)
    use_is = bool(config.use_importance_sampling)
    use_scoretable = use_is and config.sampler == "scoretable"
    batch_size = int(config.batch_size)
    pool_size = int(config.candidate_pool_size) if use_is else int(
        config.batch_size)
    refresh_size = int(config.refresh_size)
    async_refresh = use_scoretable and config.refresh_mode == "async"
    emit_size = (batch_size if async_refresh
                 else (refresh_size + batch_size) if use_scoretable
                 else pool_size)
    # Same gate as make_train_step: the ledger exists iff the step carries
    # it — the prime passes it through untouched, but the spec prefix must
    # cover the field.
    use_ledger = use_scoretable and bool(config.telemetry)

    def prime(state: MercuryState, shard_indices):
        stream = ShardStream(perm=state.stream.perm[0],
                             cursor=state.stream.cursor[0])
        sel_rng = state.rng[0]
        slots_steps = []
        for i in range(depth):
            ks = jax.random.split(sel_rng, 8)
            if use_scoretable:
                table = jax.tree_util.tree_map(
                    lambda x: x[0], state.scoretable
                )
                n = table.scores.shape[0]
                # Uniform-with-replacement through the SAME draw kernel the
                # steady state uses, on the flat distribution — consumes
                # k_sel exactly as hs_body's lookahead will.
                flat = jnp.full((n,), 1.0 / n, jnp.float32)
                if async_refresh:
                    drawn = table_draw_inverse_cdf(ks[2], flat, batch_size)
                else:
                    drawn = draw_with_replacement(
                        ks[2], flat, batch_size
                    ).astype(jnp.int32)
                if async_refresh:
                    # Async streams train rows only (the fleet owns the
                    # refresh sweep) — no window rows to prime.
                    slots_i = drawn
                else:
                    window = (
                        (table.cursor + i * refresh_size
                         + jnp.arange(refresh_size)) % n
                    ).astype(jnp.int32)
                    slots_i = jnp.concatenate([window, drawn])
            else:
                stream, slots_i = next_pool(stream, ks[0], emit_size)
                slots_i = slots_i.astype(jnp.int32)
            slots_steps.append(slots_i)
            sel_rng = ks[7]
        slots = jnp.stack(slots_steps)                 # [depth, S]
        gidx = shard_indices[0][slots]                 # [depth, S] global
        psel = PendingSelection(
            slots=slots[None],
            scaled_probs=jnp.ones((1, depth, batch_size), jnp.float32),
            rng=jax.random.key_data(sel_rng)[None],
        )
        new_state = state.replace(
            stream=ShardStream(perm=stream.perm[None],
                               cursor=stream.cursor[None]),
            pending_sel=psel,
        )
        # [depth, 1, S]: stacked pushes, worker row sharded P(axis).
        return new_state, gidx[:, None]

    specs = _state_specs(
        axis, zero_sharding=config.zero_sharding,
        has_scoretable=use_scoretable, has_pending_sel=True,
        has_sel_counts=use_ledger,
    )
    sharded = shard_map(
        prime,
        mesh=mesh,
        in_specs=(specs, P(axis)),
        out_specs=(specs, P(None, axis)),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_eval_step(model) -> Callable[..., Tuple[jax.Array, jax.Array, jax.Array]]:
    """Jitted eval on one fixed-size batch with a validity mask.

    ≡ the inner loop of ``Trainer.evaluate`` (``pytorch_collab.py:201-234``):
    inference-mode forward (BN running averages — the ``eval()`` flip at
    ``:207``), summed loss/correct counts. Returns
    ``(loss_sum, correct, n)`` for meter accumulation.
    """

    def eval_fn(params, batch_stats, images, labels, valid_n):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, images, train=False)
        losses = per_sample_loss(logits, labels)
        mask = (jnp.arange(images.shape[0]) < valid_n).astype(jnp.float32)
        loss_sum = jnp.sum(losses * mask)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.float32) * mask)
        return loss_sum, correct, jnp.sum(mask)

    return jax.jit(eval_fn)


def make_per_class_epoch(
    model, mean: np.ndarray, std: np.ndarray, num_classes: int,
    eval_augmentation: str = "none",
    mesh: Optional[Mesh] = None, axis: str = "data",
) -> Callable[..., Tuple[jax.Array, jax.Array]]:
    """One-dispatch per-class (hits, totals) over pre-batched eval arrays —
    same scan/sharding structure as :func:`make_eval_epoch`, with a
    scatter-add per batch instead of scalar sums. Returns int32 ``[C]``
    pairs for host-side division."""
    from mercury_tpu.data.pipeline import normalize_images

    def per_class_epoch(params, batch_stats, images_b, labels_b, valid_b):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats

        def body(carry, batch):
            imgs_u8, labels, mask = batch
            imgs = normalize_images(imgs_u8, mean, std)
            if eval_augmentation == "iid":
                from mercury_tpu.data.transforms import eval_transform_iid

                imgs = eval_transform_iid(jax.random.key(0), imgs)
            logits = model.apply(variables, imgs, train=False)
            maski = mask.astype(jnp.int32)
            hit = (jnp.argmax(logits, -1) == labels).astype(jnp.int32) * maski
            hits, totals = carry
            return (hits.at[labels].add(hit),
                    totals.at[labels].add(maski)), None

        init = (jnp.zeros((num_classes,), jnp.int32),
                jnp.zeros((num_classes,), jnp.int32))
        (hits, totals), _ = jax.lax.scan(
            body, init, (images_b, labels_b, valid_b)
        )
        return hits, totals

    if mesh is None:
        return jax.jit(per_class_epoch)
    from jax.sharding import NamedSharding

    from mercury_tpu.parallel.mesh import replicated_sharding

    rep = replicated_sharding(mesh)
    batched = NamedSharding(mesh, P(None, axis))
    return jax.jit(
        per_class_epoch,
        in_shardings=(rep, rep, batched, batched, batched),
        out_shardings=(rep, rep),
    )


def make_eval_epoch(
    model, mean: np.ndarray, std: np.ndarray, eval_augmentation: str = "none",
    mesh: Optional[Mesh] = None, axis: str = "data",
) -> Callable[..., Tuple[jax.Array, jax.Array, jax.Array]]:
    """One-dispatch full-split eval: ``lax.scan`` over pre-batched uint8
    arrays, normalize + forward + masked reduce in-graph.

    The reference's ``evaluate`` walks a DataLoader batch-by-batch from the
    host (``pytorch_collab.py:201-234``); a whole split here is a single
    device call — this matters when dispatch latency is non-trivial (e.g. a
    tunneled chip: ~24 host round trips become 1).

    With ``mesh``, each scanned batch's sample dimension is sharded over
    the mesh's data axis (``in_shardings`` only — GSPMD partitions the
    forward and inserts the reduction collectives), so eval uses every
    device instead of leaving W−1 idle.

    ``eval_augmentation="iid"`` applies the reference IID path's *test*
    transform — resize(33) → random crop(32) (``exp_dataset.py:63-68``; yes,
    the reference random-crops at eval) — with a fixed key per batch so
    eval stays deterministic. The live non-IID path normalizes only
    (``cifar10/data_loader.py:92-96``).
    """
    from mercury_tpu.data.pipeline import normalize_images

    def eval_epoch(params, batch_stats, images_b, labels_b, valid_b):
        # images_b: [nb, B, H, W, C] uint8; labels_b: [nb, B]; valid_b: [nb, B]
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats

        def body(carry, batch):
            imgs_u8, labels, mask = batch
            imgs = normalize_images(imgs_u8, mean, std)
            if eval_augmentation == "iid":
                from mercury_tpu.data.transforms import eval_transform_iid

                # Deterministic: key derived from the batch's first label
                # sum is overkill — a fixed key is what "same transform
                # every eval" means here.
                imgs = eval_transform_iid(jax.random.key(0), imgs)
            logits = model.apply(variables, imgs, train=False)
            losses = per_sample_loss(logits, labels)
            maskf = mask.astype(jnp.float32)
            hit = (jnp.argmax(logits, -1) == labels).astype(jnp.float32)
            loss_sum, correct, count = carry
            return (
                loss_sum + jnp.sum(losses * maskf),
                correct + jnp.sum(hit * maskf),
                count + jnp.sum(maskf),
            ), None

        init = (jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))
        (loss_sum, correct, count), _ = jax.lax.scan(
            body, init, (images_b, labels_b, valid_b)
        )
        return loss_sum, correct, count

    if mesh is None:
        return jax.jit(eval_epoch)
    from jax.sharding import NamedSharding

    from mercury_tpu.parallel.mesh import replicated_sharding

    rep = replicated_sharding(mesh)
    batched = NamedSharding(mesh, P(None, axis))  # [nb, B, ...]: shard B
    return jax.jit(
        eval_epoch,
        in_shardings=(rep, rep, batched, batched, batched),
        out_shardings=(rep, rep, rep),
    )
