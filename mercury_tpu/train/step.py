"""The fused SPMD Mercury train step.

One jitted ``shard_map`` program per step does everything the reference's hot
loop does across Python/gloo boundaries (``pytorch_collab.py:119-199`` —
SURVEY.md §3.2): pull presample candidates, score them (10 inference
forwards in the reference — here **one batched forward** over the whole
pool), EMA-smooth, draw the train batch with replacement, compute the
unbiased reweighted loss, backprop, allreduce gradients, and apply the
optimizer — with the collectives (gradient pmean ≡ ``average_gradients``
``:236-249``, importance-stat psum = north-star extension) fused in-graph by
XLA. The compute/communication overlap the reference only gestures at in
commented-out thread code (``:154-156``) falls out for free: XLA schedules
the ICI collectives asynchronously against independent compute.

Per-worker divergence (the whole point of Mercury on non-IID data: each
worker scores its *own* Dirichlet shard) lives on the mesh's data axis:
shard index rows, presample streams, EMAs, and RNG keys are ``[W]``-stacked
and sharded; params/optimizer state are replicated.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mercury_tpu.config import TrainConfig
from mercury_tpu.data.pipeline import ShardStream, augment_batch, next_pool, normalize_images
from mercury_tpu.parallel.collectives import allreduce_mean_tree
from mercury_tpu.sampling.importance import (
    EMAState,
    per_sample_loss,
    reweighted_loss,
    select_from_pool,
)
from mercury_tpu.train.state import MercuryState

from jax import shard_map


def _state_specs(axis: str) -> MercuryState:
    """PartitionSpec pytree-prefix for :class:`MercuryState`: model/opt state
    replicated, per-worker sampler state sharded along the data axis."""
    return MercuryState(
        step=P(),
        params=P(),
        batch_stats=P(),
        opt_state=P(),
        ema=EMAState(value=P(axis), count=P(axis)),
        stream=ShardStream(perm=P(axis), cursor=P(axis)),
        rng=P(axis),
    )


def make_train_step(
    model,
    tx: optax.GradientTransformation,
    config: TrainConfig,
    mesh: Mesh,
    mean: np.ndarray,
    std: np.ndarray,
) -> Callable[..., Tuple[MercuryState, Dict[str, jax.Array]]]:
    """Build the jitted train step.

    Returns ``step_fn(state, x_train, y_train, shard_indices) →
    (new_state, metrics)`` where ``x_train``/``y_train`` are the full
    device-resident train arrays (replicated) and ``shard_indices`` is the
    ``[W, L]`` per-worker index matrix (sharded over the data axis).
    """
    axis = config.mesh_axis
    use_is = config.use_importance_sampling
    pool_size = config.candidate_pool_size if use_is else config.batch_size
    batch_size = config.batch_size
    stat_axis = axis if (use_is and config.sync_importance_stats) else None

    use_pallas = config.use_pallas
    if use_pallas is None:  # auto: Mosaic kernels on real TPU only
        from mercury_tpu.ops import on_tpu

        use_pallas = on_tpu()
    if use_pallas and config.label_smoothing != 0.0:
        raise ValueError("use_pallas requires label_smoothing == 0")

    def _loss_per_sample(logits, labels):
        if use_pallas:
            from mercury_tpu.ops import per_sample_nll_pallas

            return per_sample_nll_pallas(logits, labels)
        return per_sample_loss(logits, labels, config.label_smoothing)

    def _apply_train(params, batch_stats, images, keep_stats: bool):
        """Train-mode forward. ``keep_stats=False`` (the scoring pass) uses
        batch statistics for normalization but discards the running-stat
        update — the clean version of the reference's quirk where
        ``update_samples``'s no_grad forwards still mutate BN running means
        (``pytorch_collab.py:101`` runs the net in train mode)."""
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
            logits, new_model_state = model.apply(
                variables, images, train=True, mutable=["batch_stats"]
            )
            new_stats = new_model_state["batch_stats"] if keep_stats else batch_stats
            return logits, new_stats
        return model.apply(variables, images, train=True), batch_stats

    def body(state: MercuryState, x_train, y_train, shard_indices):
        # Leading axis inside shard_map is this device's single worker row.
        rng = state.rng[0]
        k_stream, k_aug, k_sel, k_next = jax.random.split(rng, 4)

        # --- presample pool: next `pool_size` samples of this worker's shard
        # (≡ Trainer.get_next over the presampling loader, :74-82) ----------
        stream = ShardStream(perm=state.stream.perm[0], cursor=state.stream.cursor[0])
        stream, slots = next_pool(stream, k_stream, pool_size)
        global_idx = shard_indices[0][slots]
        images = normalize_images(x_train[global_idx], mean, std)
        if config.augmentation == "noniid":
            images = augment_batch(k_aug, images, use_cutout=config.cutout)
        elif config.augmentation == "iid":
            from mercury_tpu.data.transforms import augment_batch_iid

            images = augment_batch_iid(k_aug, images)
        elif config.augmentation != "none":
            raise ValueError(f"unknown augmentation {config.augmentation!r}")
        labels = y_train[global_idx]

        ema = EMAState(value=state.ema.value[0], count=state.ema.count[0])

        if use_is:
            # --- importance scoring: ONE batched inference forward over the
            # pool (≡ the 10-iteration no_grad loop, :95-106), batch-stat
            # normalization, running-stat updates discarded ----------------
            pool_logits, _ = _apply_train(state.params, state.batch_stats, images, False)
            pool_losses = _loss_per_sample(pool_logits, labels)
            if use_pallas:
                # Fused Pallas score→normalize→draw→p·N kernel; EMA update
                # and the (optional) cross-worker stat psum stay outside —
                # they are scalars.
                from mercury_tpu.ops import score_and_draw_pallas
                from mercury_tpu.sampling.importance import ema_update, pool_mean

                avg_pool_loss = pool_mean(pool_losses, stat_axis)
                ema = ema_update(ema, avg_pool_loss, config.ema_alpha)
                _, selected, scaled_probs = score_and_draw_pallas(
                    k_sel, pool_losses, ema.value, batch_size, config.is_alpha
                )
            else:
                sel = select_from_pool(
                    k_sel, pool_losses, ema, batch_size,
                    is_alpha=config.is_alpha, ema_alpha=config.ema_alpha,
                    axis_name=stat_axis,
                )
                selected, scaled_probs = sel.selected, sel.scaled_probs
                ema = sel.ema
                avg_pool_loss = sel.avg_pool_loss
        else:
            # Uniform baseline: consume the freshly streamed batch directly —
            # the stream is a shuffled without-replacement epoch pass, i.e.
            # standard shuffled-loader SGD — with unit IS weights so
            # loss/(N·p) = loss.
            selected = jnp.arange(batch_size, dtype=jnp.int32)
            scaled_probs = jnp.ones((batch_size,), jnp.float32)
            avg_pool_loss = jnp.zeros((), jnp.float32)

        sel_images = images[selected]
        sel_labels = labels[selected]

        # --- train forward/backward with the unbiased IS reweighting
        # mean(loss_i/(N·p_i)) (:132-148) --------------------------------
        def loss_fn(params):
            logits, new_bs = _apply_train(params, state.batch_stats, sel_images, True)
            losses = _loss_per_sample(logits, sel_labels)
            return reweighted_loss(losses, scaled_probs), (logits, new_bs)

        (loss, (logits, new_batch_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)

        # --- gradient allreduce (≡ average_gradients, :236-249) — in-graph
        grads = allreduce_mean_tree(grads, axis)
        loss_mean = lax.pmean(loss, axis)
        correct = lax.psum(
            jnp.sum((jnp.argmax(logits, -1) == sel_labels).astype(jnp.float32)), axis
        )
        count = lax.psum(jnp.asarray(batch_size, jnp.float32), axis)

        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        # Keep replicated BN stats replicated: under synced BN they already
        # agree; under local BN we average the running stats across workers
        # (normalization still used local batch stats this step).
        if new_batch_stats:
            new_batch_stats = allreduce_mean_tree(new_batch_stats, axis)

        new_state = MercuryState(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_batch_stats,
            opt_state=new_opt_state,
            ema=EMAState(value=ema.value[None], count=ema.count[None]),
            stream=ShardStream(perm=stream.perm[None], cursor=stream.cursor[None]),
            rng=k_next[None],
        )
        metrics = {
            "train/loss": loss_mean,
            "train/acc": correct / count,
            "train/pool_loss": lax.pmean(avg_pool_loss, axis),
        }
        return new_state, metrics

    specs = _state_specs(axis)
    sharded = shard_map(
        body,
        mesh=mesh,
        in_specs=(specs, P(), P(), P(axis)),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(sharded, donate_argnums=(0,))


def make_eval_step(model) -> Callable[..., Tuple[jax.Array, jax.Array, jax.Array]]:
    """Jitted eval on one fixed-size batch with a validity mask.

    ≡ the inner loop of ``Trainer.evaluate`` (``pytorch_collab.py:201-234``):
    inference-mode forward (BN running averages — the ``eval()`` flip at
    ``:207``), summed loss/correct counts. Returns
    ``(loss_sum, correct, n)`` for meter accumulation.
    """

    def eval_fn(params, batch_stats, images, labels, valid_n):
        variables = {"params": params}
        if batch_stats:
            variables["batch_stats"] = batch_stats
        logits = model.apply(variables, images, train=False)
        losses = per_sample_loss(logits, labels)
        mask = (jnp.arange(images.shape[0]) < valid_n).astype(jnp.float32)
        loss_sum = jnp.sum(losses * mask)
        correct = jnp.sum((jnp.argmax(logits, -1) == labels).astype(jnp.float32) * mask)
        return loss_sum, correct, jnp.sum(mask)

    return jax.jit(eval_fn)
