"""Checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5 — no ``torch.save``
anywhere; a crash loses the run). Here the whole :class:`MercuryState`
pytree — params, optimizer state, BN stats, **and** the sampler state (EMA,
presample streams, per-worker RNG keys) — serializes, so importance-sampled
training resumes bit-deterministically.

Primary backend is Orbax (the idiomatic JAX checkpointer); a msgpack
fallback (``flax.serialization``) covers environments where Orbax's API is
unavailable.

Multi-controller runs: saving all-gathers cross-process-sharded leaves
(collectively) and writes from process 0 only; restoring reads the file on
every process — the checkpoint directory must therefore be shared across
hosts (NFS/GCS) in multi-host runs.
"""

from __future__ import annotations

import functools
import hashlib
import json
import os
import re
import threading
import time
from typing import Any, Dict, Optional, Tuple

import jax

from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.train.checkpoint")

# Failed write ATTEMPTS (a save that succeeds on retry 2 still counts 2):
# the trainer folds this into the log gate as ``checkpoint/write_failures``
# so a flaky checkpoint filesystem is visible long before a restore needs
# the file. Incremented from both the trainer thread (sync saves) and
# ckpt-write-* threads (async saves), hence the lock.
_fail_lock = threading.Lock()
_write_failures = 0


def write_failures() -> int:
    """Cumulative failed checkpoint-write attempts in this process."""
    with _fail_lock:
        return _write_failures


def _count_write_failure() -> None:
    global _write_failures
    with _fail_lock:
        _write_failures += 1


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step}")


def _is_key(x) -> bool:
    try:
        import jax.dtypes

        return jax.dtypes.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key)
    except Exception:
        return False


def _unwrap_keys(tree: Any) -> Any:
    """PRNG key arrays → raw uint32 key data (serializable)."""
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rewrap_keys(template: Any, tree: Any) -> Any:
    """Inverse of :func:`_unwrap_keys`, guided by the template's key leaves."""
    import jax.numpy as jnp
    import numpy as np

    return jax.tree_util.tree_map(
        lambda t, r: (
            jax.random.wrap_key_data(jnp.asarray(np.asarray(r)))
            if _is_key(t) else r
        ),
        template, tree,
    )


@functools.lru_cache(maxsize=None)
def _replicate_fn(sharding):
    """Cached jitted identity → fully-replicated placement (an all-gather
    for cross-process-sharded inputs). Cached per target sharding so
    repeated checkpoint saves are compile-cache hits."""
    return jax.jit(lambda a: a, out_shardings=sharding)


def _host_gather(tree: Any) -> Any:
    """``device_get`` that also works in multi-controller runs: any leaf
    sharded across processes (not fully addressable — e.g. the per-worker
    sampler state placed ``P("data")`` by ``globalize_state``) is first
    resharded to fully-replicated via a jitted identity, which XLA lowers
    to an all-gather. Every process must call this collectively — true for
    the checkpoint cadence inside ``fit`` since all processes run the same
    program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            x = _replicate_fn(NamedSharding(x.sharding.mesh, P()))(x)
        return x

    return jax.device_get(jax.tree_util.tree_map(fetch, tree))


def save_checkpoint(directory: str, state: Any, step: int, *,
                    keep: int = 0, retries: int = 0,
                    retry_backoff_s: float = 0.25, manifest: bool = False,
                    faults=None, journal=None) -> str:
    """Save ``state`` under ``directory/ckpt_<step>``.

    Multi-controller: all processes participate in the host gather (a
    collective), then only process 0 writes — a shared checkpoint
    directory sees exactly one writer.

    Durability knobs (all default-off so direct callers keep the seed
    behavior): ``manifest=True`` writes a ``ckpt_<step>.manifest.json``
    sidecar with whole-file + per-leaf sha256 (and forces the msgpack
    backend, whose bytes the manifest describes, over Orbax);
    ``retries``/``retry_backoff_s`` retry transient ``OSError`` writes
    with exponential backoff; ``keep`` prunes to the newest N generations
    after a successful write. ``faults`` threads the injection plane
    through to the write hook; ``journal`` (obs/events.py) records each
    durable generation as a ``checkpoint/written`` event."""
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    to_save = _host_gather(_unwrap_keys(state))
    if jax.process_count() > 1:
        # Multi-controller: process 0 writes msgpack (self-contained — no
        # hidden barriers; Orbax's save runs internal cross-process syncs
        # that would deadlock against ours when only one process calls it),
        # then a barrier so no process can proceed to a restore before the
        # writer is done. The barrier sits in a finally so a write failure
        # on process 0 re-raises there instead of hanging everyone else.
        try:
            if jax.process_index() == 0:
                _write_with_retries(
                    path, to_save, retries=retries,
                    retry_backoff_s=retry_backoff_s, manifest=manifest,
                    faults=faults)
                _prune_old(directory, keep)
                _journal_written(journal, step, path, manifest)
        finally:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"mercury_ckpt_save_{step}")
        return path
    if not manifest:
        ocp = _orbax()
        if ocp is not None:
            try:
                ckptr = ocp.PyTreeCheckpointer()
                ckptr.save(os.path.abspath(path), to_save, force=True)
                _prune_old(directory, keep)
                _journal_written(journal, step, path, manifest)
                return path
            except Exception:
                pass
    _write_with_retries(path, to_save, retries=retries,
                        retry_backoff_s=retry_backoff_s, manifest=manifest,
                        faults=faults)
    _prune_old(directory, keep)
    _journal_written(journal, step, path, manifest)
    return path


def _journal_written(journal, step: int, path: str,
                     manifest: bool) -> None:
    """Record a durable generation in the control-plane journal (no-op
    when journaling is off; never raises into the save path)."""
    if journal is None:
        return
    try:
        journal.emit("checkpoint/written", int(step),
                     detail={"path": path, "manifest": bool(manifest)})
    except Exception:
        pass


def _sweep_stale_tmps(directory: str, min_age_secs: float = 300.0) -> None:
    """Unlink ``.msgpack.tmp`` strays left by a crash mid-write. Without
    it, each preempted run leaks a checkpoint-sized orphan into the
    (possibly shared) directory.

    Only temps older than ``min_age_secs`` are removed: the restore path
    also runs mid-run (elastic resume restores into a live trainer), where
    an async writer's fresh ``.tmp`` may legitimately be in flight — age
    gating means a racing sweep can never unlink a file another process
    (or this one's writer thread) is about to ``os.replace``. Crash
    orphans are by definition older than any live write."""
    if jax.process_index() != 0:
        return
    now = time.time()
    try:
        for name in os.listdir(directory):
            if name.endswith(".msgpack.tmp"):
                path = os.path.join(directory, name)
                try:
                    if now - os.path.getmtime(path) >= min_age_secs:
                        os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass


# --------------------------------------------------------------------------
# state-schema lineage + upgrade shims (graftlint Layer E contract)
# --------------------------------------------------------------------------

#: Ordered history of the on-disk ``MercuryState`` schema: each entry is
#: ``(version, fields_added)``. A PURE literal — graftlint Layer E
#: (``lint/state.py``) parses it with ``ast.literal_eval`` and checks
#: (GLE04) that every consecutive pair has an upgrade shim, so every
#: committed checkpoint vintage can reach HEAD. Append-only: a new
#: ``MercuryState`` field means a new version here plus a shim below.
STATE_SCHEMA_LINEAGE = (
    ("v1", ()),
    ("v2_cursor", ("pending_sel",)),
    ("v3_ledger", ("sel_counts",)),
)

#: The schema version this build WRITES (must equal the last lineage
#: entry — GLE04 errors otherwise).
STATE_SCHEMA_VERSION = "v3_ledger"


def _upgrade_v1_to_v2(raw: Dict[str, Any], template: Any) -> Any:
    """v1 → v2_cursor: checkpoints older than the host-stream cursor
    (or written by a run without ``data_placement="host_stream"``) carry
    no ``pending_sel`` ring. The ring is transient in-flight state
    (policy ``drop-on-shrink``) — drop it from the template and let the
    Trainer re-prime it; never fail the whole resume over it."""
    field = "pending_sel"
    if getattr(template, field, None) is not None and raw.get(field) is None:
        template = template.replace(pending_sel=None)
    return template


def _upgrade_v2_to_v3(raw: Dict[str, Any], template: Any) -> Any:
    """v2_cursor → v3_ledger: checkpoints older than the selection-count
    ledger (or from a telemetry=False run) carry no ``sel_counts``
    entry. Restoring one into a ledger-bearing template must not fail
    the resume — drop the field from the template and let the caller
    keep its fresh zero ledger (policy ``re-aggregate`` over an empty
    history is zeros)."""
    field = "sel_counts"
    if getattr(template, field, None) is not None and raw.get(field) is None:
        template = template.replace(sel_counts=None)
    return template


#: ``(older, newer) -> shim`` for every consecutive lineage pair. Each
#: shim is idempotent (a raw tree that already carries the field passes
#: through untouched), so :func:`apply_upgrade_shims` can walk the whole
#: chain unconditionally instead of guessing the on-disk version — field
#: presence alone cannot distinguish "old checkpoint" from "HEAD run
#: with the feature off", and both want the same template adjustment.
UPGRADE_SHIMS = {
    ("v1", "v2_cursor"): _upgrade_v1_to_v2,
    ("v2_cursor", "v3_ledger"): _upgrade_v2_to_v3,
}


def apply_upgrade_shims(raw: Any, template: Any) -> Any:
    """Walk the upgrade-shim chain over a raw (state-dict) checkpoint
    tree, returning the template adjusted for fields the checkpoint
    predates. Raises ``ValueError`` when ``raw`` carries state fields
    this build does not know — a checkpoint written by a NEWER schema
    must fail loudly rather than silently drop state on restore."""
    import dataclasses

    if not isinstance(raw, dict):
        return template
    try:
        known = {f.name for f in dataclasses.fields(type(template))}
    except TypeError:
        known = None
    if known is not None:
        unknown = sorted(set(raw) - known)
        if unknown:
            raise ValueError(
                f"checkpoint carries unknown state field(s) {unknown}: "
                "written by a newer state schema than this build "
                f"understands (HEAD is {STATE_SCHEMA_VERSION!r}); "
                "refusing to restore — state would be silently dropped")
    versions = [v for v, _ in STATE_SCHEMA_LINEAGE]
    for pair in zip(versions, versions[1:]):
        shim = UPGRADE_SHIMS.get(pair)
        if shim is not None:
            template = shim(raw, template)
    return template


def _state_schema_golden_path() -> str:
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "lint", "state_schema.json")


def state_schema_sha(path: Optional[str] = None) -> Optional[str]:
    """The committed Layer E state-schema digest (the
    ``state_schema_sha`` field of ``lint/state_schema.json``), or None
    when the golden is absent/unreadable. Stamped into every checkpoint
    manifest so restore can warn when a checkpoint predates the schema
    the running build was linted against."""
    path = path or _state_schema_golden_path()
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    sha = doc.get("state_schema_sha")
    return sha if isinstance(sha, str) else None


def _leaf_digests(to_save: Any) -> Dict[str, str]:
    """Per-leaf sha256 of the HOST value bytes, keyed by keypath string.
    Restore verifies these after parsing, so a bit flip localizes to the
    leaf it hit (``params/conv1/kernel``) instead of "file bad"."""
    import numpy as np

    leaves, _ = jax.tree_util.tree_flatten_with_path(to_save)
    out: Dict[str, str] = {}
    for kp, leaf in leaves:
        arr = np.asarray(leaf)
        out[jax.tree_util.keystr(kp)] = hashlib.sha256(
            arr.tobytes()).hexdigest()
    return out


def _manifest_path(path: str) -> str:
    return path + ".manifest.json"


def _write_manifest(path: str, file_sha: str, nbytes: int, step: int,
                    to_save: Any) -> None:
    """Atomic sidecar write (tmp + replace, same discipline as the
    payload). Ordered AFTER the payload rename: a crash in the gap
    leaves a checkpoint without a manifest — restore then skips
    verification (back-compat), never a manifest describing a file that
    does not exist."""
    doc = {
        "schema": "mercury-ckpt-manifest-v1",
        "step": int(step),
        "file": os.path.basename(path) + ".msgpack",
        "sha256": file_sha,
        "bytes": int(nbytes),
        "state_schema_sha": state_schema_sha(),
        "leaves": _leaf_digests(to_save),
    }
    final = _manifest_path(path)
    tmp = final + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _write_msgpack(path: str, to_save: Any, *, manifest: bool = False,
                   faults=None) -> None:
    """Atomic write: serialize to a temp file, then ``os.replace`` into
    place. A hard crash (SIGKILL/preemption — the exact scenario
    ``auto_resume`` targets) mid-write therefore leaves only a stray
    ``.tmp``, never a truncated ``ckpt_<step>.msgpack`` that
    :func:`latest_step` would pick as newest. Any failure unlinks the
    partial ``.tmp`` before re-raising — retries and crashed saves must
    not accumulate checkpoint-sized orphans in a (possibly shared)
    directory."""
    import flax.serialization

    if faults is not None and faults.fire("ckpt_io_error") is not None:
        # Before the open(): the injected failure models ENOSPC/EIO at
        # the filesystem boundary, and must leave no partial state.
        raise OSError("ckpt_io_error: injected checkpoint write failure")
    final = path + ".msgpack"
    tmp = final + ".tmp"
    try:
        blob = flax.serialization.to_bytes(to_save)
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, final)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    # fsync the directory too: the rename itself is metadata, and on a
    # journaled filesystem a crash right after os.replace can otherwise
    # lose the directory entry for the new name.
    dir_fd = os.open(os.path.dirname(final) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)
    if manifest:
        m = re.search(r"ckpt_(\d+)$", path)
        step = int(m.group(1)) if m else -1
        _write_manifest(path, hashlib.sha256(blob).hexdigest(), len(blob),
                        step, to_save)


def _write_with_retries(path: str, to_save: Any, *, retries: int = 0,
                        retry_backoff_s: float = 0.25,
                        manifest: bool = False, faults=None) -> None:
    """Retry transient ``OSError`` writes with exponential backoff.
    Every failed ATTEMPT bumps the ``checkpoint/write_failures`` counter
    — a save that eventually lands still leaves its flakiness visible in
    telemetry."""
    attempt = 0
    while True:
        try:
            _write_msgpack(path, to_save, manifest=manifest, faults=faults)
            return
        except OSError as exc:
            attempt += 1
            _count_write_failure()
            if attempt > max(int(retries), 0):
                raise
            delay = retry_backoff_s * (2 ** (attempt - 1))
            _log.warning(
                "checkpoint write %s failed (attempt %d/%d): %s — "
                "retrying in %.2fs", path, attempt, retries + 1, exc, delay)
            time.sleep(delay)


def _prune_old(directory: str, keep: int) -> None:
    """Keep the newest ``keep`` checkpoint generations (``keep <= 0``
    keeps everything). Process 0 only, and only after a successful save
    — a failed write must never trigger pruning, or a string of failures
    would walk the directory down to zero restorable checkpoints."""
    if keep <= 0 or jax.process_index() != 0:
        return
    for step in all_steps(directory)[:-keep]:
        base = _ckpt_path(directory, step)
        for path in (base + ".msgpack", _manifest_path(base)):
            try:
                os.unlink(path)
            except OSError:
                pass
        if os.path.isdir(base):
            import shutil

            shutil.rmtree(base, ignore_errors=True)


class _AsyncSave:
    """Handle for an in-flight background checkpoint write. ``join()``
    blocks until the write completes and RE-RAISES any exception the
    writer thread hit (a silently missing cadence checkpoint would
    otherwise surface only as a much older restore after a preemption)."""

    def __init__(self, target, name: str, failure_cb=None):
        self._exc: Optional[BaseException] = None

        def runner():
            try:
                target()
            except BaseException as e:  # re-raised at join
                self._exc = e
                if failure_cb is not None:
                    try:
                        # Out-of-band failure report (the supervisor):
                        # join() may be a full cadence away, and a wedged
                        # run never joins at all.
                        failure_cb(e)
                    except Exception:
                        _log.warning("checkpoint failure_cb raised",
                                     exc_info=True)

        self._thread = threading.Thread(target=runner, name=name,
                                        daemon=False)
        self._thread.start()

    def done(self) -> bool:
        """True once the writer thread finished (success OR failure)."""
        return not self._thread.is_alive()

    def failed(self) -> Optional[BaseException]:
        """The writer's exception, if it has failed (non-blocking)."""
        return self._exc

    def join(self, timeout: Optional[float] = 600.0) -> None:
        """Wait for the write (default bound: 10 minutes — a full
        msgpack serialize + fsync on a slow NFS mount, with headroom).
        A writer still alive past the bound raises TimeoutError rather
        than hanging shutdown forever on a wedged filesystem: the
        thread is non-daemon, so the interpreter will still wait on it
        at exit, but the caller gets a loud, attributable failure
        instead of a silent hang here. If the writer had ALSO already
        latched an exception, it is chained as the TimeoutError's cause
        rather than silently shadowed."""
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            _log.warning("checkpoint writer %r still running after "
                         "%.0fs — wedged filesystem?",
                         self._thread.name, timeout)
            raise TimeoutError(
                f"checkpoint write ({self._thread.name}) did not "
                f"finish within {timeout:.0f}s") from self._exc
        if self._exc is not None:
            raise self._exc


def save_checkpoint_async(directory: str, state: Any, step: int, *,
                          keep: int = 0, retries: int = 0,
                          retry_backoff_s: float = 0.25,
                          manifest: bool = False, faults=None,
                          journal=None, failure_cb=None):
    """Non-blocking save: the device→host fetch happens synchronously (it
    must — the caller's next train step donates/overwrites the state
    buffers), then serialization + file IO run on a background thread so
    training resumes immediately. Returns an :class:`_AsyncSave` handle —
    ``join()`` it before reading the file or exiting; writer-thread
    failures re-raise there. ``failure_cb(exc)`` additionally fires ON
    the writer thread at failure time (the supervisor's prompt signal).
    Durability knobs as in :func:`save_checkpoint`.

    Single-process only: multi-controller saves need their cross-process
    barrier to stay on the caller's thread (collective ordering), so this
    falls back to the synchronous path there (returning ``None``).
    """
    if jax.process_count() > 1:
        save_checkpoint(directory, state, step, keep=keep, retries=retries,
                        retry_backoff_s=retry_backoff_s, manifest=manifest,
                        faults=faults, journal=journal)
        return None
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    to_save = _host_gather(_unwrap_keys(state))

    def write():
        _write_with_retries(path, to_save, retries=retries,
                            retry_backoff_s=retry_backoff_s,
                            manifest=manifest, faults=faults)
        _prune_old(directory, keep)
        # Journaled on the writer thread — emit() is thread-safe and the
        # event marks when the generation actually became durable.
        _journal_written(journal, step, path, manifest)

    return _AsyncSave(write, name=f"ckpt-write-{step}",
                      failure_cb=failure_cb)


def all_steps(directory: str) -> list:
    """All checkpoint steps in ``directory``, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = set()
    for name in os.listdir(directory):
        m = re.fullmatch(r"ckpt_(\d+)(\.msgpack)?", name)
        if m:
            steps.add(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpoint step in ``directory``, or None."""
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, template: Any,
                       step: Optional[int] = None, *,
                       verify: bool = True,
                       journal=None) -> Tuple[Any, int]:
    """Restore the checkpoint at ``step`` (default: latest) into the
    structure of ``template`` (a live state used for pytree/shape/dtype
    reference). Returns ``(state, step)``.

    When ``step`` is None (the ``auto_resume`` path), a checkpoint that
    fails to deserialize — e.g. truncated by a crash predating atomic
    writes, or torn on a non-atomic filesystem — is skipped with a
    warning and the next-older step is tried, so one corrupt file does
    not defeat crash recovery. An explicit ``step`` never falls back.

    Multi-controller: every process walks the same candidate list and the
    per-candidate success/failure is agreed GLOBALLY (all-gather of the
    local outcome) — a transient read error on one host must not leave it
    resuming an older step than its peers, which would silently mix
    divergent states through the next gradient psum. The agreed list is
    capped to the NEWEST 256 steps (the fixed-size broadcast buffer): with
    more checkpoints than that on disk, the multi-host fallback walk stops
    after 256 candidates rather than trying every older file — 256
    consecutive corrupt checkpoints means the directory, not a torn write,
    is the problem.

    ``verify=True`` (default) checks each msgpack candidate against its
    sha256 manifest sidecar when one exists — whole-file digest before
    parsing, per-leaf digests after — so silent corruption (a bit flip
    that still deserializes) is caught and falls back exactly like a torn
    file. Checkpoints without a sidecar restore unverified (back-compat)."""
    if step is not None:
        return _restore_one(directory, template, step, verify=verify,
                            journal=journal), step
    _sweep_stale_tmps(directory)
    steps = all_steps(directory)
    multi = jax.process_count() > 1
    if multi:
        # Agree on the candidate list itself: each process's os.listdir of
        # a shared directory can disagree (NFS attribute-cache lag), and a
        # divergent list would desynchronize the per-candidate allgather
        # below — pairing one host's verdict for step 5 with another's for
        # step 4. Walk process 0's list everywhere; a host whose listing
        # is stale simply fails _restore_one and the group falls back
        # together.
        import numpy as np
        from jax.experimental import multihost_utils

        padded = np.full(256, -1, dtype=np.int32)
        mine = np.asarray(steps[-256:], dtype=np.int32)
        padded[: len(mine)] = mine
        agreed = multihost_utils.broadcast_one_to_all(padded)
        steps = [int(s) for s in agreed if s >= 0]
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {directory}")

    def globally_ok(local_ok: bool) -> bool:
        if not multi:
            return local_ok
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1.0 if local_ok else 0.0])
        )
        return bool(np.min(flags) > 0.5)

    errors = []
    for candidate in reversed(steps):
        try:
            restored = _restore_one(directory, template, candidate,
                                    verify=verify, journal=journal)
            local_ok, err = True, None
        except Exception as e:  # corrupt/partial file — try older
            restored, local_ok, err = None, False, e
        if globally_ok(local_ok):
            return restored, candidate
        if err is not None:
            errors.append((candidate, err))
            print(
                f"warning: checkpoint ckpt_{candidate} in {directory} failed "
                f"to restore ({type(err).__name__}: {err}); trying older"
            )
            reason = f"{type(err).__name__}: {err}"
        else:
            print(
                f"warning: checkpoint ckpt_{candidate} restored locally but "
                f"failed on a peer process; trying older"
            )
            reason = "peer process failed to restore it"
        if journal is not None:
            try:
                journal.emit("checkpoint/fallback", int(candidate),
                             detail={"rejected_step": int(candidate),
                                     "reason": reason})
            except Exception:
                pass
    raise RuntimeError(
        f"all {len(steps)} checkpoints under {directory} failed to restore"
        + (f"; newest local error: {errors[0][1]!r}" if errors else
           " (failures were on peer processes)")
    )


def _load_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The sidecar, or None when absent/unreadable (unverified restore —
    a corrupt sidecar should not defeat a good checkpoint; per-file
    integrity still catches payload damage when the sidecar IS good)."""
    try:
        with open(_manifest_path(path)) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    if doc.get("schema") != "mercury-ckpt-manifest-v1":
        return None
    return doc


def _restore_one(directory: str, template: Any, step: int,
                 verify: bool = True, journal=None) -> Any:
    path = _ckpt_path(directory, step)
    # Only the template's structure/shapes/dtypes matter (the deserializer
    # overwrites every value) — build host zeros rather than fetching (or,
    # multi-controller, all-gathering) the live state.
    import numpy as np

    template_data = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), getattr(x, "dtype", None)),
        _unwrap_keys(template),
    )
    ocp = _orbax()
    if os.path.isdir(path) and ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), item=template_data)
    else:
        import flax.serialization

        with open(path + ".msgpack", "rb") as f:
            blob = f.read()
        doc = _load_manifest(path) if verify else None
        if doc is not None:
            # Schema-drift warning (non-fatal): a checkpoint stamped with
            # a different (or no) state-schema sha predates the schema
            # this build was linted against — the elastic path's upgrade
            # shims cover missing fields, but the drift itself should be
            # visible in logs and the journal, not silent.
            want_sha = state_schema_sha()
            have_sha = doc.get("state_schema_sha")
            if want_sha is not None and have_sha != want_sha:
                _log.warning(
                    "ckpt_%d was written under a different state schema "
                    "(manifest %s, HEAD %s): fields added since are "
                    "covered by upgrade shims on the elastic path",
                    step, str(have_sha)[:12], want_sha[:12])
                if journal is not None:
                    try:
                        journal.emit(
                            "checkpoint/schema_drift", int(step),
                            detail={"manifest_sha": have_sha,
                                    "head_sha": want_sha})
                    except Exception:
                        pass
        if doc is not None:
            # Whole-file digest BEFORE parsing: a torn/flipped file can
            # still deserialize into plausible garbage, and raising here
            # lets restore_checkpoint's fallback walk treat it exactly
            # like a parse failure.
            got = hashlib.sha256(blob).hexdigest()
            if got != doc["sha256"]:
                raise ValueError(
                    f"ckpt_{step}.msgpack sha256 mismatch: manifest "
                    f"{doc['sha256'][:12]}…, file {got[:12]}… "
                    f"({len(blob)} bytes vs {doc.get('bytes')} recorded)")
        restored = flax.serialization.from_bytes(template_data, blob)
        if doc is not None and doc.get("leaves"):
            flat, _ = jax.tree_util.tree_flatten_with_path(restored)
            have = {jax.tree_util.keystr(kp): leaf for kp, leaf in flat}
            for key, want in doc["leaves"].items():
                if key not in have:
                    raise ValueError(
                        f"ckpt_{step} manifest names leaf {key!r} absent "
                        "from the restored tree")
                got = hashlib.sha256(
                    np.asarray(have[key]).tobytes()).hexdigest()
                if got != want:
                    raise ValueError(
                        f"ckpt_{step} leaf {key!r} sha256 mismatch "
                        "(corrupt value survived deserialization)")
        if doc is not None and journal is not None:
            try:
                journal.emit(
                    "checkpoint/verified", int(step),
                    detail={"path": path,
                            "leaves": len(doc.get("leaves") or {})})
            except Exception:
                pass
    # Pull everything to host first — orbax otherwise hands back arrays
    # committed to device 0 with layouts of ITS choosing, which conflicts
    # with a multi-device mesh.
    restored = jax.device_get(restored)
    restored = _rewrap_keys(template, restored)
    # Do NOT return the raw host numpy: on CPU the next device_put may
    # zero-copy alias these buffers (some are tensorstore/mmap-backed),
    # and the first donated train step then releases memory XLA does not
    # own — observed as NaN params, SIGSEGV, or glibc heap corruption
    # when the step executable is replayed from the persistent
    # compilation cache. A trivial jitted identity materializes every
    # leaf as an executable OUTPUT, i.e. an XLA-allocated buffer that is
    # safe to donate; later jits remain free to re-place it.
    return jax.jit(lambda t: t)(restored)
