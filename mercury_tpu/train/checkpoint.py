"""Checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5 — no ``torch.save``
anywhere; a crash loses the run). Here the whole :class:`MercuryState`
pytree — params, optimizer state, BN stats, **and** the sampler state (EMA,
presample streams, per-worker RNG keys) — serializes, so importance-sampled
training resumes bit-deterministically.

Primary backend is Orbax (the idiomatic JAX checkpointer); a msgpack
fallback (``flax.serialization``) covers environments where Orbax's API is
unavailable.
"""

from __future__ import annotations

import os
import re
from typing import Any, Optional, Tuple

import jax


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step}")


def _is_key(x) -> bool:
    try:
        import jax.dtypes

        return jax.dtypes.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key)
    except Exception:
        return False


def _unwrap_keys(tree: Any) -> Any:
    """PRNG key arrays → raw uint32 key data (serializable)."""
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rewrap_keys(template: Any, tree: Any) -> Any:
    """Inverse of :func:`_unwrap_keys`, guided by the template's key leaves."""
    import jax.numpy as jnp
    import numpy as np

    return jax.tree_util.tree_map(
        lambda t, r: (
            jax.random.wrap_key_data(jnp.asarray(np.asarray(r)))
            if _is_key(t) else r
        ),
        template, tree,
    )


def save_checkpoint(directory: str, state: Any, step: int) -> str:
    """Save ``state`` under ``directory/ckpt_<step>``."""
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    to_save = jax.device_get(_unwrap_keys(state))
    ocp = _orbax()
    if ocp is not None:
        try:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), to_save, force=True)
            return path
        except Exception:
            pass
    import flax.serialization

    with open(path + ".msgpack", "wb") as f:
        f.write(flax.serialization.to_bytes(to_save))
    return path


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpoint step in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = []
    for name in os.listdir(directory):
        m = re.fullmatch(r"ckpt_(\d+)(\.msgpack)?", name)
        if m:
            steps.append(int(m.group(1)))
    return max(steps) if steps else None


def restore_checkpoint(directory: str, template: Any, step: Optional[int] = None) -> Tuple[Any, int]:
    """Restore the checkpoint at ``step`` (default: latest) into the
    structure of ``template`` (a live state used for pytree/shape/dtype
    reference). Returns ``(state, step)``."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _ckpt_path(directory, step)
    template_data = jax.device_get(_unwrap_keys(template))
    ocp = _orbax()
    if os.path.isdir(path) and ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), item=template_data)
    else:
        import flax.serialization

        with open(path + ".msgpack", "rb") as f:
            restored = flax.serialization.from_bytes(template_data, f.read())
    # Return host-resident (uncommitted) arrays so the next jitted step is
    # free to place them per its shardings — orbax otherwise commits
    # everything to device 0, which conflicts with a multi-device mesh.
    restored = jax.device_get(restored)
    return _rewrap_keys(template, restored), step
