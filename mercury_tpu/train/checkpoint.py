"""Checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5 — no ``torch.save``
anywhere; a crash loses the run). Here the whole :class:`MercuryState`
pytree — params, optimizer state, BN stats, **and** the sampler state (EMA,
presample streams, per-worker RNG keys) — serializes, so importance-sampled
training resumes bit-deterministically.

Primary backend is Orbax (the idiomatic JAX checkpointer); a msgpack
fallback (``flax.serialization``) covers environments where Orbax's API is
unavailable.

Multi-controller runs: saving all-gathers cross-process-sharded leaves
(collectively) and writes from process 0 only; restoring reads the file on
every process — the checkpoint directory must therefore be shared across
hosts (NFS/GCS) in multi-host runs.
"""

from __future__ import annotations

import functools
import os
import re
import time
from typing import Any, Optional, Tuple

import jax

from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.train.checkpoint")


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


def _ckpt_path(directory: str, step: int) -> str:
    return os.path.join(directory, f"ckpt_{step}")


def _is_key(x) -> bool:
    try:
        import jax.dtypes

        return jax.dtypes.issubdtype(getattr(x, "dtype", None), jax.dtypes.prng_key)
    except Exception:
        return False


def _unwrap_keys(tree: Any) -> Any:
    """PRNG key arrays → raw uint32 key data (serializable)."""
    return jax.tree_util.tree_map(
        lambda x: jax.random.key_data(x) if _is_key(x) else x, tree
    )


def _rewrap_keys(template: Any, tree: Any) -> Any:
    """Inverse of :func:`_unwrap_keys`, guided by the template's key leaves."""
    import jax.numpy as jnp
    import numpy as np

    return jax.tree_util.tree_map(
        lambda t, r: (
            jax.random.wrap_key_data(jnp.asarray(np.asarray(r)))
            if _is_key(t) else r
        ),
        template, tree,
    )


@functools.lru_cache(maxsize=None)
def _replicate_fn(sharding):
    """Cached jitted identity → fully-replicated placement (an all-gather
    for cross-process-sharded inputs). Cached per target sharding so
    repeated checkpoint saves are compile-cache hits."""
    return jax.jit(lambda a: a, out_shardings=sharding)


def _host_gather(tree: Any) -> Any:
    """``device_get`` that also works in multi-controller runs: any leaf
    sharded across processes (not fully addressable — e.g. the per-worker
    sampler state placed ``P("data")`` by ``globalize_state``) is first
    resharded to fully-replicated via a jitted identity, which XLA lowers
    to an all-gather. Every process must call this collectively — true for
    the checkpoint cadence inside ``fit`` since all processes run the same
    program."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def fetch(x):
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            x = _replicate_fn(NamedSharding(x.sharding.mesh, P()))(x)
        return x

    return jax.device_get(jax.tree_util.tree_map(fetch, tree))


def save_checkpoint(directory: str, state: Any, step: int) -> str:
    """Save ``state`` under ``directory/ckpt_<step>``.

    Multi-controller: all processes participate in the host gather (a
    collective), then only process 0 writes — a shared checkpoint
    directory sees exactly one writer."""
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    to_save = _host_gather(_unwrap_keys(state))
    if jax.process_count() > 1:
        # Multi-controller: process 0 writes msgpack (self-contained — no
        # hidden barriers; Orbax's save runs internal cross-process syncs
        # that would deadlock against ours when only one process calls it),
        # then a barrier so no process can proceed to a restore before the
        # writer is done. The barrier sits in a finally so a write failure
        # on process 0 re-raises there instead of hanging everyone else.
        try:
            if jax.process_index() == 0:
                _write_msgpack(path, to_save)
        finally:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(f"mercury_ckpt_save_{step}")
        return path
    ocp = _orbax()
    if ocp is not None:
        try:
            ckptr = ocp.PyTreeCheckpointer()
            ckptr.save(os.path.abspath(path), to_save, force=True)
            return path
        except Exception:
            pass
    _write_msgpack(path, to_save)
    return path


def _sweep_stale_tmps(directory: str, min_age_secs: float = 300.0) -> None:
    """Unlink ``.msgpack.tmp`` strays left by a crash mid-write. Without
    it, each preempted run leaks a checkpoint-sized orphan into the
    (possibly shared) directory.

    Only temps older than ``min_age_secs`` are removed: the restore path
    also runs mid-run (elastic resume restores into a live trainer), where
    an async writer's fresh ``.tmp`` may legitimately be in flight — age
    gating means a racing sweep can never unlink a file another process
    (or this one's writer thread) is about to ``os.replace``. Crash
    orphans are by definition older than any live write."""
    if jax.process_index() != 0:
        return
    now = time.time()
    try:
        for name in os.listdir(directory):
            if name.endswith(".msgpack.tmp"):
                path = os.path.join(directory, name)
                try:
                    if now - os.path.getmtime(path) >= min_age_secs:
                        os.unlink(path)
                except OSError:
                    pass
    except OSError:
        pass


def _write_msgpack(path: str, to_save: Any) -> None:
    """Atomic write: serialize to a temp file, then ``os.replace`` into
    place. A hard crash (SIGKILL/preemption — the exact scenario
    ``auto_resume`` targets) mid-write therefore leaves only a stray
    ``.tmp``, never a truncated ``ckpt_<step>.msgpack`` that
    :func:`latest_step` would pick as newest."""
    import flax.serialization

    final = path + ".msgpack"
    tmp = final + ".tmp"
    with open(tmp, "wb") as f:
        f.write(flax.serialization.to_bytes(to_save))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, final)
    # fsync the directory too: the rename itself is metadata, and on a
    # journaled filesystem a crash right after os.replace can otherwise
    # lose the directory entry for the new name.
    dir_fd = os.open(os.path.dirname(final) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


class _AsyncSave:
    """Handle for an in-flight background checkpoint write. ``join()``
    blocks until the write completes and RE-RAISES any exception the
    writer thread hit (a silently missing cadence checkpoint would
    otherwise surface only as a much older restore after a preemption)."""

    def __init__(self, target, name: str):
        import threading

        self._exc: Optional[BaseException] = None

        def runner():
            try:
                target()
            except BaseException as e:  # re-raised at join
                self._exc = e

        self._thread = threading.Thread(target=runner, name=name,
                                        daemon=False)
        self._thread.start()

    def join(self, timeout: Optional[float] = 600.0) -> None:
        """Wait for the write (default bound: 10 minutes — a full
        msgpack serialize + fsync on a slow NFS mount, with headroom).
        A writer still alive past the bound raises TimeoutError rather
        than hanging shutdown forever on a wedged filesystem: the
        thread is non-daemon, so the interpreter will still wait on it
        at exit, but the caller gets a loud, attributable failure
        instead of a silent hang here."""
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            _log.warning("checkpoint writer %r still running after "
                         "%.0fs — wedged filesystem?",
                         self._thread.name, timeout)
            raise TimeoutError(
                f"checkpoint write ({self._thread.name}) did not "
                f"finish within {timeout:.0f}s")
        if self._exc is not None:
            raise self._exc


def save_checkpoint_async(directory: str, state: Any, step: int):
    """Non-blocking save: the device→host fetch happens synchronously (it
    must — the caller's next train step donates/overwrites the state
    buffers), then serialization + file IO run on a background thread so
    training resumes immediately. Returns an :class:`_AsyncSave` handle —
    ``join()`` it before reading the file or exiting; writer-thread
    failures re-raise there.

    Single-process only: multi-controller saves need their cross-process
    barrier to stay on the caller's thread (collective ordering), so this
    falls back to the synchronous path there (returning ``None``).
    """
    if jax.process_count() > 1:
        save_checkpoint(directory, state, step)
        return None
    os.makedirs(directory, exist_ok=True)
    path = _ckpt_path(directory, step)
    to_save = _host_gather(_unwrap_keys(state))
    return _AsyncSave(lambda: _write_msgpack(path, to_save),
                      name=f"ckpt-write-{step}")


def all_steps(directory: str) -> list:
    """All checkpoint steps in ``directory``, ascending."""
    if not os.path.isdir(directory):
        return []
    steps = set()
    for name in os.listdir(directory):
        m = re.fullmatch(r"ckpt_(\d+)(\.msgpack)?", name)
        if m:
            steps.add(int(m.group(1)))
    return sorted(steps)


def latest_step(directory: str) -> Optional[int]:
    """Newest checkpoint step in ``directory``, or None."""
    steps = all_steps(directory)
    return steps[-1] if steps else None


def restore_checkpoint(directory: str, template: Any, step: Optional[int] = None) -> Tuple[Any, int]:
    """Restore the checkpoint at ``step`` (default: latest) into the
    structure of ``template`` (a live state used for pytree/shape/dtype
    reference). Returns ``(state, step)``.

    When ``step`` is None (the ``auto_resume`` path), a checkpoint that
    fails to deserialize — e.g. truncated by a crash predating atomic
    writes, or torn on a non-atomic filesystem — is skipped with a
    warning and the next-older step is tried, so one corrupt file does
    not defeat crash recovery. An explicit ``step`` never falls back.

    Multi-controller: every process walks the same candidate list and the
    per-candidate success/failure is agreed GLOBALLY (all-gather of the
    local outcome) — a transient read error on one host must not leave it
    resuming an older step than its peers, which would silently mix
    divergent states through the next gradient psum. The agreed list is
    capped to the NEWEST 256 steps (the fixed-size broadcast buffer): with
    more checkpoints than that on disk, the multi-host fallback walk stops
    after 256 candidates rather than trying every older file — 256
    consecutive corrupt checkpoints means the directory, not a torn write,
    is the problem."""
    if step is not None:
        return _restore_one(directory, template, step), step
    _sweep_stale_tmps(directory)
    steps = all_steps(directory)
    multi = jax.process_count() > 1
    if multi:
        # Agree on the candidate list itself: each process's os.listdir of
        # a shared directory can disagree (NFS attribute-cache lag), and a
        # divergent list would desynchronize the per-candidate allgather
        # below — pairing one host's verdict for step 5 with another's for
        # step 4. Walk process 0's list everywhere; a host whose listing
        # is stale simply fails _restore_one and the group falls back
        # together.
        import numpy as np
        from jax.experimental import multihost_utils

        padded = np.full(256, -1, dtype=np.int32)
        mine = np.asarray(steps[-256:], dtype=np.int32)
        padded[: len(mine)] = mine
        agreed = multihost_utils.broadcast_one_to_all(padded)
        steps = [int(s) for s in agreed if s >= 0]
    if not steps:
        raise FileNotFoundError(f"no checkpoints under {directory}")

    def globally_ok(local_ok: bool) -> bool:
        if not multi:
            return local_ok
        import numpy as np
        from jax.experimental import multihost_utils

        flags = multihost_utils.process_allgather(
            np.asarray([1.0 if local_ok else 0.0])
        )
        return bool(np.min(flags) > 0.5)

    errors = []
    for candidate in reversed(steps):
        try:
            restored = _restore_one(directory, template, candidate)
            local_ok, err = True, None
        except Exception as e:  # corrupt/partial file — try older
            restored, local_ok, err = None, False, e
        if globally_ok(local_ok):
            return restored, candidate
        if err is not None:
            errors.append((candidate, err))
            print(
                f"warning: checkpoint ckpt_{candidate} in {directory} failed "
                f"to restore ({type(err).__name__}: {err}); trying older"
            )
        elif multi:
            print(
                f"warning: checkpoint ckpt_{candidate} restored locally but "
                f"failed on a peer process; trying older"
            )
    raise RuntimeError(
        f"all {len(steps)} checkpoints under {directory} failed to restore"
        + (f"; newest local error: {errors[0][1]!r}" if errors else
           " (failures were on peer processes)")
    )


def _restore_one(directory: str, template: Any, step: int) -> Any:
    path = _ckpt_path(directory, step)
    # Only the template's structure/shapes/dtypes matter (the deserializer
    # overwrites every value) — build host zeros rather than fetching (or,
    # multi-controller, all-gathering) the live state.
    import numpy as np

    template_data = jax.tree_util.tree_map(
        lambda x: np.zeros(np.shape(x), getattr(x, "dtype", None)),
        _unwrap_keys(template),
    )
    ocp = _orbax()
    if os.path.isdir(path) and ocp is not None:
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path), item=template_data)
    else:
        import flax.serialization

        with open(path + ".msgpack", "rb") as f:
            restored = flax.serialization.from_bytes(template_data, f.read())
    # Pull everything to host first — orbax otherwise hands back arrays
    # committed to device 0 with layouts of ITS choosing, which conflicts
    # with a multi-device mesh.
    restored = jax.device_get(restored)
    restored = _rewrap_keys(template, restored)
    # Do NOT return the raw host numpy: on CPU the next device_put may
    # zero-copy alias these buffers (some are tensorstore/mmap-backed),
    # and the first donated train step then releases memory XLA does not
    # own — observed as NaN params, SIGSEGV, or glibc heap corruption
    # when the step executable is replayed from the persistent
    # compilation cache. A trivial jitted identity materializes every
    # leaf as an executable OUTPUT, i.e. an XLA-allocated buffer that is
    # safe to donate; later jits remain free to re-place it.
    return jax.jit(lambda t: t)(restored)
