"""Trainer — epoch orchestration, eval, logging, checkpoints.

Capability parity with the reference ``Trainer`` (``pytorch_collab.py:
36-250``) and the launch path ``my_run``/``init_processes``/``__main__``
(``:252-292``), collapsed into single-controller SPMD: no process forking,
no gloo world — one Python process drives a jitted ``shard_map`` step over
the device mesh.

Parity map:
- ``fit`` (``:56-72``): epoch loop, cosine schedule, step-budget break
  (``step×world_size > budget``, ``:71``); initial parameter sync
  (``average_model``, ``:84-87``) is implicit in replicated init.
- ``train`` (``:119-199``): the hot loop is one fused step
  (``mercury_tpu.train.step``); the global train loader's only live role —
  a step clock (``:127``, SURVEY.md §3.2) — becomes ``steps_per_epoch =
  n_train // batch_size``.
- ``evaluate`` (``:201-234``): full pass over train and test sets in
  inference mode, loss/accuracy meters.
- rank-0 printing/TensorBoard every 100/200 steps (``:170-195``) →
  non-blocking metric streaming (``obs/writer.py``: JSONL + TensorBoard +
  a rate-limited stdout heartbeat on ``heartbeat_every``), same tags; a
  run manifest and steps/s / examples/s / MFU accounting ride along
  (``obs/manifest.py``, ``obs/accounting.py``).
- wall-clock segment timing (``step/ff/is/bp/sync``, ``:129-168``): a fused
  XLA step has no host-visible segment boundaries — the trainer reports
  true ``step_time`` and throughput; per-segment attribution lives in
  ``mercury_tpu.train.profile`` (instrumented sub-step timings comparable
  to the reference's five named segments).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from mercury_tpu.config import TrainConfig
from mercury_tpu.data import cifar
from mercury_tpu.data.partition import partition_data
from mercury_tpu.data.pipeline import ShardedDataset, eval_batches, make_sharded_dataset
from mercury_tpu.models import create_model
from mercury_tpu.obs.accounting import ThroughputMeter, analytic_flops_per_step
from mercury_tpu.obs.aggregate import (
    CrossHostGatherAggregator,
    HostShardAggregator,
    shard_filename,
)
from mercury_tpu.obs.anomaly import AnomalyEngine
from mercury_tpu.obs.manifest import build_run_manifest, write_run_manifest
from mercury_tpu.obs.sampler_health import SamplerHealthMonitor
from mercury_tpu.obs.trace import NULL_TRACER, SpanTracer
from mercury_tpu.obs.writer import (
    AsyncMetricWriter,
    HeartbeatShardSink,
    HeartbeatSink,
    JsonlSink,
    host_thread_stats,
    try_tensorboard_sink,
)
from mercury_tpu.parallel.mesh import make_mesh
from mercury_tpu.train import checkpoint as ckpt
from mercury_tpu.train.state import MercuryState, create_state, make_optimizer
from mercury_tpu.train.step import make_eval_epoch, make_eval_step, make_train_step
from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.train.trainer")


def build_dataset(config: TrainConfig, seed_offset: int = 0) -> ShardedDataset:
    """Load + partition per config (≡ ``__main__``'s parent-process dataset
    build, ``pytorch_collab.py:280-282`` → ``exp_dataset.py``)."""
    if config.dataset == "imagefolder":
        from mercury_tpu.data.imagefolder import load_imagefolder_dataset

        if not config.data_dir:
            raise ValueError("dataset='imagefolder' requires data_dir")
        train, test, info = load_imagefolder_dataset(
            config.data_dir, image_size=config.image_size,
            seed=config.seed + seed_offset,
        )
    else:
        train, test, info = cifar.load_dataset(
            config.dataset, data_dir=config.data_dir, seed=config.seed + seed_offset
        )
    mode = "hetero" if config.noniid else "homo"
    shards = partition_data(
        train[1],
        config.world_size,
        mode=mode,
        alpha=config.dirichlet_alpha,
        seed=config.seed,
        min_size=config.min_shard_size,
    )
    return make_sharded_dataset(
        train, test, shards, info["mean"], info["std"], info["num_classes"],
        synthetic=info.get("synthetic", True),
        # host_stream: pixels stay host numpy arrays — the prefetch
        # pipeline streams selected rows; only labels go to device.
        device_resident=config.data_placement not in ("sharded",
                                                      "host_stream"),
    )


class Trainer:
    def __init__(
        self,
        config: TrainConfig,
        dataset: Optional[ShardedDataset] = None,
        mesh=None,
    ) -> None:
        # --- auto-planner (plan/auto.py): resolve config.plan to concrete
        # knob overrides BEFORE anything reads the config — the dataset
        # build keys off data_placement and the whole constructor below
        # keys off the resolved parallelism knobs. The decision (scored
        # table included) is journaled as plan/selected once the journal
        # exists, and restore_elastic re-runs the planner on a (W, L)
        # change (elastic/replan). DESIGN.md §16.
        self._plan_decision = None
        self._replan_count = 0
        if getattr(config, "plan", ""):
            from mercury_tpu.plan.auto import resolve_plan_config

            config, self._plan_decision = resolve_plan_config(
                config,
                device_kind=jax.devices()[0].device_kind,
                process_count=jax.process_count(),
            )
            _log.info(
                "auto-planner: plan=%r resolved to %s "
                "(%d candidates, %d feasible)",
                self._plan_decision and config.plan,
                self._plan_decision.selected,
                len(self._plan_decision.candidates),
                len(self._plan_decision.feasible),
            )
        self.config = config
        if config.serve_port < 0 or config.serve_port > 65535:
            raise ValueError(
                f"serve_port must be 0 (off) or a valid TCP port, "
                f"got {config.serve_port}"
            )
        self.dataset = dataset if dataset is not None else build_dataset(config)
        tp = config.tensor_parallel
        fs = config.fsdp_parallel
        if tp > 1 and fs > 1:
            raise ValueError(
                "tensor_parallel and fsdp_parallel are mutually exclusive "
                "(both claim the second mesh axis); pick one"
            )
        if mesh is not None:
            self.mesh = mesh
        elif tp > 1:
            from mercury_tpu.parallel.mesh import make_tp_mesh

            self.mesh = make_tp_mesh(config.world_size, tp,
                                     config.mesh_axis, config.model_axis)
        elif fs > 1:
            from mercury_tpu.parallel.mesh import make_tp_mesh

            self.mesh = make_tp_mesh(config.world_size, fs,
                                     config.mesh_axis, config.fsdp_axis)
        else:
            self.mesh = make_mesh(config.world_size, config.mesh_axis)
        if self.mesh.shape[config.mesh_axis] != config.world_size:
            raise ValueError(
                f"mesh axis size {self.mesh.shape[config.mesh_axis]} != "
                f"world_size {config.world_size}"
            )
        if tp > 1:
            if config.model not in ("transformer", "vit"):
                raise ValueError(
                    "tensor_parallel requires the transformer family "
                    f"(model='transformer'|'vit'), got {config.model!r}"
                )
            if config.model_axis not in self.mesh.axis_names or (
                self.mesh.shape[config.model_axis] != tp
            ):
                raise ValueError(
                    f"mesh must carry a {config.model_axis!r} axis of size "
                    f"{tp}; mesh axes: {dict(self.mesh.shape)}"
                )
        if fs > 1 and (
            config.fsdp_axis not in self.mesh.axis_names
            or self.mesh.shape[config.fsdp_axis] != fs
        ):
            raise ValueError(
                f"mesh must carry a {config.fsdp_axis!r} axis of size "
                f"{fs}; mesh axes: {dict(self.mesh.shape)}"
            )

        if (
            config.num_classes is not None
            and config.num_classes != self.dataset.num_classes
        ):
            raise ValueError(
                f"config.num_classes={config.num_classes} but dataset "
                f"{config.dataset!r} has {self.dataset.num_classes} classes"
            )

        bn_axis = config.mesh_axis if config.batch_norm == "sync" else None
        model_kw = {}
        if config.moe_experts is not None:
            if config.model not in ("transformer", "vit"):
                raise ValueError(
                    "moe_experts requires the transformer family "
                    f"(model='transformer'|'vit'), got {config.model!r}"
                )
            model_kw["moe_experts"] = config.moe_experts
        if config.remat:
            if config.model not in ("transformer", "vit"):
                raise ValueError(
                    "remat requires the transformer family "
                    f"(model='transformer'|'vit'), got {config.model!r}"
                )
            model_kw["remat"] = True
        self.model = create_model(
            config.model,
            num_classes=self.dataset.num_classes,
            compute_dtype=config.compute_dtype,
            param_dtype=config.param_dtype,
            bn_axis_name=bn_axis,
            **model_kw,
        )
        # Optional low-precision scorer: same architecture (params are
        # shared — flax modules are layout, not weights), different compute
        # dtype for the candidate-scoring forward only.
        self.scoring_model = None
        if config.scoring_dtype is not None:
            self.scoring_model = create_model(
                config.model,
                num_classes=self.dataset.num_classes,
                compute_dtype=config.scoring_dtype,
                param_dtype=config.param_dtype,
                bn_axis_name=bn_axis,
                **model_kw,
            )

        n_train = self.dataset.n_train
        self.steps_per_epoch = config.steps_per_epoch or max(n_train // config.batch_size, 1)
        total_steps = self.steps_per_epoch * config.num_epochs
        self.tx = make_optimizer(
            config.optimizer, config.lr, total_steps, config.weight_decay,
            grad_accum_steps=config.grad_accum_steps,
            warmup_steps=config.warmup_steps,
        )

        # Model-init sample and pending-batch shapes come from the dataset
        # itself: [H, W, C] for images, [T, F] for sequences (the BiLSTM
        # speech path — beyond the reference, which never trains MyLSTM).
        sample_shape = tuple(int(s) for s in self.dataset.x_train.shape[1:])
        is_image = len(sample_shape) == 3
        if not is_image and config.augmentation != "none":
            raise ValueError(
                f"augmentation={config.augmentation!r} needs image data; "
                f"dataset {config.dataset!r} has sample shape {sample_shape} — "
                "set augmentation='none'"
            )
        sample = jnp.zeros((1,) + sample_shape, jnp.float32)
        self.state: MercuryState = create_state(
            jax.random.key(config.seed),
            self.model,
            self.tx,
            sample,
            config.world_size,
            int(self.dataset.shard_indices.shape[1]),
            with_groupwise=(
                config.use_importance_sampling and config.sampler == "groupwise"
            ),
            pending_batch_size=(
                config.batch_size
                if config.use_importance_sampling and config.pipelined_scoring
                else 0
            ),
            # The IID augmentation pipeline crops to 32 regardless of the raw
            # image size (exp_dataset.py:26-27); noniid/none keep the
            # dataset's own sample shape.
            pending_sample_shape=((32, 32, sample_shape[-1])
                                  if config.augmentation == "iid"
                                  else sample_shape),
            zero_sharding=config.zero_sharding,
            init_opt=(tp == 1 and fs == 1),
            cached_pool_size=(
                config.candidate_pool_size
                if config.use_importance_sampling
                and config.sampler == "pool"
                and config.score_refresh_every > 1
                else 0
            ),
            with_scoretable=(
                config.use_importance_sampling
                and config.sampler == "scoretable"
            ),
            # Selection-count ledger rides only when the step will
            # actually scatter into it — scoretable sampler AND telemetry
            # on (obs/sampler_health.py). A telemetry=False run carries
            # no ledger at all, keeping its traced program byte-identical
            # to the seed's (Layer-2/3 digests).
            with_sel_counts=(
                config.use_importance_sampling
                and config.sampler == "scoretable"
                and bool(config.telemetry)
            ),
            stream_depth=(config.prefetch_depth
                          if config.data_placement == "host_stream" else 0),
            stream_emit_size=self._stream_emit_size(),
            stream_batch_size=config.batch_size,
        )
        params_sharded = tp > 1 or fs > 1
        if params_sharded:
            # Commit params in the sharded layout — Megatron column/row
            # under tensor_parallel, per-leaf largest-dim FSDP under
            # fsdp_parallel — and re-derive the optimizer state from the
            # sharded params (its moments inherit the layout). The train
            # step is manual-SPMD over the data axis only, so GSPMD reads
            # these committed shardings and partitions every matmul /
            # inserts the weight all-gathers over the second axis
            # (parallel/tensor.py, parallel/fsdp.py).
            if tp > 1:
                from mercury_tpu.parallel.tensor import (
                    transformer_tp_shardings,
                )

                if self.model.num_heads % tp != 0:
                    raise ValueError(
                        f"num_heads={self.model.num_heads} must be divisible "
                        f"by tensor_parallel={tp}"
                    )
                param_sh = transformer_tp_shardings(
                    self.state.params, self.mesh, config.model_axis
                )
            else:
                from mercury_tpu.parallel.fsdp import fsdp_shardings

                param_sh = fsdp_shardings(self.state.params, self.mesh,
                                          config.fsdp_axis)
            if jax.process_count() == 1:
                sh_params = jax.device_put(self.state.params, param_sh)
                # create_state skipped tx.init (init_opt=False): the single
                # init below inherits the sharded layout via zeros_like — no
                # transient replicated moment tree.
                sh_opt = self.tx.init(sh_params)
                self.state = self.state.replace(params=sh_params,
                                                opt_state=sh_opt)
            # Multi-controller: device_put cannot target other hosts'
            # devices — the placement happens inside globalize_state below
            # (params_sharding=param_sh), and the optimizer init runs as an
            # SPMD program on the placed params afterwards.
            self._tp_param_sh = param_sh
        else:
            self._state_out_shardings = None
        # Multi-controller (multi-host) runs: the host-created state and
        # dataset are process-local; re-place them as global arrays over the
        # (cross-process) mesh. Single-process runs skip this — shard_map
        # handles placement there.
        # Step-input train arrays. "sharded": materialize each worker's
        # shard rows as [W, L, ...] arrays sharded over the data axis —
        # per-device memory is one shard row, and in multi-controller runs
        # each host transfers only its own workers' rows; the dataset's
        # x_train/y_train stay host-side for eval. Built BEFORE the
        # dataset is globalized (it reads the process-local host copy,
        # identical on every process by seeded construction).
        data_sharded = config.data_placement == "sharded"
        host_stream = config.data_placement == "host_stream"
        if data_sharded:
            from mercury_tpu.parallel.distributed import (
                worker_shard_global_arrays,
            )

            self._step_x, self._step_y = worker_shard_global_arrays(
                self.dataset, self.mesh, config.mesh_axis
            )
        if host_stream:
            # Stashed BEFORE the dataset is globalized (the [W, L] matrix
            # becomes a non-addressable P(data) array under
            # multi-controller): the drain-side slot→global-row mapping
            # (_refill_stream_pipe) needs the full host copy, which every
            # process holds identically by seeded construction.
            self._host_shard_indices = np.asarray(self.dataset.shard_indices)
        if jax.process_count() > 1:
            from mercury_tpu.parallel.distributed import (
                globalize_dataset,
                globalize_state,
            )

            self.state = globalize_state(
                self.state, self.mesh, config.mesh_axis,
                zero_sharding=config.zero_sharding,
                params_sharding=(self._tp_param_sh if params_sharded
                                 else None),
            )
            if params_sharded:
                # SPMD optimizer init on the TP-placed params, with the
                # moment layout pinned explicitly (opt_sharding_like):
                # zeros_like gives the partitioner no constraint to
                # propagate, so an unpinned init can come back replicated
                # — which would alias-clash with the TP-sharded step
                # outputs on the first donated call.
                from mercury_tpu.parallel.tensor import opt_sharding_like

                opt_shapes = jax.eval_shape(self.tx.init, self.state.params)
                self._tp_opt_sh = opt_sharding_like(
                    opt_shapes, self.state.params, self._tp_param_sh,
                    self.mesh,
                )
                tp_opt = jax.jit(
                    self.tx.init, out_shardings=self._tp_opt_sh
                )(self.state.params)
                self.state = self.state.replace(opt_state=tp_opt)
            self.dataset = globalize_dataset(
                self.dataset, self.mesh, config.mesh_axis,
                # host_stream: pixels must STAY host numpy — the per-host
                # prefetch pipelines stream selected rows; replicating
                # x_train onto every device is the thing the placement
                # exists to avoid.
                include_train_arrays=not data_sharded and not host_stream,
            )
        if params_sharded:
            # The moment layout is DERIVED (opt_sharding_like), not
            # inferred from live leaves: the structural param-path match
            # is exact for optax states, where sharding inference from a
            # jitted init's outputs is backend-dependent. The multi-
            # controller branch above already computed it; compute here
            # only on the single-process path.
            if getattr(self, "_tp_opt_sh", None) is None:
                from mercury_tpu.parallel.tensor import opt_sharding_like

                self._tp_opt_sh = opt_sharding_like(
                    self.state.opt_state, self.state.params,
                    self._tp_param_sh, self.mesh,
                )
            opt_sh = self._tp_opt_sh
            from mercury_tpu.train.step import mercury_state_out_shardings

            self._state_out_shardings = mercury_state_out_shardings(
                self.mesh, config.mesh_axis, self._tp_param_sh, opt_sh,
                has_groupwise=(config.use_importance_sampling
                               and config.sampler == "groupwise"),
                has_pending=(config.use_importance_sampling
                             and config.pipelined_scoring),
                has_cached_pool=(config.use_importance_sampling
                                 and config.sampler == "pool"
                                 and config.score_refresh_every > 1),
                has_scoretable=(config.use_importance_sampling
                                and config.sampler == "scoretable"),
                has_sel_counts=(config.use_importance_sampling
                                and config.sampler == "scoretable"
                                and bool(config.telemetry)),
            )
            if jax.process_count() == 1:
                # Pre-place the whole state with the pinned shardings (a
                # no-copy no-op for the already-committed params/opt): the
                # first step then donates cleanly instead of warning about
                # unusable host-resident sampler buffers and resharding on
                # entry. device_put accepts the prefix sharding pytree, so
                # groupwise/pending subtrees are covered too. (Multi-
                # controller state is already fully placed by
                # globalize_state.)
                state_sh, _ = self._state_out_shardings
                self.state = jax.device_put(self.state, state_sh)
        if host_stream:
            # Pixels never become a step input: _step_x is the per-step
            # streamed batch (popped from the prefetch pipeline in
            # _host_stream_step). Labels are tiny ([N] int32) and the
            # in-graph gathers index them, so they live on device.
            self._step_x = None
            self._step_y = jnp.asarray(np.asarray(self.dataset.y_train),
                                       jnp.int32)
        elif not data_sharded:
            self._step_x = self.dataset.x_train
            self._step_y = self.dataset.y_train
        self.train_step = make_train_step(
            self.model, self.tx, config, self.mesh, self.dataset.mean,
            self.dataset.std, state_out_shardings=self._state_out_shardings,
            scoring_model=self.scoring_model,
        )
        # K-step chunked variant: one dispatch per config.scan_steps steps
        # (lax.scan over the same body; jit is lazy, so this costs nothing
        # unless used).
        self.scan_steps = max(int(config.scan_steps), 1)
        if self.scan_steps > 1:
            for name in ("log_every", "eval_every", "checkpoint_every"):
                every = getattr(config, name)
                if every and every % self.scan_steps != 0:
                    print(
                        f"warning: {name}={every} is not a multiple of "
                        f"scan_steps={self.scan_steps}; cadence actions fire "
                        "at most once per chunk (at chunk boundaries)"
                    )
        self.train_step_many = (
            make_train_step(
                self.model, self.tx, config, self.mesh,
                self.dataset.mean, self.dataset.std, scan_steps=self.scan_steps,
                state_out_shardings=self._state_out_shardings,
                scoring_model=self.scoring_model,
            )
            if self.scan_steps > 1
            else None
        )
        self.eval_step = make_eval_step(self.model)
        # Shard eval batches over the mesh so evaluation uses every device
        # (single-controller only: multi-process would need global eval
        # arrays; there the replicated path is correct, just redundant).
        # Under TP/FSDP the explicit in_shardings would force the sharded
        # params to replicate; plain jit lets GSPMD partition eval too.
        eval_mesh = (self.mesh
                     if jax.process_count() == 1 and not params_sharded
                     else None)
        if jax.process_count() > 1:
            # Not a silent restriction: multi-controller eval still RUNS
            # (plain jit over host-replicated eval arrays), but every
            # process executes the full pass redundantly instead of
            # sharding batches over the mesh — sharded eval would need
            # globally-placed eval arrays, which nothing builds yet.
            _log.warning(
                "multi-controller run (%d processes): evaluation executes "
                "replicated — every process runs the full eval pass "
                "redundantly (correct, but no eval speedup from the mesh)",
                jax.process_count(),
            )
        self.eval_epoch = make_eval_epoch(self.model, self.dataset.mean,
                                          self.dataset.std,
                                          eval_augmentation=config.augmentation
                                          if config.augmentation == "iid"
                                          else "none",
                                          mesh=eval_mesh,
                                          axis=config.mesh_axis)
        # --- fault-injection plane (mercury_tpu/faults.py): built BEFORE
        # every subsystem that hooks into it (metric writer, prefetch
        # pipeline, scorer fleet, checkpoint writes, the fit loop). None
        # when disabled — each hook site is a plain attribute check and
        # the traced step program is byte-identical (Layer-2/3 digests).
        # --- control-plane event journal (obs/events.py): every host
        # appends its supervisor/scorer/fault/elastic/checkpoint/anomaly
        # decisions to events.h{p}.jsonl with causal parent_id links.
        # Built FIRST among the host-side subsystems so every producer
        # below can take it at construction. Emission is a buffered dict
        # append; IO rides the metric writer's drain thread. Host-only —
        # the traced program is byte-identical with it on or off.
        self._journal = None
        if config.log_dir and config.event_journal:
            from mercury_tpu.obs.events import EventJournal

            self._journal = EventJournal(config.log_dir,
                                         jax.process_index())
            if self._plan_decision is not None:
                # Construction-time plan resolution, scored table and
                # per-rejection reasons in detail (report.py renders it
                # as the "Plan selection" section).
                self._journal.emit("plan/selected", -1,
                                   detail=self._plan_decision.detail())
        self._faults = None
        if config.fault_spec:
            from mercury_tpu.faults import FaultPlane

            self._faults = FaultPlane(config.fault_spec,
                                      journal=self._journal)
        # --- observability: run manifest + non-blocking metric stream ---
        # The manifest (resolved config, jax/jaxlib versions, mesh/device
        # topology, git sha) makes the metrics stream interpretable later;
        # the AsyncMetricWriter replaces the seed's synchronous per-log
        # float()+flush() with an enqueue — device_get and filesystem IO
        # happen on a background thread (obs/writer.py).
        sinks = []
        pidx = jax.process_index()
        if config.log_dir and pidx == 0:
            write_run_manifest(config.log_dir, config, self.mesh)
            sinks.append(JsonlSink(config.log_dir))
            sinks.append(try_tensorboard_sink(config.log_dir))
        if config.log_dir:
            # EVERY process (host 0 included) writes its own metric +
            # heartbeat shards — non-zero hosts used to be completely
            # dark, so a wedged host 3 left no post-mortem at all. The
            # shards also feed the cross-host aggregator below.
            sinks.append(JsonlSink(config.log_dir,
                                   filename=shard_filename(pidx)))
            sinks.append(HeartbeatShardSink(config.log_dir, pidx))
        if config.heartbeat_every and pidx == 0:
            sinks.append(HeartbeatSink(every_steps=config.heartbeat_every))
        # --- cross-host aggregation (obs/aggregate.py): host/{min,max,
        # spread}/* + host/straggler_ratio merged onto host 0's records.
        # "files" tails the per-host shards on the writer's drain thread
        # (observer); "allgather" runs a small dedicated jitted gather at
        # the log gate instead. Neither touches the fused step program.
        mode = config.crosshost_telemetry
        if mode not in ("auto", "off", "files", "allgather"):
            raise ValueError(
                f"crosshost_telemetry={mode!r}: expected one of "
                "'auto', 'off', 'files', 'allgather'")
        if mode == "auto":
            mode = "files" if jax.process_count() > 1 else "off"
        if mode == "files" and not config.log_dir:
            mode = "off"  # file aggregation needs shards to tail
        self._crosshost_mode = mode
        self._host_agg: Optional[HostShardAggregator] = None
        self._crosshost_gather: Optional[CrossHostGatherAggregator] = None
        if pidx == 0:
            if mode == "files":
                self._host_agg = HostShardAggregator(
                    config.log_dir,
                    processes=jax.process_count(),
                    window=config.crosshost_window,
                )
            elif mode == "allgather":
                self._crosshost_gather = CrossHostGatherAggregator(
                    window=config.crosshost_window)
        elif mode == "allgather":
            # Non-zero hosts still participate in the collective.
            self._crosshost_gather = CrossHostGatherAggregator(
                window=config.crosshost_window)
        # --- step-timeline tracer + flight recorder (obs layer 2) ---
        # Disabled tracing is the shared no-op NULL_TRACER: every span
        # call site below stays unconditional and costs ~100 ns
        # (benchmarks/telemetry_overhead.py measures both arms). The
        # anomaly engine's value checks ride the writer's drain thread
        # as an observer; only the ~1 µs slow-step bookkeeping runs on
        # this thread.
        self.tracer = (SpanTracer(config.trace_capacity)
                       if config.trace else NULL_TRACER)
        self.anomaly: Optional[AnomalyEngine] = None
        if config.anomaly_detection and pidx == 0:
            self.anomaly = AnomalyEngine(
                ring_steps=config.anomaly_window,
                slow_step_factor=config.anomaly_slow_step_factor,
                ess_floor=config.slo_ess_floor,
                stall_frac_max=(config.slo_stall_frac_max
                                if config.data_placement == "host_stream"
                                else 0.0),
                mfu_floor=config.slo_mfu_floor,
                straggler_factor=config.anomaly_straggler_factor,
                gini_max=config.slo_selection_gini_max,
                # Any starved class breaches — the share floor itself
                # lives in the monitor's class_spread derivation.
                starved_classes=(1.0 if config.slo_class_starvation_share
                                 > 0 else 0.0),
                var_ratio_patience=config.slo_var_ratio_patience,
                cooldown_steps=config.anomaly_cooldown_steps,
                dump_dir=config.anomaly_dir or config.log_dir,
                tracer=self.tracer,
                context_fn=self._flight_context,
                profile_steps=config.anomaly_profile_steps,
                journal=self._journal,
            )
        # --- sampler-health monitor (obs/sampler_health.py): derives the
        # coverage / Gini / class-spread / bias-audit scalars from the
        # selection-count ledger at the log gate. Single-controller only
        # — the ledger is a global array and device_get on another host's
        # shards raises (same constraint as the async scorer fleet).
        self._sampler_monitor: Optional[SamplerHealthMonitor] = None
        if (
            config.use_importance_sampling
            and config.sampler == "scoretable"
            and config.telemetry
            and jax.process_count() == 1
        ):
            self._sampler_monitor = SamplerHealthMonitor(
                np.asarray(self.dataset.shard_indices),
                np.asarray(self.dataset.y_train),
                self.dataset.num_classes,
                config.is_alpha,
                starvation_share=(config.slo_class_starvation_share
                                  or 0.2),
            )
        # Observer order matters: the shard aggregator attaches host/*
        # keys first, then the anomaly engine reads them (straggler).
        observers = []
        if self._host_agg is not None:
            observers.append(self._host_agg.observe_record)
        if self.anomaly is not None:
            observers.append(self.anomaly.observe_record)
        self.logger = AsyncMetricWriter(sinks, observers=observers,
                                        faults=self._faults,
                                        journal=self._journal)
        # --- host supervisor (runtime/supervisor.py): liveness + restart
        # + the degradation ladder. Units register below as the worker
        # fleets are built; the writer-observer hook makes the supervisor
        # see every host metric record (its heartbeat of the metric
        # plane). Host step stash: the supervisor's probe path must never
        # sync the device (int(self.state.step) would), so the fit loop
        # publishes the host-side step counter here each iteration.
        self._host_step = 0
        self.supervisor = None
        if config.supervise:
            from mercury_tpu.runtime.supervisor import HostSupervisor

            self.supervisor = HostSupervisor(
                restart_budget=config.supervisor_restart_budget,
                backoff_s=config.supervisor_backoff_s,
                probe_every=config.supervisor_probe_every,
                poll_s=config.supervisor_poll_s,
                anomaly=self.anomaly,
                journal=self._journal,
                plan_provider=self._plan_facts,
            )
            self.logger.add_observer(self.supervisor.observe_record)
        # On-demand jax.profiler capture window: >0 means "this many more
        # steps, then stop_trace" (armed by an anomaly trigger).
        self._profile_steps_left = 0
        self._profiling = False
        self._nan_injected = False
        # steps/s, examples/s, MFU between log ticks; the analytic FLOPs
        # estimate is filled in lazily at the first log gate (the step has
        # compiled by then, so lower().compile() is a jit-cache hit).
        self._throughput = ThroughputMeter(
            examples_per_step=config.batch_size * config.world_size,
        )
        self._flops_known = False
        self.history: List[Dict[str, float]] = []
        # Round up to a multiple of world_size so the sharded-eval batch
        # dimension always divides the mesh axis (e.g. world_size=5 → 260).
        self._eval_batch = -(-256 // config.world_size) * config.world_size
        self._eval_cache: Dict[bool, tuple] = {}
        self._ckpt_thread = None  # in-flight async checkpoint write

        # --- host-stream prefetch pipeline (data_placement="host_stream"):
        # prime the in-graph selection ring with the first prefetch_depth
        # draws (uniform cold start), then keep depth gathers in flight.
        # Built BEFORE auto_resume: a restore re-seeds the ring and the
        # pipeline via _recommit_state → _refill_stream_pipe.
        self._stream_pipe = None
        self._stream_local_workers = None
        if host_stream:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P
            from mercury_tpu.data.stream import (
                HostStreamSource,
                PrefetchPipeline,
            )
            from mercury_tpu.parallel.distributed import host_worker_slice
            from mercury_tpu.train.step import make_host_stream_prime

            # Multi-controller: each process runs its own pipeline over
            # its local workers' rows and device_puts only to its
            # addressable shards — the global streamed batch is assembled
            # per-host with zero cross-host pixel traffic.
            shard_mode = config.stream_shard_mode
            if shard_mode not in ("auto", "local", "replicated"):
                raise ValueError(
                    f"stream_shard_mode={shard_mode!r}: expected one of "
                    "'auto', 'local', 'replicated'")
            if shard_mode == "auto":
                shard_mode = ("local" if jax.process_count() > 1
                              else "replicated")
            if shard_mode == "replicated" and jax.process_count() > 1:
                raise ValueError(
                    "stream_shard_mode='replicated' is single-process "
                    "only: a multi-controller process can read only its "
                    "addressable rows of the in-flight index output — "
                    "use 'local' (the multi-controller default)")
            if shard_mode == "local":
                self._stream_local_workers = host_worker_slice(
                    self.mesh, config.mesh_axis)
            source = HostStreamSource(
                np.asarray(self.dataset.x_train),
                decode_workers=config.decode_workers,
            )
            self._stream_x_sharding = NamedSharding(
                self.mesh, P(config.mesh_axis)
            )
            self._stream_gen = 0
            self._stream_pipe = PrefetchPipeline(
                source,
                (config.world_size, self._stream_emit_size()),
                self._stream_x_sharding,
                depth=config.prefetch_depth,
                tracer=self.tracer,
                local_workers=self._stream_local_workers,
                faults=self._faults,
            )
            if self.supervisor is not None:
                # escalates=False: training cannot proceed without input,
                # so past the restart budget a prefetch death propagates
                # (there is no degraded mode that synthesizes pixels).
                # alive reads the CURRENT pipe — restarts replace it.
                self.supervisor.register_unit(
                    "prefetch",
                    alive=lambda: self._stream_pipe.alive(),
                    restart=self._restart_stream_pipe,
                    escalates=False,
                )
            self._stream_prime = make_host_stream_prime(config, self.mesh)
            self.state, primed_gidx = self._stream_prime(
                self.state, self.dataset.shard_indices
            )
            self._seed_stream_pipe(primed_gidx)
            # The streamed-x step has no host-side x template for the XLA
            # cost model (analytic_flops_per_step reads _step_x); skip the
            # lazy fill — mfu reports 0.0, steps/s and examples/s remain.
            self._flops_known = True

        # --- async scorer fleet (refresh_mode="async"): background host
        # threads continuously re-score round-robin shard chunks against a
        # periodically-snapshotted copy of the params and stream (slots,
        # scores) chunks into the device table between step dispatches
        # (sampling/scorer_fleet.py; drained by _async_refresh_tick in the
        # fit loop). Built BEFORE auto_resume: a restore resets the fleet
        # via _recommit_state (queued chunks scored the old trajectory).
        self._scorer_fleet = None
        # Non-finite chunks rejected by the apply guard (scorer_nan
        # injection, or an organically diverged scoring forward) — the
        # table must never be scattered with NaN.
        self._chunks_rejected = 0
        # Highest ladder level actually ACTUATED on the device table:
        # the level-3 flatten runs exactly once per descent to uniform.
        self._actuated_level = 0
        # Runtime retrace guard (graftlint Layer P): armed explicitly via
        # arm_retrace_guard(); when live, the log gate emits
        # lint/retrace_events + lint/compile_count per tick.
        self._retrace_monitor = None
        if (config.use_importance_sampling
                and config.sampler == "scoretable"
                and config.refresh_mode == "async"):
            from mercury_tpu.sampling.scorer_service import (
                ScorerService,
                validate_scorer_composition,
            )

            # Reject unsupported backend/tenancy/process compositions
            # with loud, specific errors BEFORE any thread spawns. The
            # old blanket multi-process rejection lives here now, scoped
            # to the host backend (the device backend's lockstep mode
            # supports multi-process; see sampling/scorer_service.py).
            validate_scorer_composition(config, jax.process_count())

            # The scoring forwards run OUTSIDE shard_map, where the mesh
            # data axis doesn't exist — build a local-BN scorer clone
            # (params are shared; flax modules are layout, not weights).
            # scoring_dtype applies, as it would in-graph.
            fleet_model = create_model(
                config.model,
                num_classes=self.dataset.num_classes,
                compute_dtype=config.scoring_dtype or config.compute_dtype,
                param_dtype=config.param_dtype,
                bn_axis_name=None,
                **model_kw,
            )
            scorer_args = (
                np.asarray(self.dataset.x_train),
                np.asarray(self.dataset.y_train),
                np.asarray(self.dataset.shard_indices),
                fleet_model,
                self.dataset.mean,
                self.dataset.std,
                config,
            )
            # Plain host-backend single-tenant runs keep the PR-8 fleet
            # unchanged; the device backend, any multi-tenant run, and
            # any armed scoring SLO go through the ScorerService front
            # (same external contract — the fleet has no slo_status).
            use_service = (config.scorer_backend == "device"
                           or config.scorer_tenants > 1
                           or config.slo_score_staleness_max > 0
                           or config.scorer_queue_highwater > 0)
            if use_service:
                self._scorer_fleet = ScorerService(
                    *scorer_args,
                    tracer=self.tracer,
                    faults=self._faults,
                    train_mesh=self.mesh,
                    journal=self._journal,
                )
            else:
                from mercury_tpu.sampling.scorer_fleet import ScorerFleet

                self._scorer_fleet = ScorerFleet(
                    *scorer_args,
                    tracer=self.tracer,
                    faults=self._faults,
                )
            self._apply_refresh = self._make_refresh_apply()
            self._scorer_fleet.snapshot(
                self.state.params, self.state.batch_stats,
                step=int(self.state.step),
            )
            if self.supervisor is not None:
                # escalates=True: scorer exhaustion enters the
                # degradation ladder (the table can be refreshed on the
                # trainer thread, frozen, or flattened to uniform —
                # training proceeds either way).
                self.supervisor.register_unit(
                    "scorer_service" if use_service else "scorer",
                    alive=lambda: self._scorer_fleet.alive(),
                    restart=lambda: self._scorer_fleet.restart_workers(),
                    escalates=True,
                )
                self.supervisor.set_ladder(
                    probe=self._probe_scoring,
                    revive=lambda: self._scorer_fleet.restart_workers(),
                )
                if use_service:
                    # Backpressure + staleness SLOs enter the ladder:
                    # a breach (wedged tenant, undrained queue) walks
                    # async → sync → frozen → uniform exactly as a
                    # scorer death does.
                    self.supervisor.register_slo(
                        "scorer_service",
                        lambda: self._scorer_fleet.slo_status(
                            self._host_step),
                    )

        # Crash/preemption recovery: pick up the newest checkpoint, sampler
        # state included (bit-deterministic IS resume). The NEXT fit() then
        # runs to the ORIGINAL end step, not num_epochs more (see fit) —
        # gated on this flag, so non-resumed fit() calls keep their usual
        # "train N epochs from here" semantics.
        self._auto_resumed = False
        if config.auto_resume and config.checkpoint_dir:
            if ckpt.latest_step(config.checkpoint_dir) is not None:
                # Topology change (preemption shrank the pod / it grew
                # back): the checkpoint's world size decides between the
                # bit-exact restore and the elastic one — checked BEFORE
                # deserializing into a mismatched template, because the
                # msgpack path would silently accept wrong-shaped sampler
                # leaves. Single-controller only: the probe is plain local
                # IO with no cross-process agreement, and divergent
                # branches would hang mismatched collectives — multi-host
                # auto_resume keeps the agreed restore path (which
                # broadcasts its candidate list); a multi-host topology
                # change uses an explicit restore_elastic call instead.
                w_ckpt = None
                raw = raw_step = None
                if jax.process_count() == 1:
                    from mercury_tpu.train.elastic import (
                        probe_checkpoint,
                        world_size_of_raw,
                    )

                    raw, raw_step = probe_checkpoint(config.checkpoint_dir)
                    w_ckpt = world_size_of_raw(raw)
                if w_ckpt is not None and w_ckpt != config.world_size:
                    # The probe's raw tree feeds the restore — the file is
                    # deserialized once on this (elastic) branch.
                    resumed = self.restore_elastic(step=raw_step, raw=raw)
                    _log.info(
                        "auto-resumed elastically from a %d-worker "
                        "checkpoint at step %d (now %d workers)",
                        w_ckpt, resumed, config.world_size,
                    )
                else:
                    # Same topology (the common case): the probe's tree is
                    # not a substitute for restore()'s corrupt-fallback
                    # walk, so release it before the second read rather
                    # than holding two copies of a possibly-large state.
                    del raw
                    resumed = self.restore()
                    _log.info("auto-resumed from checkpoint at step %d",
                              resumed)
                self._auto_resumed = True

        # --- live scrape plane (obs/serve.py): /healthz /statusz
        # /metricsz on host 0, started LAST so every callback target
        # exists. serve_port=0 (default) means no server object, no
        # thread, no socket — the disabled path costs nothing.
        self._status_server = None
        if config.serve_port > 0 and pidx == 0:
            from mercury_tpu.obs.serve import StatusServer

            self._status_server = StatusServer(
                config.serve_port,
                health_fn=self._serve_health,
                status_fn=self._serve_status,
                metrics_fn=self.logger.latest_record,
            )

    # ---------------------------------------------------------- scrape plane
    def _serve_health(self) -> Dict[str, Any]:
        """``/healthz`` body: liveness + ladder level. Runs on the serve
        thread — host counters only, never a device sync."""
        body: Dict[str, Any] = {"step": self._host_step}
        if self.supervisor is not None:
            s = self.supervisor.summary()
            body["level"] = s["level"]
            body["level_name"] = s["level_name"]
            body["units_down"] = sum(1 for u in s["units"] if u["down"])
        return body

    def _serve_status(self) -> Dict[str, Any]:
        """``/statusz`` body: manifest + ladder + tenant queues + the
        journal tail — the first page of any live incident."""
        doc: Dict[str, Any] = {"step": self._host_step}
        if self.config.log_dir:
            try:
                with open(os.path.join(self.config.log_dir,
                                       "run_manifest.json")) as f:
                    doc["manifest"] = json.load(f)
            except Exception:
                pass
        if self.supervisor is not None:
            doc["supervisor"] = self.supervisor.summary()
        fleet = getattr(self, "_scorer_fleet", None)
        if fleet is not None and hasattr(fleet, "summary"):
            try:
                doc["scorer"] = fleet.summary()
            except Exception:
                pass
        if self._journal is not None:
            doc["events"] = self._journal.tail()
            doc["event_counts"] = self._journal.counts()
        # The state schema this build was linted against (graftlint
        # Layer E golden) — lets a scraper correlate restore warnings
        # with the running build's schema without shell access.
        doc["state_schema_sha"] = ckpt.state_schema_sha()
        return doc

    # -------------------------------------------------------- host streaming
    def _stream_emit_size(self) -> int:
        """Rows streamed per worker per step (mirrors ``make_train_step``):
        the candidate pool for the pool sampler, refresh window + train
        batch for the scoretable one, the batch itself for uniform."""
        cfg = self.config
        if cfg.use_importance_sampling and cfg.sampler == "scoretable":
            if cfg.refresh_mode == "async":
                # Async streams only the train rows — the scorer fleet
                # owns the refresh sweep host-side.
                return int(cfg.batch_size)
            return int(cfg.refresh_size) + int(cfg.batch_size)
        if cfg.use_importance_sampling:
            return int(cfg.candidate_pool_size)
        return int(cfg.batch_size)

    def _host_stream_step(self, step: int = 0):
        """One pop→step→push cycle: train on the oldest prefetched batch,
        hand the step's emitted t+depth indices straight back to the
        pipeline (still an in-flight device value — the worker thread
        absorbs the sync)."""
        # pop blocks only when the prefetch worker fell behind — the
        # span IS the input-stall (its wall time, minus µs of queue
        # bookkeeping, is time the trainer waited on data).
        with self.tracer.span("trainer/pop", cat="trainer"):
            try:
                batch = self._stream_pipe.pop()  # graftlint: disable=GL120 -- supervisor callbacks (restart/probe/revive) run on the trainer thread only: tick()/request_restart() are fit-loop calls and the monitor thread never invokes them
            except RuntimeError:
                # Worker death. The trainer cannot take this step without
                # input, so the restart is synchronous (budget + backoff
                # via the supervisor); the rebuilt pipeline resumes from
                # the stream cursor (state.pending_sel), so the popped
                # batch is exactly the one the dead worker owed us — no
                # sample skipped or duplicated.
                if self.supervisor is None or not \
                        self.supervisor.request_restart("prefetch", step):
                    raise
                batch = self._stream_pipe.pop()
        with self.tracer.span("trainer/dispatch", cat="trainer"):
            self.state, metrics, next_gidx = self.train_step(  # graftlint: disable=GL120 -- supervisor callbacks run on the trainer thread only (see pop() above); state is never touched off-thread
                self.state, batch, self._step_y, self.dataset.shard_indices
            )
        with self.tracer.span("trainer/push", cat="trainer"):
            self._stream_pipe.push(next_gidx)
        return metrics

    def _seed_stream_pipe(self, primed_gidx) -> None:
        """Push the primed ``[depth, W, S]`` selections into the prefetch
        pipeline, reset first (queued work belongs to a previous
        trajectory). Multi-controller: only this host's worker rows of
        the ``P(None, data)``-sharded prime output are readable here —
        and they are exactly the rows this host's pipeline gathers."""
        self._stream_pipe.reset()
        lw = self._stream_local_workers
        if lw is None:
            for i in range(self.config.prefetch_depth):
                self._stream_pipe.push(primed_gidx[i])
            return
        if getattr(primed_gidx, "is_fully_addressable", True):
            local = np.asarray(jax.device_get(primed_gidx))[:, lw]
        else:
            rows: Dict[int, np.ndarray] = {}
            for sh in primed_gidx.addressable_shards:
                start = sh.index[1].start or 0
                data = np.asarray(sh.data)       # [depth, nw, S]
                for j in range(data.shape[1]):
                    rows[start + j] = data[:, j]
            local = np.stack([rows[int(g)] for g in lw], axis=1)
        for i in range(self.config.prefetch_depth):
            self._stream_pipe.push(local[i])

    def _refill_stream_pipe(self) -> None:
        """Re-seed the prefetch pipeline from ``state.pending_sel`` after a
        checkpoint restore: every in-flight batch belongs to the previous
        trajectory, but the restored ring's slots are exactly the
        selections steps t..t+depth-1 will train on — push their global
        rows so the pop→step→push cadence resumes unchanged."""
        if getattr(self, "_stream_pipe", None) is None:
            return
        with self.tracer.span("trainer/refill_stream_pipe", cat="trainer"):
            self._stream_pipe.reset()
            # [W, depth, S] shard-local slots → global ids via the HOST
            # copy of the shard index table (the globalized device copy is
            # not addressable across hosts). Multi-controller reads only
            # this host's worker rows of the P(data)-sharded slots.
            slots_arr = self.state.pending_sel.slots
            if getattr(slots_arr, "is_fully_addressable", True):
                slots = np.asarray(jax.device_get(slots_arr))
                workers = np.arange(slots.shape[0])
            else:
                owned: Dict[int, np.ndarray] = {}
                for sh in slots_arr.addressable_shards:
                    start = sh.index[0].start or 0
                    data = np.asarray(sh.data)   # [nw, depth, S]
                    for j in range(data.shape[0]):
                        owned[start + j] = data[j]
                workers = np.asarray(sorted(owned))
                slots = np.stack([owned[int(w)] for w in workers])
            shard_indices = self._host_shard_indices
            for d in range(slots.shape[1]):
                gidx = np.stack([
                    shard_indices[w][slots[i, d]]
                    for i, w in enumerate(workers)
                ])
                self._stream_pipe.push(gidx)

    def _restart_stream_pipe(self) -> None:
        """Supervisor restart: tear down the dead pipeline and build a
        generation-bumped replacement, resuming from the stream cursor.
        ``state.pending_sel`` holds the selections for steps
        t..t+depth-1 regardless of where the worker died, and
        ``_refill_stream_pipe`` recomputes ALL depth in-flight gathers
        from it — so the restarted trajectory is bit-identical to an
        uninterrupted one (test-enforced)."""
        from mercury_tpu.data.stream import HostStreamSource, PrefetchPipeline

        cfg = self.config
        old = self._stream_pipe
        self._stream_gen += 1
        try:
            old.close(timeout=5.0)
        except Exception as exc:
            _log.warning("dead prefetch pipeline close() raised: %s", exc)
        source = HostStreamSource(
            np.asarray(self.dataset.x_train),
            decode_workers=cfg.decode_workers,
        )
        self._stream_pipe = PrefetchPipeline(
            source,
            (cfg.world_size, self._stream_emit_size()),
            self._stream_x_sharding,
            depth=cfg.prefetch_depth,
            tracer=self.tracer,
            local_workers=self._stream_local_workers,
            faults=self._faults,
            generation=self._stream_gen,
        )
        self._refill_stream_pipe()

    # --------------------------------------------------- async scorer fleet
    def _make_refresh_apply(self):
        """Jitted ``[W]``-vmapped chunk scatter for the async fleet
        (``apply_async_chunk`` per worker row), output pinned to the
        scoretable's data-axis layout so applying a chunk never perturbs
        the step's committed state sharding (jit-cache stability)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from mercury_tpu.sampling.scoretable import (
            ScoreTableState,
            apply_async_chunk,
        )

        sh = NamedSharding(self.mesh, P(self.config.mesh_axis))

        def apply(tab, ema_value, slots, values, weight):
            new_scores = jax.vmap(
                apply_async_chunk, in_axes=(0, 0, 0, 0, None)
            )(tab.scores, slots, values, ema_value, weight)
            return tab._replace(scores=new_scores)

        return jax.jit(
            apply,
            out_shardings=ScoreTableState(scores=sh, cursor=sh),
        )

    def _apply_chunks(self, chunks, step: int) -> None:
        """Scatter scored chunks into the device score table
        (staleness-weighted by ``table_decay**age``, the exact in-graph
        decay an age-0 apply would have accrued). Non-finite chunks are
        REJECTED and counted (``sampler/chunks_rejected``): a corrupted
        chunk (scorer_nan injection, a diverged scoring forward) must
        never poison the sampling distribution — max(NaN, ε) semantics
        would otherwise zero that slot's probability forever."""
        fleet = self._scorer_fleet
        for chunk in chunks:
            if not np.all(np.isfinite(chunk.scores)):
                self._chunks_rejected += 1  # graftlint: disable=GL120 -- _apply_chunks runs on the trainer thread only: the supervisor probe/restart callbacks that reach it are fit-loop calls, never the monitor thread
                _log.warning(
                    "rejected a non-finite score chunk (snapshot step %d) "
                    "at step %d — table untouched", chunk.step, step)
                continue
            age = max(step - chunk.step, 0)
            weight = jnp.float32(self.config.table_decay ** age)
            new_tab = self._apply_refresh(
                self.state.scoretable, self.state.ema.value,
                jnp.asarray(chunk.slots), jnp.asarray(chunk.scores),
                weight,
            )
            self.state = self.state.replace(scoretable=new_tab)
            fleet.note_applied(age)

    def _async_refresh_tick(self, step: int, advanced: int = 1) -> None:
        """Per-iteration fleet service (ladder level 0): scatter every
        ready chunk into the device score table and re-snapshot the
        params on the ``snapshot_every`` cadence. Host ints only — no
        device sync ever happens on this thread."""
        fleet = self._scorer_fleet
        if fleet is None:
            return
        if self.supervisor is not None and not fleet.alive():
            # A worker died mid-interval: skip this drain (drain() would
            # raise) — supervisor.tick() restarts the fleet or walks the
            # ladder; queued chunks survive the restart.
            return
        if hasattr(fleet, "drain_for_step"):
            # ScorerService: the step-aware drain also advances every
            # tenant's staleness clock (the SLO input) and empties the
            # non-primary tenants' queues into their accounting.
            chunks = fleet.drain_for_step(step)
        else:
            chunks = fleet.drain()
        if chunks:
            with self.tracer.span("trainer/apply_refresh", cat="trainer",
                                  chunks=len(chunks)):
                self._apply_chunks(chunks, step)
        every = int(self.config.snapshot_every)
        if (step // every) > ((step - advanced) // every):
            # The identity-jit inside snapshot() copies — the live state
            # is donated into the next dispatch, so the fleet must never
            # hold its buffers.
            fleet.snapshot(self.state.params, self.state.batch_stats, step)

    def _sync_refresh_tick(self, step: int, advanced: int = 1) -> None:
        """Ladder level 1: the async fleet is gone, so the TRAINER thread
        scores one round-robin chunk every ``supervisor_sync_every``
        steps (``ScorerFleet.score_once`` — no worker threads involved).
        A failure here escalates the ladder one level."""
        fleet = self._scorer_fleet
        every = max(int(self.config.supervisor_sync_every), 1)
        if (step // every) <= ((step - advanced) // every):
            return
        try:
            with self.tracer.span("trainer/sync_refresh", cat="trainer"):
                # Snapshot first: level 1 has no background cadence, so
                # the sync chunk always scores the CURRENT params.
                fleet.snapshot(self.state.params, self.state.batch_stats,
                               step)
                chunk = fleet.score_once()
        except Exception as exc:
            self.supervisor.report_failure("sync refresh", step, exc)
            return
        self._apply_chunks([chunk], step)

    def _make_table_flatten(self):
        """Jitted table flatten for ladder level 3: zeroed scores make
        ``p ∝ max(score + α·EMA_mean, ε)`` a per-row constant, so the
        step's inverse-CDF draw IS uniform sampling — no retrace, no
        program change, just constant table contents. Output pinned to
        the table's committed data-axis layout (jit-cache stability)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P
        from mercury_tpu.sampling.scoretable import ScoreTableState

        sh = NamedSharding(self.mesh, P(self.config.mesh_axis))
        return jax.jit(
            lambda tab: tab._replace(scores=jnp.zeros_like(tab.scores)),
            out_shardings=ScoreTableState(scores=sh, cursor=sh),
        )

    def _refresh_tick(self, step: int, advanced: int = 1) -> None:
        """Ladder-aware refresh dispatch, called once per fit iteration.
        Level 0 drains the async fleet; level 1 scores on the trainer
        thread; level 2 (frozen) does nothing — the in-graph decay keeps
        flattening the table toward the EMA mean; level 3 re-pins the
        table to a constant EVERY iteration, making the draw uniform
        (``sampler/is_active=0``). Per-iteration, not once: the step's
        free write-back re-scores the trained slots in-graph (it cannot
        be gated without a retrace), so a one-shot flatten would let S
        of L slots re-tilt each draw — the host pin bounds that tilt to
        the single in-flight step."""
        sup = self.supervisor
        level = 0 if sup is None else sup.level()
        if level == 0:
            self._async_refresh_tick(step, advanced)
        elif level == 1:
            self._sync_refresh_tick(step, advanced)
        if sup is None:
            return
        if level >= 3:
            if not hasattr(self, "_flatten_table"):
                self._flatten_table = self._make_table_flatten()
            self.state = self.state.replace(
                scoretable=self._flatten_table(self.state.scoretable))
            if self._actuated_level < 3:
                self._actuated_level = 3
                _log.warning(
                    "sampler degraded to UNIFORM at step %d: score table "
                    "flattened (sampler/is_active=0)", step)
        elif level < 3:
            # A recovery below uniform needs no inverse actuation: the
            # resumed refresh path (and the in-graph EMA updates) repaint
            # the flattened table organically.
            self._actuated_level = level

    def _probe_scoring(self) -> None:
        """Supervisor recovery probe: one trainer-thread scoring round
        against fresh params, applied to the table. Raises on any
        failure (the supervisor escalates); success climbs the ladder."""
        fleet = self._scorer_fleet
        if fleet is None:
            raise RuntimeError("no scorer fleet to probe")
        step = self._host_step
        fleet.snapshot(self.state.params, self.state.batch_stats, step)
        chunk = fleet.score_once()
        if not np.all(np.isfinite(chunk.scores)):
            raise RuntimeError("probe chunk contains non-finite scores")
        self._apply_chunks([chunk], step)

    # ---------------------------------------------------------- flight data
    def _plan_facts(self) -> Optional[Dict[str, Any]]:
        """Active auto-planner decision for status surfaces (the
        supervisor's ``summary()``/statusz ``plan`` field). None when the
        run is manually planned."""
        decision = self._plan_decision
        if decision is None:
            return None
        return {
            "requested": self.config.plan,
            "selected": decision.selected,
            "candidates_considered": len(decision.candidates),
            "feasible": [c.name for c in decision.feasible],
            "replans": self._replan_count,
        }

    def _flight_context(self) -> Dict[str, Any]:
        """Run context for flight-record dumps (obs/anomaly.py) —
        evaluated lazily, only when a trigger actually fires."""
        ctx: Dict[str, Any] = {
            "config": dataclasses.asdict(self.config),
            "manifest": build_run_manifest(self.config, self.mesh),
        }
        pipe = getattr(self, "_stream_pipe", None)
        if pipe is not None:
            ctx["pipeline"] = pipe.summary()
        fleet = getattr(self, "_scorer_fleet", None)
        if fleet is not None:
            ctx["scorer_fleet"] = fleet.summary()
        supervisor = getattr(self, "supervisor", None)
        if supervisor is not None:
            ctx["supervisor"] = supervisor.summary()
        faults = getattr(self, "_faults", None)
        if faults is not None:
            ctx["faults"] = faults.summary()
        return ctx

    def arm_retrace_guard(self):
        """Arm the Layer P runtime retrace guard for this trainer.

        Installs a :class:`mercury_tpu.lint.tracecheck.CompileMonitor`
        whose per-tick deltas the log gate emits as
        ``lint/retrace_events`` / ``lint/compile_count``. In steady state
        both should be 0 every tick; a nonzero reading names a step that
        re-entered the compiler (the offline guard,
        ``python -m mercury_tpu.lint.tracecheck``, then attributes it).
        Idempotent; returns the monitor so tests can snapshot it."""
        if self._retrace_monitor is None:
            from mercury_tpu.lint.tracecheck import CompileMonitor

            self._retrace_monitor = CompileMonitor()
            self._retrace_monitor.start()
            self._retrace_last = (0, 0)
        return self._retrace_monitor

    # ------------------------------------------------------------------ fit
    def fit(self, num_epochs: Optional[int] = None) -> Dict[str, float]:
        """Run training (``Trainer.fit``, ``pytorch_collab.py:56-72``).

        Returns the final eval metrics. Honors the step-budget break
        (``step×world_size > budget``, ``:71``)."""
        cfg = self.config
        num_epochs = num_epochs or cfg.num_epochs
        step = int(self.state.step)
        self._throughput.reset(step)
        final_metrics: Dict[str, float] = {}

        # End of the run: num_epochs' worth of steps from here, clipped by
        # the step budget — the reference executes the first step for which
        # step×world_size > budget, then breaks (:71). After an actual
        # auto-resume the horizon is absolute (finish the original run), so
        # re-running the same script after a crash completes it instead of
        # extending it; ordinary fit() calls keep the relative horizon.
        if self._auto_resumed:
            target = self.steps_per_epoch * num_epochs
            # Consumed: the absolute horizon applies only to the first
            # fit() after the resume; later calls are ordinary.
            self._auto_resumed = False
        else:
            target = step + self.steps_per_epoch * num_epochs
        budget_cap = int(cfg.step_budget // cfg.world_size) + 1
        end = min(target, budget_cap)

        def crossed(every: int, at: int, advanced: int) -> bool:
            """Did [at-advanced, at] cross a multiple of ``every``?"""
            return bool(every) and (at // every) > ((at - advanced) // every)

        self.tracer.register_thread("train")
        try:
            while step < end:
                # Wall time of the whole training action: under async
                # dispatch each iteration converges to the true device
                # step cadence once the dispatch queue applies
                # backpressure — exactly the signal slow_step wants.
                t_iter = time.perf_counter()
                if self._faults is not None:
                    # Advance the fault plane's step clock (workers fire
                    # against it) and run the trainer-thread hook.
                    self._faults.note_step(step)
                    slow = self._faults.fire("host_slow")
                    if slow is not None:
                        time.sleep(float(slow.get("secs", 1.0)))
                if self._stream_pipe is not None:
                    k = 1
                    metrics = self._host_stream_step(step)
                elif self.train_step_many is not None and step + self.scan_steps <= end:
                    k = self.scan_steps
                    with self.tracer.span("trainer/dispatch",
                                          cat="trainer", steps=k):
                        self.state, metrics = self.train_step_many(
                            self.state,
                            self._step_x,
                            self._step_y,
                            self.dataset.shard_indices,
                        )
                else:
                    k = 1
                    with self.tracer.span("trainer/dispatch", cat="trainer"):
                        self.state, metrics = self.train_step(
                            self.state,
                            self._step_x,
                            self._step_y,
                            self.dataset.shard_indices,
                        )
                step += k
                self._host_step = step
                if self._scorer_fleet is not None:
                    # Scatter ready async-refresh chunks and re-snapshot on
                    # cadence — host bookkeeping + async device dispatches,
                    # nothing here waits on the step. Ladder-aware: a
                    # degraded run refreshes on this thread, freezes, or
                    # flattens to uniform (_refresh_tick).
                    self._refresh_tick(step, advanced=k)
                if self.supervisor is not None:
                    # Liveness check + restarts + recovery probing —
                    # host bookkeeping on the step cadence.
                    self.supervisor.tick(step)
                if self.anomaly is not None:
                    self.anomaly.observe_step_time(
                        step, time.perf_counter() - t_iter, steps=k)
                # On-demand profiler window: an anomaly trigger arms M
                # steps of jax.profiler capture; open it here (next
                # occurrence of a sporadic anomaly lands inside it) and
                # close it M steps later.
                if self._profile_steps_left > 0:
                    self._profile_steps_left -= k
                    if self._profile_steps_left <= 0:
                        self._stop_profiler()
                elif self.anomaly is not None:
                    want = self.anomaly.take_profile_request()
                    if want > 0:
                        self._start_profiler(want)
                if crossed(cfg.log_every, step, k):
                    if not self._flops_known:
                        # First log gate: ask XLA's cost model for the
                        # step program's FLOPs (re-traces but does NOT
                        # re-compile — see analytic_flops_per_step),
                        # enabling perf/mfu.
                        fn, ks = ((self.train_step_many, self.scan_steps)
                                  if k > 1 else (self.train_step, 1))
                        self._throughput.flops_per_step = (
                            analytic_flops_per_step(
                                fn, self.state, self._step_x, self._step_y,
                                self.dataset.shard_indices, scan_steps=ks,
                            )
                        )
                        self._flops_known = True
                    # Enqueue the ON-DEVICE metric pytree: no float(), no
                    # device sync, no filesystem write on this thread. The
                    # drain thread device_gets and reduces scanned [K]
                    # metric series to their chunk MEAN (keeping only the
                    # last entry would discard (K-1)/K of the signal) —
                    # obs/writer.py:_to_host_record. Safe to hold: metric
                    # outputs are not donated (only the state is).
                    with self.tracer.span("trainer/log_gate",
                                          cat="trainer", step=step):
                        record = dict(metrics)
                        record.update(self._throughput.tick(step))
                        if self._stream_pipe is not None:
                            # Host-side floats (stall/queue/bytes since
                            # the last log): no device sync, safe to
                            # merge here.
                            record.update(self._stream_pipe.stats())
                        if self._scorer_fleet is not None:
                            # Same contract: host counters only
                            # (scorer/throughput, staleness, lag).
                            record.update(self._scorer_fleet.stats())
                            record["sampler/chunks_rejected"] = float(
                                self._chunks_rejected)
                        if self._sampler_monitor is not None:
                            # Ledger-derived distribution stats: ONE
                            # [W, L] int32 device fetch per log tick
                            # (plus the score table for the bias
                            # audit) — the only log-gate merge that
                            # touches the device, scaled by log_every.
                            record.update(
                                self._sampler_monitor.stats(self.state))
                        if self.supervisor is not None:
                            # Ladder level, restarts, degradations — and
                            # sampler/is_active (0.0 once uniform).
                            record.update(self.supervisor.stats())
                        if self._faults is not None:
                            record.update(self._faults.stats())
                        if cfg.checkpoint_dir:
                            record["checkpoint/write_failures"] = float(
                                ckpt.write_failures())
                        if self._plan_decision is not None:
                            # Auto-planner bookkeeping (host floats):
                            # decision width + elastic re-plan count.
                            record["plan/candidates_considered"] = float(
                                len(self._plan_decision.candidates))
                            record["plan/replan_count"] = float(
                                self._replan_count)
                        # Thread-fleet liveness (Layer C telemetry):
                        # process-wide census + the metric queue's own
                        # depth; the prefetch/scorer depths rode in with
                        # their stats() above. Host-only, no sync.
                        record.update(host_thread_stats())
                        record["threads/queue_depth/metrics"] = float(
                            self.logger.queue_depth())
                        if self._retrace_monitor is not None:
                            # Retrace guard armed: per-tick deltas of the
                            # process-wide trace/compile event counters.
                            # Steady state is 0/0 — anything else means a
                            # step re-entered the compiler this interval.
                            traces, compiles = \
                                self._retrace_monitor.snapshot()
                            lt, lc = self._retrace_last
                            record["lint/retrace_events"] = float(
                                traces - lt)
                            record["lint/compile_count"] = float(
                                compiles - lc)
                            self._retrace_last = (traces, compiles)
                        record["epoch"] = (step - 1) // self.steps_per_epoch
                        if self._crosshost_gather is not None:
                            # allgather mode: EVERY process participates
                            # in the (deterministic-cadence) collective;
                            # only host 0 gets a non-empty merge back.
                            record.update(
                                self._crosshost_gather.update(record))
                        # Fault injection (tests/CI): poison the HOST
                        # record so the non_finite trigger path runs
                        # end-to-end; the traced program is untouched.
                        if (cfg.anomaly_inject_nan_step
                                and not self._nan_injected
                                and step >= cfg.anomaly_inject_nan_step):
                            record["train/loss"] = float("nan")
                            self._nan_injected = True
                        self.logger.write(step, record)
                if crossed(cfg.eval_every, step, k):
                    with self.tracer.span("trainer/eval", cat="trainer",
                                          step=step):
                        final_metrics = self.evaluate()
                    self.logger.log_scalars(step, final_metrics)
                    print(
                        f"  eval @ {step}: "
                        + " ".join(f"{k}={v:.4f}" for k, v in final_metrics.items())
                    )
                if cfg.checkpoint_dir and crossed(cfg.checkpoint_every, step, k):
                    with self.tracer.span("trainer/checkpoint",
                                          cat="trainer", step=step):
                        if cfg.async_checkpoint:
                            # One in-flight write at a time: join the
                            # previous before fetching the next snapshot.
                            if self._ckpt_thread is not None:
                                self._ckpt_thread.join()
                            self._ckpt_thread = ckpt.save_checkpoint_async(
                                cfg.checkpoint_dir, self.state, step,
                                failure_cb=self._ckpt_failure_cb,
                                **self._ckpt_kwargs(),
                            )
                        else:
                            ckpt.save_checkpoint(cfg.checkpoint_dir,
                                                 self.state, step,
                                                 **self._ckpt_kwargs())
        finally:
            # An exception mid-loop (KeyboardInterrupt, eval error) must not
            # leave a write in flight — a relaunched auto_resume reading a
            # half-written file would restore garbage.
            if self._ckpt_thread is not None:
                self._ckpt_thread.join()
                self._ckpt_thread = None
            # Drain the metric queue to the sinks so callers (and crashed
            # runs' postmortems) see every step logged up to here. The
            # writer itself stays open — fit() can be called again.
            self.logger.flush()
        if not final_metrics:
            final_metrics = self.evaluate()
        if cfg.checkpoint_dir:
            ckpt.save_checkpoint(cfg.checkpoint_dir, self.state, step,
                                 **self._ckpt_kwargs())
        return final_metrics

    def _ckpt_kwargs(self) -> Dict[str, Any]:
        """Durability knobs threaded into every cadence/final save."""
        cfg = self.config
        return dict(
            keep=cfg.checkpoint_keep,
            retries=cfg.checkpoint_write_retries,
            retry_backoff_s=cfg.checkpoint_retry_backoff_s,
            manifest=cfg.checkpoint_manifest,
            faults=self._faults,
            journal=self._journal,
        )

    def _ckpt_failure_cb(self, exc: BaseException) -> None:
        """Async-writer failure hook (runs ON the ckpt-write thread):
        leave a flight record immediately — join() may be a cadence away
        and a wedged run never joins. Never raises."""
        try:
            if self.anomaly is not None:
                self.anomaly.dump_flight_record(
                    "checkpoint_write_failed", self._host_step, {
                        "error": f"{type(exc).__name__}: {exc}",
                        "write_failures": ckpt.write_failures(),
                    })
        except Exception:
            _log.warning("checkpoint failure flight record failed",
                         exc_info=True)

    # ------------------------------------------------- profiler window
    def _start_profiler(self, steps: int) -> None:
        """Open a ``jax.profiler`` capture for the next ``steps`` steps
        (anomaly-armed). Never raises — profiling is best-effort."""
        logdir = self.config.anomaly_dir or self.config.log_dir
        if not logdir or self._profiling:
            return
        path = os.path.join(logdir, "profile")
        try:
            jax.profiler.start_trace(path)
        except Exception as exc:
            _log.warning("profiler start failed: %s", exc)
            return
        self._profiling = True
        self._profile_steps_left = int(steps)
        self.tracer.instant("profiler/start", cat="trainer", steps=steps)
        _log.warning("anomaly-armed profiler capture: %d steps -> %s",
                     steps, path)

    def _stop_profiler(self) -> None:
        if not self._profiling:
            return
        self._profiling = False
        self._profile_steps_left = 0
        try:
            jax.profiler.stop_trace()
        except Exception as exc:
            _log.warning("profiler stop failed: %s", exc)
        self.tracer.instant("profiler/stop", cat="trainer")
        self._fold_back_profile()

    def _fold_back_profile(self) -> None:
        """Attribute the capture that just closed (obs/profile_parse —
        offline parse, no jax) and fold the result into the metric
        stream as prof/scope_frac/* + write device_time_breakdown.json
        next to the metrics. Best-effort: a capture format we can't
        parse must never take the run down."""
        logdir = self.config.anomaly_dir or self.config.log_dir
        if not logdir or jax.process_index() != 0:
            return
        try:
            from mercury_tpu.obs.profile_parse import (
                parse_profile,
                scope_frac_metrics,
                write_breakdown,
            )

            breakdown = parse_profile(os.path.join(logdir, "profile"))
            out_dir = self.config.log_dir or logdir
            write_breakdown(
                breakdown,
                os.path.join(out_dir, "device_time_breakdown.json"))
            if breakdown["total_device_time_us"] > 0:
                step = getattr(self._throughput, "_last_step", None) or 0
                self.logger.write(step, scope_frac_metrics(breakdown))
            _log.warning(
                "device-time breakdown written: %.1f%% attributed to "
                "named scopes",
                100.0 * (1.0 - breakdown["scopes"]
                         .get("unattributed", {}).get("frac", 0.0)))
        except Exception as exc:
            _log.warning("profile fold-back failed: %s: %s",
                         type(exc).__name__, exc)

    def close(self) -> None:
        """Shut down the trainer's background subsystems — scorer fleet,
        prefetch pipeline, armed profiler, span-trace export, metric
        writer — in dependency order: producers (threads that can still
        emit work or spans) stop before the sinks they feed.

        Idempotent (a second call is a no-op — the subsystems' own
        ``close()`` methods tolerate repeats, and the ``_closed`` latch
        skips the trace re-export) and safe on partially-constructed
        trainers: every attribute access is guarded, so a constructor
        that raised halfway still closes cleanly
        (``tests/test_async_refresh.py`` pins both). A trainer also works
        as a context manager: ``with Trainer(cfg) as t: t.fit()``."""
        if getattr(self, "_closed", False):
            return
        self._closed = True
        try:
            server = getattr(self, "_status_server", None)
            if server is not None:
                # Scrapers go first: a request arriving mid-teardown
                # would read half-closed subsystems.
                server.close()
            supervisor = getattr(self, "supervisor", None)
            if supervisor is not None:
                # A live supervisor poll/probe must not race the unit
                # teardown below (it would read restarts as deaths).
                supervisor.close()
            fleet = getattr(self, "_scorer_fleet", None)
            if fleet is not None:
                fleet.close()
            monitor = getattr(self, "_retrace_monitor", None)
            if monitor is not None:
                monitor.stop()
            if getattr(self, "_stream_pipe", None) is not None:
                self._stream_pipe.close()
            if getattr(self, "_profiling", False):
                self._stop_profiler()
            tracer = getattr(self, "tracer", None)
            config = getattr(self, "config", None)
            journal = getattr(self, "_journal", None)
            if (tracer is not None and tracer.enabled
                    and config is not None and config.log_dir
                    and jax.process_index() == 0):
                try:
                    # Merge the control-plane journal into the exported
                    # timeline: spans + decision instants + causal flow
                    # arrows land in ONE perfetto-loadable trace.json.
                    events = []
                    if journal is not None:
                        from mercury_tpu.obs.events import (
                            journal_filename,
                            read_journal,
                        )

                        journal.flush()
                        events = read_journal(os.path.join(
                            config.log_dir,
                            journal_filename(jax.process_index())))
                    tracer.export_chrome_trace(
                        os.path.join(config.log_dir, "trace.json"),
                        events=events or None)
                except Exception as exc:
                    _log.warning("trace export failed: %s", exc)
            logger = getattr(self, "logger", None)
            if logger is not None:
                logger.close()
        finally:
            # Even a teardown crash leaves the ladder history and the
            # journal on disk — they are the post-mortem.
            self._write_supervisor_summary()
            journal = getattr(self, "_journal", None)
            if journal is not None:
                journal.close()

    def _write_supervisor_summary(self) -> None:
        """Persist ``HostSupervisor.summary()`` (ladder transitions,
        restart budgets, SLO latch counts) as ``supervisor_summary.json``
        — called from ``close()``'s finally so a crashed run still
        leaves its ladder history on disk. Never raises."""
        supervisor = getattr(self, "supervisor", None)
        config = getattr(self, "config", None)
        if (supervisor is None or config is None or not config.log_dir
                or jax.process_index() != 0):
            return
        try:
            path = os.path.join(config.log_dir,
                                "supervisor_summary.json")
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(supervisor.summary(), f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, path)
        except Exception as exc:
            _log.warning("supervisor summary write failed: %s", exc)

    def __enter__(self) -> "Trainer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ----------------------------------------------------------------- eval
    def _eval_arrays(self, train: bool):
        """Pre-batched uint8 arrays + masks for one split, cached — the
        whole split then evals in a single scanned device call."""
        if train not in self._eval_cache:
            x = self.dataset.x_train if train else self.dataset.x_test
            y = self.dataset.y_train if train else self.dataset.y_test
            n = int(x.shape[0])
            plan = eval_batches(n, self._eval_batch)
            idx = np.stack([p[0] for p in plan])                     # [nb, B]
            valid = np.stack([
                np.arange(self._eval_batch) < p[1] for p in plan
            ])                                                       # [nb, B]
            # Multi-controller: keep eval inputs as host arrays — jit treats
            # them as replicated, compatible with the global params. (A
            # committed process-local device array would conflict.) Same
            # for sharded data placement: eval reads the host copy rather
            # than committing a device-replicated full split.
            conv = (np.asarray
                    if jax.process_count() > 1
                    or self.config.data_placement in ("sharded",
                                                      "host_stream")
                    else jnp.asarray)
            self._eval_cache[train] = (
                conv(np.asarray(x)[idx]),
                conv(np.asarray(y)[idx]),
                conv(valid),
            )
        return self._eval_cache[train]

    def _eval_split(self, train: bool) -> Dict[str, float]:
        images_b, labels_b, valid_b = self._eval_arrays(train)
        loss_sum, correct, count = self.eval_epoch(
            self.state.params, self.state.batch_stats, images_b, labels_b, valid_b
        )
        count = max(float(count), 1.0)
        prefix = "train" if train else "test"
        return {
            f"{prefix}/eval_loss": float(loss_sum) / count,
            f"{prefix}/eval_acc": float(correct) / count,
        }

    def evaluate(self, include_train: bool = True) -> Dict[str, float]:
        """Full train+test pass in inference mode
        (``Trainer.evaluate``, ``pytorch_collab.py:201-234``)."""
        out: Dict[str, float] = {}
        if include_train:
            out.update(self._eval_split(train=True))
        out.update(self._eval_split(train=False))
        return out

    # ------------------------------------------------------------- inference
    def predict(self, inputs) -> np.ndarray:
        """Inference-mode logits for raw inputs.

        ``inputs``: ``[N, H, W, C]`` images (uint8 or float — normalized
        with the dataset's statistics, as eval does) or ``[N, T, F]``
        sequences (passed through). Returns ``[N, num_classes]`` float32
        logits; ``argmax(-1)`` gives class predictions. The reference has
        no inference entry point at all — evaluation is the closest thing
        (``pytorch_collab.py:201-234``).
        """
        # Multi-controller: keep inputs host-resident (replicated by jit)
        # so they compose with the global params — same guard as
        # _eval_arrays.
        x = np.asarray(inputs)
        if x.ndim == len(self.dataset.x_train.shape[1:]):
            x = x[None]  # single sample convenience
        if not hasattr(self, "_predict_fn"):
            model = self.model
            mean, std = self.dataset.mean, self.dataset.std
            iid_eval = self.config.augmentation == "iid"

            def fwd(params, batch_stats, x):
                from mercury_tpu.data.pipeline import normalize_images

                # The exact eval-path preprocessing (make_eval_epoch):
                # normalize (no-op stats for sequences), and the IID
                # path's fixed-key eval transform.
                x = normalize_images(x, mean, std)
                if iid_eval:
                    from mercury_tpu.data.transforms import eval_transform_iid

                    x = eval_transform_iid(jax.random.key(0), x)
                variables = {"params": params}
                if batch_stats:
                    variables["batch_stats"] = batch_stats
                return model.apply(variables, x, train=False)

            self._predict_fn = jax.jit(fwd)
        return np.asarray(
            self._predict_fn(self.state.params, self.state.batch_stats, x),
            np.float32,
        )

    def per_class_accuracy(self, train: bool = False) -> np.ndarray:
        """Per-class accuracy over a split — the class-level view the
        reference's scalar metrics can't give (relevant under Dirichlet
        non-IID skew, where aggregate accuracy hides starved classes).
        One scanned device dispatch over the cached eval batches (same
        sharding as ``evaluate``). Returns ``[num_classes]`` float64;
        classes absent from the split are NaN."""
        if not hasattr(self, "_per_class_fn"):
            from mercury_tpu.train.step import make_per_class_epoch

            self._per_class_fn = make_per_class_epoch(
                self.model, self.dataset.mean, self.dataset.std,
                self.dataset.num_classes,
                eval_augmentation=self.config.augmentation
                if self.config.augmentation == "iid" else "none",
                mesh=(self.mesh if jax.process_count() == 1
                      and self.config.tensor_parallel == 1
                      and self.config.fsdp_parallel == 1 else None),
                axis=self.config.mesh_axis,
            )
        images_b, labels_b, valid_b = self._eval_arrays(train)
        hits, totals = self._per_class_fn(
            self.state.params, self.state.batch_stats,
            images_b, labels_b, valid_b,
        )
        hits = np.asarray(hits, np.int64)
        totals = np.asarray(totals, np.int64)
        with np.errstate(invalid="ignore"):
            return np.where(totals > 0, hits / np.maximum(totals, 1), np.nan)

    # ----------------------------------------------------- checkpoint hooks
    def save(self, directory: Optional[str] = None) -> str:
        directory = directory or self.config.checkpoint_dir
        assert directory, "no checkpoint directory configured"
        return ckpt.save_checkpoint(directory, self.state,
                                    int(self.state.step),
                                    **self._ckpt_kwargs())

    def _recommit_state(self, reprime_stream: bool = False) -> None:
        """Re-place a host-resident ``self.state`` for this trainer's
        topology: global arrays over the cross-process mesh
        (multi-controller), and/or the committed Megatron TP layout —
        so the first post-restore step hits the jit cache (the input
        sharding signature is part of it) and the layout-stability
        invariant holds from step one. Shared by ``restore`` and
        ``restore_elastic``.

        Single-process restores must NOT skip this: the checkpoint
        reader hands back host numpy leaves, and donating those into a
        step executable replayed from the persistent compilation cache
        corrupts the transient input buffers (NaN params or SIGSEGV on
        the following step, jax 0.4.37 CPU). Committing the whole state
        to the step's layout first makes the first donated call operate
        on real device buffers."""
        if jax.process_count() > 1:
            from mercury_tpu.parallel.distributed import globalize_state

            tp_kw = {}
            if self._state_out_shardings is not None:
                state_sh, _ = self._state_out_shardings
                tp_kw = dict(params_sharding=state_sh.params,
                             opt_sharding=state_sh.opt_state)
            self.state = globalize_state(
                self.state, self.mesh, self.config.mesh_axis,
                zero_sharding=self.config.zero_sharding, **tp_kw,
            )
        else:
            if self._state_out_shardings is not None:
                state_sh, _ = self._state_out_shardings
            else:
                # Non-TP: params/opt replicated, sampler state sharded
                # over the data axis — the same layout the step program
                # produces.
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P
                from mercury_tpu.train.step import (
                    mercury_state_out_shardings,
                )

                cfg = self.config
                rep = NamedSharding(self.mesh, P())
                state_sh, _ = mercury_state_out_shardings(
                    self.mesh, cfg.mesh_axis, rep, rep,
                    has_groupwise=(cfg.use_importance_sampling
                                   and cfg.sampler == "groupwise"),
                    has_pending=(cfg.use_importance_sampling
                                 and cfg.pipelined_scoring),
                    has_cached_pool=(cfg.use_importance_sampling
                                     and cfg.sampler == "pool"
                                     and cfg.score_refresh_every > 1),
                    has_scoretable=(cfg.use_importance_sampling
                                    and cfg.sampler == "scoretable"),
                    has_pending_sel=(cfg.data_placement == "host_stream"),
                    has_sel_counts=(cfg.use_importance_sampling
                                    and cfg.sampler == "scoretable"
                                    and bool(cfg.telemetry)),
                )
            # Identity jit, not a bare device_put: on CPU device_put may
            # zero-copy alias the checkpoint reader's host buffers, and
            # the first donated step would then hand XLA memory it
            # doesn't own. Executable outputs are always XLA-allocated.
            self.state = jax.jit(lambda s: s, out_shardings=state_sh)(
                jax.device_put(self.state, state_sh)
            )
        if reprime_stream and getattr(self, "_stream_pipe", None) is not None:
            # Elastic restore: the live ring was drawn for the OLD (W, L)
            # topology — regenerate depth in-flight selections from the
            # restored (step-folded) rng and seed the pipeline with them.
            self.state, primed_gidx = self._stream_prime(
                self.state, self.dataset.shard_indices
            )
            self._seed_stream_pipe(primed_gidx)
        else:
            # The restored pending_sel ring defines steps t..t+depth-1's
            # selections; re-seed the prefetch pipeline with their rows.
            self._refill_stream_pipe()
        # Async fleet: queued chunks scored the pre-restore trajectory —
        # discard them and re-snapshot from the restored params (a restore
        # is already a sync point, so the int() here costs nothing new).
        fleet = getattr(self, "_scorer_fleet", None)
        if fleet is not None:
            fleet.reset()
            fleet.snapshot(self.state.params, self.state.batch_stats,
                           int(self.state.step))

    def restore_elastic(self, directory: Optional[str] = None,
                        step: Optional[int] = None, raw=None) -> int:
        """Restore a checkpoint saved at a DIFFERENT world size: model and
        optimizer state transfer exactly (ZeRO-1 chunks reshard W→W′);
        per-worker sampler state re-derives for the new topology. See
        ``mercury_tpu.train.elastic``. ``raw`` passes a pre-probed raw
        checkpoint tree (with its ``step``) to skip re-reading the file.
        The reference hangs on any topology change
        (``pytorch_collab.py:291-292``)."""
        from mercury_tpu.train.elastic import (
            elastic_restore,
            probe_checkpoint,
            world_size_of_raw,
        )

        directory = directory or self.config.checkpoint_dir
        assert directory, "no checkpoint directory configured"
        if raw is None:
            raw, step = probe_checkpoint(directory, step, strict=True)
        w_old = world_size_of_raw(raw)
        step = elastic_restore(directory, self, step, raw=raw)
        # --- auto-planner elastic re-plan: the constructor already
        # resolved plan="auto" for the NEW mesh; here the topology change
        # becomes visible (w_old → world_size), so score the OLD mesh too
        # and journal both tables — the conformance record that the plan
        # switch (or non-switch) was a scored decision, not drift. The
        # applied knobs are the construction-time resolution's (the whole
        # trainer is already built on them). DESIGN.md §16.
        if (self.config.plan == "auto" and self._plan_decision is not None
                and w_old and w_old != self.config.world_size):
            from mercury_tpu.plan.auto import decision_for_config

            old_decision = decision_for_config(
                self.config,
                device_kind=jax.devices()[0].device_kind,
                process_count=jax.process_count(),
                world_size=w_old,
            )
            self._replan_count += 1
            if self._journal is not None:
                self._journal.emit(
                    "elastic/replan", step,
                    detail={
                        "w_old": int(w_old),
                        "w_new": int(self.config.world_size),
                        "plan_old": old_decision.selected,
                        "plan_new": self._plan_decision.selected,
                        "changed": (old_decision.selected
                                    != self._plan_decision.selected),
                        "old_table": old_decision.table(),
                        "new_table": self._plan_decision.table(),
                    })
            _log.info(
                "auto-planner: re-plan W=%s→%s: %s → %s",
                w_old, self.config.world_size,
                old_decision.selected, self._plan_decision.selected,
            )
        # host_stream: the checkpointed pending_sel ring indexes the OLD
        # (W, L) shard matrix — after elastic_restore carried the score
        # table and stream cursor across, re-prime the lookahead ring for
        # the new topology (make_host_stream_prime on the restored,
        # step-folded rng) and seed each host's pipeline from it.
        self._recommit_state(
            reprime_stream=self.config.data_placement == "host_stream"
        )
        return step

    def restore(self, directory: Optional[str] = None, step: Optional[int] = None) -> int:
        directory = directory or self.config.checkpoint_dir
        assert directory, "no checkpoint directory configured"
        self.state, step = ckpt.restore_checkpoint(
            directory, self.state, step,
            verify=self.config.checkpoint_verify,
            journal=self._journal)
        self._recommit_state()
        return step
