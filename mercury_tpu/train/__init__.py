from mercury_tpu.train.checkpoint import (  # noqa: F401
    latest_step,
    restore_checkpoint,
    save_checkpoint,
)
from mercury_tpu.train.state import MercuryState, create_state, make_optimizer  # noqa: F401
from mercury_tpu.train.step import make_eval_step, make_train_step  # noqa: F401
from mercury_tpu.train.trainer import Trainer, build_dataset  # noqa: F401
