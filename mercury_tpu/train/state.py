"""Training state: params, BN stats, optimizer state, and the Mercury
sampler state (EMA + per-worker presampling streams + RNG).

The reference scatters this state across a ``Trainer`` object's attributes
(``pytorch_collab.py:38-54`` — net/optimizer/loaders/``next_batch_iter``/
EMA meter). Here it is one pytree, so the whole training step is a pure
function ``state → state`` and the entire thing checkpoints/resumes
deterministically (including sampler RNG — SURVEY.md §5's checkpoint gap).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import flax.struct
import jax
import jax.numpy as jnp
import optax

from mercury_tpu.data.pipeline import ShardStream
from mercury_tpu.sampling.groupwise import GroupwiseState, init_groupwise
from mercury_tpu.sampling.importance import EMAState, init_ema
from mercury_tpu.sampling.scoretable import init_score_table


class CachedPool(NamedTuple):
    """A scored candidate pool reused across steps (score-refresh cadence,
    ``config.score_refresh_every > 1``).

    Refreshed every K-th step: the freshly streamed pool's shard slots and
    the normalized importance distribution computed from its scores
    (``update_samples``'s score→normalize, ``pytorch_collab.py:108-112``).
    Intermediate steps redraw from ``probs`` (fresh multinomial draws ≡
    ``:114``) and re-gather/re-augment by slot — the scoring forward, the
    dominant per-step IS cost, runs once per K steps."""

    slots: jax.Array      # [P] int32 — pool positions into the worker shard
    probs: jax.Array      # [P] float32 — normalized sampling distribution
    pool_loss: jax.Array  # [] float32 — pool-loss metric from the refresh


class PendingBatch(NamedTuple):
    """The next step's pre-selected train batch (pipelined scoring).

    Carries the exact augmented/normalized images that were scored — the
    reference also trains on the very tensors ``update_samples`` scored
    (``pytorch_collab.py:116,132``), not a re-load by index."""

    images: jax.Array        # [B, H, W, C] float32 — augmented + normalized
    labels: jax.Array        # [B] int32
    scaled_probs: jax.Array  # [B] float32 — p_i·N for the unbiased reweight


class PendingSelection(NamedTuple):
    """Ring of in-flight sample selections (``data_placement=
    "host_stream"``): the step at t consumes ``slots[0]`` (its rows arrive
    pre-gathered from the host via ``data/stream.py``) and pushes the
    selection it just drew for step t+depth onto the back. The RNG
    lookahead makes the draws key-for-key identical to the device-resident
    path: ``rng`` is the worker RNG advanced ``depth`` steps ahead, so the
    slot draw for step t+d uses exactly the key the replicated step would
    split at t+d. Carried as raw uint32 key data (not a typed key array)
    so the leaf shards like any other array under legacy jax."""

    slots: jax.Array         # [depth, S] int32 — shard-local slot ids per step
    scaled_probs: jax.Array  # [depth, B] float32 — p_i·L at draw time
                             # (scoretable; ones for uniform/pool)
    rng: jax.Array           # [2] uint32 — raw key data of rng_{t+depth}


@flax.struct.dataclass
class MercuryState:
    step: jax.Array                 # [] int32 — global step counter
    params: Any                     # model params (replicated over mesh)
    batch_stats: Any                # BN running stats (replicated)
    opt_state: Any                  # optax state (replicated; under ZeRO-1
                                    # [W, ceil(P/W)]-chunked, sharded P(data))
    ema: EMAState                   # [W]-stacked per-worker EMA of mean pool loss
    stream: ShardStream             # [W]-stacked per-worker presample streams
    rng: jax.Array                  # [W, key] per-worker PRNG keys
    groupwise: Any = None           # [W]-stacked GroupwiseState (sampler="groupwise")
    pending: Any = None             # [W]-stacked PendingBatch (pipelined_scoring)
    cached_pool: Any = None         # [W]-stacked CachedPool (score_refresh_every>1)
    scoretable: Any = None          # [W]-stacked ScoreTableState (sampler="scoretable")
    pending_sel: Any = None         # [W]-stacked PendingSelection (host_stream)
    sel_counts: Any = None          # [W, L] int32 selection-count ledger
                                    # (scoretable + telemetry): draws of
                                    # each shard slot consumed by training
                                    # so far (obs/sampler_health.py)


#: Declared elastic policy per ``MercuryState`` field — the state-plane
#: contract checked by graftlint Layer E (``lint/state.py``). A PURE
#: literal (the linter parses it with ``ast.literal_eval``); every
#: dataclass field above MUST have an entry here (GLE01) and every
#: policy must have a matching carry site in ``train/elastic.py`` /
#: ``train/trainer.py`` (GLE02). The vocabulary:
#:
#: - ``replicate``      — restored exactly as saved; identical on every
#:                        worker, so (W, L) changes don't touch it.
#: - ``reshard-exact``  — re-partitioned across the new mesh with every
#:                        per-element value preserved bit-exactly
#:                        (ZeRO chunks, per-sample scoretable rows).
#: - ``re-aggregate``   — reduced to a global quantity and re-spread;
#:                        the global reduction (sum / weighted mean) is
#:                        invariant across the reshard.
#: - ``re-seed``        — deliberately NOT carried by copy: derived from
#:                        the new template's keys via ``fold_in`` so no
#:                        two workers ever share a key (GLE05 rejects a
#:                        plain copy).
#: - ``cursor-fraction``— positional state carried as an epoch fraction
#:                        and re-scaled to the new shard length.
#: - ``drop-on-shrink`` — transient pipeline state that is deliberately
#:                        re-initialized from the new template (and,
#:                        where needed, re-primed by the Trainer).
ELASTIC_POLICIES = {
    "step": "replicate",
    "params": "replicate",
    "batch_stats": "replicate",
    "opt_state": "reshard-exact",
    "ema": "re-aggregate",
    "stream": "cursor-fraction",
    "rng": "re-seed",
    "groupwise": "drop-on-shrink",
    "pending": "drop-on-shrink",
    "cached_pool": "drop-on-shrink",
    "scoretable": "reshard-exact",
    "pending_sel": "drop-on-shrink",
    "sel_counts": "re-aggregate",
}


def init_worker_sampler_state(
    stream_key: jax.Array, worker_key: jax.Array,
    n_workers: int, shard_len: int,
):
    """Per-worker sampler state, ``[W]``-stacked: bootstrap EMA, shuffled
    shard streams, independent PRNG keys. One definition shared by the
    fused dp step's :func:`create_state` and the dp×sp Mercury step's
    init (``train/sp_step.py``) so seeding/bootstrap semantics cannot
    drift between them. Returns ``(ema, stream, rng)``."""
    from mercury_tpu.data.pipeline import init_shard_streams

    ema0 = init_ema()
    ema = EMAState(
        value=jnp.zeros((n_workers,), jnp.float32) + ema0.value,
        count=jnp.zeros((n_workers,), jnp.int32) + ema0.count,
    )
    stream = init_shard_streams(stream_key, n_workers, shard_len)
    rng = jax.random.split(worker_key, n_workers)
    return ema, stream, rng


def create_state(
    rng: jax.Array,
    model,
    tx: optax.GradientTransformation,
    sample_batch: jax.Array,
    n_workers: int,
    shard_len: int,
    with_groupwise: bool = False,
    pending_batch_size: int = 0,
    pending_sample_shape: Optional[tuple] = None,
    zero_sharding: bool = False,
    init_opt: bool = True,
    cached_pool_size: int = 0,
    with_scoretable: bool = False,
    stream_depth: int = 0,
    stream_emit_size: int = 0,
    stream_batch_size: int = 0,
    with_sel_counts: bool = False,
) -> MercuryState:
    """Initialize model/optimizer/sampler state.

    Initial cross-worker parameter sync (``Trainer.average_model``,
    ``pytorch_collab.py:84-87``) is implicit: params are created once and
    placed replicated — every device starts from identical weights.
    """
    from mercury_tpu.data.pipeline import init_shard_streams

    init_key, stream_key, worker_key = jax.random.split(rng, 3)
    variables = model.init(init_key, sample_batch, train=False)
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    if zero_sharding:
        # ZeRO-1: the optimizer runs on this worker's 1/W chunk of the
        # flattened parameter vector, so its state is chunk-shaped,
        # [W]-stacked here (sharded P(axis) by the step's specs).
        from mercury_tpu.utils.tree import tree_flatten_to_vector, zero_chunk_size

        pvec, _ = tree_flatten_to_vector(params)
        chunk = zero_chunk_size(pvec.size, n_workers)
        chunk_state = tx.init(jnp.zeros((chunk,), pvec.dtype))
        opt_state = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(
                jnp.asarray(x), (n_workers,) + jnp.shape(x)
            ),
            chunk_state,
        )
    elif init_opt:
        opt_state = tx.init(params)
    else:
        # Caller re-derives the optimizer state from re-placed params
        # (e.g. tensor-parallel layout) — don't allocate a replicated
        # moment tree just to discard it.
        opt_state = None
    ema, stream, worker_keys = init_worker_sampler_state(
        stream_key, worker_key, n_workers, shard_len
    )
    groupwise = None
    if with_groupwise:
        g0 = init_groupwise(shard_len)
        groupwise = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (n_workers,) + x.shape), g0
        )
    pending = None
    if pending_batch_size:
        # Placeholder only — step 0 primes it in-graph (the analogue of the
        # reference's epoch-prologue update_samples call, pytorch_collab:125).
        # The stored samples are POST-augmentation, whose shape can differ
        # from the raw dataset's (the IID pipeline crops to 32) — lax.cond
        # requires the placeholder to match exactly.
        shape = (tuple(pending_sample_shape) if pending_sample_shape is not None
                 else tuple(sample_batch.shape[1:]))
        pending = PendingBatch(
            images=jnp.zeros((n_workers, pending_batch_size) + shape, jnp.float32),
            labels=jnp.zeros((n_workers, pending_batch_size), jnp.int32),
            scaled_probs=jnp.ones((n_workers, pending_batch_size), jnp.float32),
        )
    cached_pool = None
    if cached_pool_size:
        # Placeholder only — step 0's refresh branch fires (step % K == 0)
        # and overwrites it before any draw happens; uniform probs keep the
        # placeholder a valid distribution regardless.
        cached_pool = CachedPool(
            slots=jnp.zeros((n_workers, cached_pool_size), jnp.int32),
            probs=jnp.full((n_workers, cached_pool_size),
                           1.0 / cached_pool_size, jnp.float32),
            pool_loss=jnp.zeros((n_workers,), jnp.float32),
        )
    pending_sel = None
    if stream_depth:
        # Placeholder only — the jitted prime program (step.py
        # make_host_stream_prime) overwrites it with depth uniform
        # cold-start draws (and the advanced lookahead RNG) before the
        # first step runs; the Trainer feeds the host pipeline from the
        # prime's emitted indices.
        pending_sel = PendingSelection(
            slots=jnp.zeros((n_workers, stream_depth, stream_emit_size),
                            jnp.int32),
            scaled_probs=jnp.ones((n_workers, stream_depth,
                                   stream_batch_size), jnp.float32),
            rng=jnp.zeros((n_workers, 2), jnp.uint32),
        )
    scoretable = None
    if with_scoretable:
        # Uniform initial scores over every shard slot — step 0 draws
        # uniformly (the table IS the distribution, no priming branch
        # needed) and the first refresh windows sharpen it in place.
        t0 = init_score_table(shard_len)
        scoretable = jax.tree_util.tree_map(
            lambda x: jnp.broadcast_to(x, (n_workers,) + x.shape), t0
        )
    sel_counts = None
    if with_sel_counts:
        # Selection-count ledger (obs/sampler_health.py): zeros until the
        # first trained batch scatter-adds its slots. Rides alongside the
        # scoretable (same [W, L] geometry) but is a MercuryState field of
        # its own so the ScoreTableState constructors in the step and the
        # elastic carry stay untouched.
        sel_counts = jnp.zeros((n_workers, shard_len), jnp.int32)
    return MercuryState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
        ema=ema,
        stream=stream,
        rng=worker_keys,
        groupwise=groupwise,
        pending=pending,
        cached_pool=cached_pool,
        scoretable=scoretable,
        pending_sel=pending_sel,
        sel_counts=sel_counts,
    )


def make_optimizer(
    name: str,
    lr: float,
    total_steps: int,
    weight_decay: float = 0.0,
    grad_accum_steps: int = 1,
    warmup_steps: int = 0,
) -> optax.GradientTransformation:
    """Adam + cosine decay — the reference's recipe: ``optim.Adam`` at
    ``0.001×world_size`` (``pytorch_collab.py:262,28``) under
    ``CosineAnnealingLR`` over the full run (``:62``). The reference steps
    its scheduler per epoch; here the schedule is per-step (smooth cosine to
    the same endpoint). ``sgd`` is provided as the uniform-baseline control.

    ``grad_accum_steps=A > 1`` wraps the optimizer in ``optax.MultiSteps``:
    each train step contributes its (mean) gradient to an accumulator and
    the parameter update applies every A-th step — an effective batch of
    ``A × batch_size`` per worker without the activation memory. The
    cosine schedule then decays over actual updates (``total_steps / A``).

    ``warmup_steps > 0`` runs a linear 0→peak warmup, then the cosine
    decays over the *remaining* steps so the schedule still ends with the
    run (counted in steps; divided by A like the decay horizon). Must be
    smaller than ``total_steps``.
    """
    if grad_accum_steps < 1:
        raise ValueError(f"grad_accum_steps must be >= 1, got {grad_accum_steps}")
    updates = max(-(-total_steps // grad_accum_steps), 1)
    if warmup_steps > 0:
        w_updates = max(-(-warmup_steps // grad_accum_steps), 1)
        # Compare post-division (update-count) values: with accumulation,
        # ceil(warmup/A) can collide with ceil(total/A) even when
        # warmup_steps < total_steps, which would leave optax a zero-length
        # cosine segment.
        if w_updates >= updates:
            raise ValueError(
                f"warmup_steps ({warmup_steps}) must leave decay room after "
                f"accumulation: warmup updates ({w_updates}) >= total "
                f"updates ({updates})"
            )
        # optax's decay_steps INCLUDES the warmup segment, so this is
        # warmup then cosine over the remaining (updates - w) updates.
        schedule = optax.warmup_cosine_decay_schedule(
            init_value=0.0, peak_value=lr, warmup_steps=w_updates,
            decay_steps=updates,
        )
    else:
        schedule = optax.cosine_decay_schedule(lr, decay_steps=updates)
    if name == "adam":
        opt = optax.adam(schedule)
    elif name == "adamw":
        opt = optax.adamw(schedule, weight_decay=weight_decay)
    elif name == "sgd":
        opt = optax.sgd(schedule, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    if weight_decay and name == "adam":
        opt = optax.chain(optax.add_decayed_weights(weight_decay), opt)
    if grad_accum_steps > 1:
        opt = optax.MultiSteps(opt, every_k_schedule=grad_accum_steps)
    return opt
