"""The Mercury importance-sampled step on a PIPELINED model.

Completes the flagship-algorithm × parallelism matrix (dp: ``train/step.py``;
dp×sp: ``train/sp_step.py``; dp×tp: ``train/step.py`` partial-auto; pp:
here): the candidate pool is scored through the GPipe schedule
(:func:`mercury_tpu.parallel.pipeline.make_pp_apply`), the batch is drawn
by the same EMA-smoothed ``loss + α·EMA`` rule (``pytorch_collab.py:
89-117``), and the reweighted backward runs through the schedule's exact
AD reverse — the transformer stack's params live staged across the pipe
axis the whole time.

One data worker (the pipe mesh IS the machine here); sampler state mirrors
``MercuryState``'s per-worker slice. The transformer family has no
BatchNorm, so scoring and training forwards are the same pure function —
the reference's BN-churn quirk has nothing to mutate.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from mercury_tpu.compat import donate_argnums
from mercury_tpu.config import TrainConfig
from mercury_tpu.data.pipeline import (
    ShardStream,
    init_shard_streams,
    next_pool,
)
from mercury_tpu.parallel.pipeline import make_pp_apply
from mercury_tpu.sampling.importance import (
    EMAState,
    init_ema,
    per_sample_loss,
    reweighted_loss,
    select_from_pool,
)


class PPMercuryState(NamedTuple):
    step: jax.Array
    stacked: dict          # block params, layer axis sharded P(pipe)
    rest: dict             # embed/pos/norm/head params, replicated
    opt_state: tuple       # optax state over (stacked, rest)
    ema: EMAState
    stream: ShardStream    # single worker's presample stream (no [W] axis)
    rng: jax.Array


def create_pp_state(
    rng: jax.Array, model, tx: optax.GradientTransformation,
    sample_batch: jax.Array, shard_len: int, mesh: Mesh, axis: str = "pipe",
) -> PPMercuryState:
    """Init params, stage the block stack over the pipe axis, and derive
    the optimizer state from the STAGED params (its moments inherit the
    placement)."""
    from mercury_tpu.parallel.pipeline import (
        shard_stacked_blocks,
        stack_block_params,
    )

    init_key, stream_key, step_key = jax.random.split(rng, 3)
    params = model.init(init_key, sample_batch, train=False)["params"]
    stacked, rest = stack_block_params(params, model.num_layers)
    stacked = shard_stacked_blocks(stacked, mesh, axis)
    streams = init_shard_streams(stream_key, 1, shard_len)
    return PPMercuryState(
        step=jnp.zeros((), jnp.int32),
        stacked=stacked,
        rest=rest,
        opt_state=tx.init((stacked, rest)),
        ema=init_ema(),
        stream=ShardStream(perm=streams.perm[0], cursor=streams.cursor[0]),
        rng=step_key,
    )


def make_pp_mercury_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    batch_size: int,
    presample_batches: int = 10,
    num_microbatches: int = 2,
    axis: str = "pipe",
    is_alpha: float = 0.5,
    ema_alpha: float = 0.9,
    moe_aux_weight: float = TrainConfig.moe_aux_weight,
    telemetry: bool = False,
    io_constraints: bool = True,
) -> Callable[..., Tuple[PPMercuryState, dict]]:
    """Build ``step(state, x_train, y_train) → (state, metrics)``.

    ``x_train`` is the worker's shard data (float, model-ready — sequences
    or images for a ``patch_size`` model), ``y_train`` its labels; the
    pool (``presample_batches × batch_size`` candidates) and the drawn
    train batch both flow through the pipelined forward, so both must be
    divisible by ``num_microbatches``.

    MoE models compose: the Switch router's load-balancing aux loss flows
    out of the staged scan (``make_pp_apply(with_aux=True)``) and enters
    the training objective as ``moe_aux_weight × aux`` — the same term the
    fused data-parallel step applies (``train/step.py``). The default IS
    ``TrainConfig.moe_aux_weight`` (one source of truth); a caller using a
    config with a non-default value must pass ``config.moe_aux_weight``
    explicitly — this factory takes keywords, not a ``TrainConfig``. The
    scoring pass discards the aux (scores are per-sample CE, matching
    ``pytorch_collab.py:102``).

    ``telemetry=True`` adds the fused dp step's sampler-health scalars
    (``sampler/ess``, ``sampler/clip_frac``, ``sampler/ema_drift``,
    ``train/grad_norm`` — see ``obs/diagnostics.py``); gated at trace
    time, so the default traces the original program.

    SHARDING CONTRACT (graftlint Layer 3): ``x_train``/``y_train`` are
    pinned replicated over the pipe mesh (``P()``) with
    ``with_sharding_constraint`` at the step boundary — every stage
    reads the worker's full shard (stage 0 injects microbatches, the
    last stage emits), so a pipe-sharded input would silently all-gather
    per tick. ``io_constraints=False`` drops the pins (and the plan's
    ``sharding_constraints`` budget with them).
    """
    pool_size = presample_batches * batch_size
    if pool_size % num_microbatches or batch_size % num_microbatches:
        raise ValueError(
            f"pool ({pool_size}) and batch ({batch_size}) must divide by "
            f"num_microbatches ({num_microbatches})"
        )
    moe = getattr(model, "moe_experts", None) is not None
    pp_fwd = make_pp_apply(model, mesh, num_microbatches, axis,
                           with_aux=moe)
    from jax.sharding import NamedSharding, PartitionSpec as P

    rep_ns = NamedSharding(mesh, P())

    def step(state: PPMercuryState, x_train, y_train):
        if io_constraints:
            # SHARDING CONTRACT (see docstring): the shard data stays
            # replicated over the pipe axis.
            x_train = jax.lax.with_sharding_constraint(x_train, rep_ns)
            y_train = jax.lax.with_sharding_constraint(y_train, rep_ns)
        k_stream, k_sel, k_next = jax.random.split(state.rng, 3)
        stream, slots = next_pool(state.stream, k_stream, pool_size)
        pool_x = x_train[slots]
        pool_y = y_train[slots]

        # Score the pool through the pipeline (one schedule pass). The
        # mercury_scoring scope anchors the jaxpr auditor's per-region
        # checks (lint/audit.py).
        with jax.named_scope("mercury_scoring"):
            pool_out = pp_fwd(state.stacked, state.rest, pool_x)
            pool_logits = pool_out[0] if moe else pool_out
            pool_losses = per_sample_loss(pool_logits, pool_y)
        sel = select_from_pool(
            k_sel, pool_losses, state.ema, batch_size,
            is_alpha=is_alpha, ema_alpha=ema_alpha,
        )

        def loss_fn(stacked, rest):
            out = pp_fwd(stacked, rest, pool_x[sel.selected])
            logits, aux = out if moe else (out, jnp.zeros((), jnp.float32))
            total = reweighted_loss(
                per_sample_loss(logits, pool_y[sel.selected]),
                sel.scaled_probs,
            )
            if moe:
                total = total + moe_aux_weight * aux
            return total, (logits, aux)

        (loss, (logits, moe_aux)), grads = jax.value_and_grad(
            loss_fn, argnums=(0, 1), has_aux=True
        )(state.stacked, state.rest)
        with jax.named_scope("mercury_optimizer"):
            updates, opt_state = tx.update(
                grads, state.opt_state, (state.stacked, state.rest)
            )
            stacked, rest = optax.apply_updates(
                (state.stacked, state.rest), updates
            )
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == pool_y[sel.selected]).astype(
                jnp.float32
            )
        )
        new_state = PPMercuryState(
            step=state.step + 1, stacked=stacked, rest=rest,
            opt_state=opt_state, ema=sel.ema, stream=stream, rng=k_next,
        )
        metrics = {
            "train/loss": loss,
            "train/acc": acc,
            "train/pool_loss": sel.avg_pool_loss,
            "train/moe_aux": moe_aux,
        }
        if telemetry:
            from mercury_tpu.obs.diagnostics import (
                clip_fraction,
                ema_drift,
                ess_fraction,
                global_grad_norm,
            )

            metrics["sampler/ess"] = ess_fraction(sel.scaled_probs)
            metrics["sampler/clip_frac"] = clip_fraction(
                pool_losses, sel.ema.value, is_alpha
            )
            metrics["sampler/ema_drift"] = ema_drift(
                sel.avg_pool_loss, state.ema.value
            )
            metrics["train/grad_norm"] = global_grad_norm(grads)
        return new_state, metrics

    return jax.jit(step, donate_argnums=donate_argnums(0))
