"""Elastic resume: restore a checkpoint into a DIFFERENT world size.

The reference cannot survive topology change at all — any worker loss hangs
the gloo group forever (``pytorch_collab.py:291-292`` joins forked workers
that block in collectives; SURVEY.md §5 "failure detection: none"). Plain
``restore_checkpoint`` here already beats that for same-shape restarts;
this module handles the genuinely elastic case: train W-way, come back
W′-way (preemption shrank the pod, or it grew back).

What transfers and what re-derives, by world-size dependence:

- **model state** (params, BN stats, step) — world-size independent:
  restored exactly; the learning trajectory continues bit-for-bit in the
  weights.
- **optimizer state** — exact for the replicated layout; under ZeRO-1 the
  ``[W, ceil(P/W)]`` moment chunks are a flat view of the parameter-sized
  moment vector, so W→W′ resharding is concat → trim to P → re-pad →
  re-chunk: the moments also transfer exactly.
- **per-worker sampler state** (streams, RNG, groupwise scores, cached
  pool, pending batch) — indexed by the W-way Dirichlet partition, which
  a W′-way run re-draws as W′ different shards: the old values are
  meaningless under the new partition, so they re-derive deterministically
  from (config seed, restored step): fresh streams over the new shards and
  per-worker keys folded with the restored step (a resumed run never
  repeats the step-0 draw sequence).
- **EMA of the pool loss** — a cross-worker statistic, not a per-shard
  one (under ``sync_importance_stats`` every worker holds the same
  value): the new workers warm-start from the old workers' mean instead
  of re-bootstrapping, so the importance scores stay smoothed through the
  topology change.
- **score table + stream cursor** (``config.stream_checkpoint_cursor``,
  default on) — per-SAMPLE state wearing per-worker clothes: a table
  entry scores dataset row ``shard_indices[w, l]``, and the partition is
  deterministic in ``(labels, W, seed)``, so both the old and the new
  ``[W, L]`` index matrices can be recomputed host-side and the scores
  REPARTITIONED by new worker ownership (rows that changed hands keep
  their learned scores; rows the old run never held warm-start at the
  EMA mean). The shard-stream and refresh cursors carry as epoch
  fractions — a run preempted 60% through its shard sweep resumes ~60%
  through the new one instead of restarting the epoch.
- **host_stream's pending_sel ring** — genuinely in-flight (the
  selections reference old-world slots whose pixels were never
  streamed): re-primed by the caller (``Trainer.restore_elastic`` runs
  ``make_host_stream_prime`` on the restored, step-folded RNG) for the
  new topology.
"""

from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from mercury_tpu.sampling.importance import EMAState
from mercury_tpu.train import checkpoint as ckpt
from mercury_tpu.train.state import MercuryState


def probe_checkpoint(
    directory: str, step: Optional[int] = None, strict: bool = False,
) -> Tuple[Optional[dict], Optional[int]]:
    """Read the (newest, or ``step``'s) checkpoint's raw state dict once.
    Returns ``(raw, step)``; with ``strict=False`` an absent or unreadable
    checkpoint yields ``(None, None)`` (the auto-resume probe must not
    crash construction), with ``strict=True`` read/deserialization errors
    propagate so a corrupt file surfaces as its real exception, not a
    misleading not-found. The raw tree can be handed to
    :func:`elastic_restore` so a resume that probed the world size first
    does not deserialize the file twice."""
    import flax.serialization

    if step is None:
        step = ckpt.latest_step(directory)
        if step is None:
            if strict:
                raise FileNotFoundError(f"no checkpoints under {directory}")
            return None, None
    path = ckpt._ckpt_path(directory, step)
    try:
        if os.path.isdir(path):
            ocp = ckpt._orbax()
            assert ocp is not None, "directory checkpoint needs orbax"
            raw = ocp.PyTreeCheckpointer().restore(os.path.abspath(path))
            raw = _lists_to_dicts(raw)
        else:
            with open(path + ".msgpack", "rb") as f:
                raw = flax.serialization.msgpack_restore(f.read())
    except Exception:
        if strict:
            raise
        return None, None
    return raw, step


def _read_raw_state(directory: str, template: MercuryState,
                    step: Optional[int] = None,
                    raw: Optional[dict] = None) -> Tuple[Any, int]:
    """Read a checkpoint WITHOUT shape-checking against the template:
    returns a template-structured tree whose leaves keep their on-disk
    (old-world) shapes, plus the step. PRNG keys stay as raw uint32 key
    data (the caller re-derives RNG anyway). A pre-probed ``raw`` tree
    (with its ``step``) skips the file read."""
    import flax.serialization

    if raw is None:
        raw, step = probe_checkpoint(directory, step, strict=True)
    # Upgrade-shim chain (checkpoint.STATE_SCHEMA_LINEAGE): checkpoints
    # written before a state field existed get that field dropped from
    # the template (the caller keeps its fresh init), and a checkpoint
    # carrying fields this build does not know fails LOUDLY instead of
    # silently dropping state.
    template = ckpt.apply_upgrade_shims(raw, template)
    # from_state_dict maps the raw dict back onto the template STRUCTURE
    # without reshaping values — exactly what elastic needs: old-shape
    # leaves inside a navigable MercuryState.
    state_shaped = flax.serialization.from_state_dict(
        ckpt._unwrap_keys(template), raw
    )
    return state_shaped, step


def _lists_to_dicts(tree: Any) -> Any:
    """Orbax restores tuple nodes as real lists; flax's ``from_state_dict``
    expects the msgpack convention (dicts keyed by the stringified index).
    Normalize so both save formats feed the same restore path."""
    if isinstance(tree, (list, tuple)):
        return {str(i): _lists_to_dicts(v) for i, v in enumerate(tree)}
    if isinstance(tree, dict):
        return {k: _lists_to_dicts(v) for k, v in tree.items()}
    return tree


def world_size_of_raw(raw: Optional[dict]) -> Optional[int]:
    """World size a raw checkpoint tree was saved at (the leading dim of
    the per-worker EMA), or None when unreadable. Lets ``auto_resume``
    decide between the exact restore and the elastic one BEFORE
    deserializing into a mismatched template — the msgpack path would
    otherwise silently accept wrong-shaped leaves."""
    try:
        return int(np.shape(raw["ema"]["value"])[0])
    except Exception:
        return None


def _reshard_zero_opt(old_opt: Any, new_opt: Any, w_old: int, w_new: int,
                      n_params: int) -> Any:
    """ZeRO-1 moment chunks ``[W, C]`` → ``[W′, C′]``: the chunks are a
    padded flat view of the parameter-sized moment vector, so resharding
    is exact — concat, trim the old padding, re-pad, re-chunk. Per-chunk
    scalar leaves (Adam's step count, ``[W]``) broadcast their (identical)
    first entry."""

    def leaf(o, n):
        o = np.asarray(o)
        want = np.shape(n)
        if o.shape == want:
            return o
        if o.ndim >= 2 and o.shape[0] == w_old and want[0] == w_new:
            full = o.reshape((w_old * o.shape[1],) + o.shape[2:])[:n_params]
            c_new = want[1]
            pad = w_new * c_new - n_params
            full = np.concatenate(
                [full, np.zeros((pad,) + full.shape[1:], full.dtype)]
            )
            return full.reshape((w_new, c_new) + o.shape[2:])
        if o.ndim == 1 and o.shape[0] == w_old and want == (w_new,):
            return np.full(w_new, o[0], o.dtype)
        raise ValueError(
            f"cannot reshard optimizer leaf {o.shape} -> {want} "
            f"(W {w_old} -> {w_new})"
        )

    return jax.tree_util.tree_map(leaf, old_opt, new_opt)


def _shard_index_matrix(trainer, n_workers: int) -> np.ndarray:
    """Recompute the ``[W, L]`` cyclically-tiled shard-index matrix a
    ``n_workers``-way run of this config builds (``partition_data`` is
    deterministic in ``(labels, W, seed)``; tiling mirrors
    ``make_sharded_dataset``) — elastic can then map per-worker state to
    per-SAMPLE state for any world size without reading the live (possibly
    non-addressable) device copy."""
    from mercury_tpu.data.partition import partition_data

    labels = np.asarray(jax.device_get(trainer.dataset.y_train))
    cfg = trainer.config
    shards = partition_data(
        labels, n_workers,
        mode="hetero" if cfg.noniid else "homo",
        alpha=cfg.dirichlet_alpha, seed=cfg.seed,
        min_size=cfg.min_shard_size,
    )
    max_len = max(len(s) for s in shards)
    rows = []
    for s in shards:
        reps = int(np.ceil(max_len / len(s)))
        rows.append(np.tile(s, reps)[:max_len])
    return np.stack(rows).astype(np.int64)


def _carry_streamed_state(trainer, old: Any, template: MercuryState,
                          w_old: int, w_new: int, ema_val: float) -> dict:
    """Mid-epoch sampler-state carry across a ``(W, L)`` change (gated by
    ``config.stream_checkpoint_cursor``): repartition the score table's
    per-sample scores by new worker ownership and carry the shard-stream /
    refresh cursors as epoch fractions. Returns replace() kwargs."""
    import jax.numpy as jnp

    extra: dict = {}
    old_stream = getattr(old, "stream", None)
    if old_stream is not None and np.size(
            np.asarray(old_stream.cursor)) == w_old:
        l_old = int(np.shape(old_stream.perm)[1])
        l_new = int(np.shape(template.stream.perm)[1])
        frac = float(np.mean(
            np.asarray(old_stream.cursor, np.float64)) / max(l_old, 1))
        cursor = np.full((w_new,),
                         min(int(frac * l_new), l_new), np.int32)
        extra["stream"] = type(template.stream)(
            perm=jnp.asarray(np.asarray(template.stream.perm)),
            cursor=jnp.asarray(cursor),
        )
    old_tab = getattr(old, "scoretable", None)
    new_tab = template.scoretable
    if old_tab is not None and new_tab is not None:
        old_scores = np.asarray(old_tab.scores, np.float32)
        l_old = int(old_scores.shape[1])
        l_new = int(np.shape(new_tab.scores)[1])
        old_sidx = _shard_index_matrix(trainer, w_old)
        new_sidx = _shard_index_matrix(trainer, w_new)
        if old_sidx.shape != (w_old, l_old) \
                or new_sidx.shape != (w_new, l_new):
            # The recomputed partition disagrees with the live shapes
            # (config drift?) — fall back to the fresh template table.
            return extra
        n = int(np.asarray(jax.device_get(trainer.dataset.y_train)).size)
        # Samples the old run never owned (partition boundaries moved)
        # warm-start at the EMA mean — exactly where table_decay pulls
        # never-refreshed entries anyway. Cyclic-tiling duplicates write
        # last-wins; their scores differ only by refresh age.
        global_scores = np.full((n,), ema_val, np.float32)
        global_scores[old_sidx.reshape(-1)] = old_scores.reshape(-1)
        frac = float(np.mean(
            np.asarray(old_tab.cursor, np.float64)) / max(l_old, 1))
        cursor = np.full((w_new,),
                         int(frac * l_new) % max(l_new, 1), np.int32)
        extra["scoretable"] = type(new_tab)(
            scores=jnp.asarray(global_scores[new_sidx], jnp.float32),
            cursor=jnp.asarray(cursor),
        )
        # Selection-count ledger (obs/sampler_health.py): also per-SAMPLE
        # state wearing per-worker clothes, but ADDITIVE — cyclic-tiling
        # duplicates SUM into the global count (unlike the scores'
        # last-wins), and each sample's total is scattered to its FIRST
        # slot in the new matrix only (later duplicates start at 0), so
        # the global per-sample counts carry EXACTLY across any (W, L)
        # change (test-pinned, tests/test_sampler_health.py).
        old_led = getattr(old, "sel_counts", None)
        if old_led is not None and template.sel_counts is not None:
            old_counts = np.asarray(old_led, np.int64)
            if old_counts.shape == (w_old, l_old):
                global_counts = np.zeros((n,), np.int64)
                np.add.at(global_counts, old_sidx.reshape(-1),
                          old_counts.reshape(-1))
                flat = new_sidx.reshape(-1)
                uniq, first_idx = np.unique(flat, return_index=True)
                new_counts = np.zeros((flat.size,), np.int64)
                new_counts[first_idx] = global_counts[uniq]
                extra["sel_counts"] = jnp.asarray(
                    new_counts.reshape(new_sidx.shape), jnp.int32
                )
    return extra


def _check_same(old: Any, new: Any, what: str) -> Any:
    def leaf(o, n):
        if np.shape(o) != np.shape(n):
            raise ValueError(
                f"{what} shape mismatch {np.shape(o)} vs {np.shape(n)}: "
                "elastic resume requires the same model/optimizer config"
            )
        return np.asarray(o)

    return jax.tree_util.tree_map(leaf, old, new)


def elastic_restore(directory: str, trainer,
                    step: Optional[int] = None,
                    raw: Optional[dict] = None) -> int:
    """Restore ``directory``'s checkpoint (saved at any world size) into
    ``trainer`` (built at the new world size). Returns the restored step.

    The trainer's freshly-initialized state supplies everything the new
    topology defines (streams over the new partition, per-worker RNG,
    groupwise/cached-pool/pending placeholders); the checkpoint supplies
    the learning trajectory (params, BN stats, optimizer moments, step,
    EMA warm start). See the module docstring for the rationale per field.
    """
    # Work from a fully host-resident view of the template: in a
    # multi-controller run the live state's sampler leaves are global
    # arrays spanning non-addressable devices — np.asarray on those (or
    # re-globalizing them) would raise. _host_gather is collective
    # (all-gather of cross-process shards), and every process calls
    # elastic_restore, so this is safe by the same argument as
    # save_checkpoint's gather.
    live = trainer.state
    template = ckpt._rewrap_keys(
        live, ckpt._host_gather(ckpt._unwrap_keys(live))
    )
    old, restored_step = _read_raw_state(directory, template, step, raw=raw)
    w_old = int(np.shape(old.ema.value)[0])
    w_new = int(np.shape(template.ema.value)[0])

    # Journal the reshard as a begin/end pair (host-side only): the
    # (W, L) change is the single most important fact for explaining a
    # post-resume trajectory shift.
    journal = getattr(trainer, "_journal", None)
    begin_eid = None
    if journal is not None:
        tab_old = getattr(old, "scoretable", None)
        tab_new = getattr(template, "scoretable", None)
        # Shape metadata only — never materializes device values.
        l_old = (int(np.shape(tab_old.scores)[1])
                 if tab_old is not None else None)
        l_new = (int(np.shape(tab_new.scores)[1])
                 if tab_new is not None else None)
        begin_eid = journal.emit(
            "elastic/reshard_begin", restored_step,
            detail={"w_old": w_old, "w_new": w_new,
                    "l_old": l_old, "l_new": l_new,
                    "directory": directory,
                    # The schema this build was linted against — the run
                    # report surfaces it per reshard so a post-resume
                    # trajectory shift can be tied to a schema change.
                    "state_schema_sha": ckpt.state_schema_sha()})

    params = _check_same(old.params, ckpt._unwrap_keys(template).params,
                         "params")
    batch_stats = _check_same(old.batch_stats, template.batch_stats,
                              "batch_stats")
    if trainer.config.zero_sharding and w_old != w_new:
        from mercury_tpu.utils.tree import tree_flatten_to_vector

        pvec, _ = tree_flatten_to_vector(template.params)
        opt_state = _reshard_zero_opt(old.opt_state, template.opt_state,
                                      w_old, w_new, int(pvec.size))
    else:
        opt_state = _check_same(old.opt_state, template.opt_state,
                                "opt_state")

    # EMA warm start: mean over the old workers (identical values under
    # sync_importance_stats), count carried so the bootstrap doesn't rerun.
    ema_val = float(np.mean(np.asarray(old.ema.value)))
    ema_cnt = int(np.max(np.asarray(old.ema.count)))
    ema = EMAState(
        value=jnp.full((w_new,), ema_val, jnp.float32),
        count=jnp.full((w_new,), ema_cnt, jnp.int32),
    )
    # Per-worker RNG: the new topology's keys, folded with the restored
    # step — deterministic, and never re-plays the step-0 sequence.
    rng = jax.vmap(lambda k: jax.random.fold_in(k, restored_step))(
        template.rng
    )

    # Mid-epoch carry (config.stream_checkpoint_cursor): score table
    # repartitioned by new worker ownership, shard-stream + refresh
    # cursors carried as epoch fractions. Off → those fields keep the
    # template's fresh initialization.
    extra = {}
    if getattr(trainer.config, "stream_checkpoint_cursor", True):
        extra = _carry_streamed_state(trainer, old, template, w_old, w_new,
                                      ema_val)

    trainer.state = template.replace(
        step=jnp.asarray(int(old.step), jnp.int32),
        params=jax.tree_util.tree_map(jnp.asarray, params),
        batch_stats=jax.tree_util.tree_map(jnp.asarray, batch_stats),
        opt_state=jax.tree_util.tree_map(jnp.asarray, opt_state),
        ema=ema,
        rng=rng,
        # groupwise/pending/cached_pool/pending_sel: the template's fresh,
        # deterministic initialization over the NEW partition (host_stream
        # re-primes pending_sel in Trainer.restore_elastic).
        **extra,
    )
    if journal is not None:
        journal.emit("elastic/reshard_end", restored_step,
                     parent=begin_eid,
                     detail={"w_old": w_old, "w_new": w_new,
                             # Fields carried from the checkpoint (the
                             # rest kept the new template's fresh init).
                             "carried": sorted(
                                 ["step", "params", "batch_stats",
                                  "opt_state", "ema", "rng"]
                                 + list(extra))})
    # Re-placement (global arrays multi-controller, committed TP layout)
    # is the caller's job — Trainer.restore_elastic runs the same
    # _recommit_state step the plain restore path uses.
    return restored_step
