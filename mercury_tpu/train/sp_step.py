"""Long-context training step on a 2-D (data × sequence) mesh.

The data-parallel Mercury step (``train/step.py``) shards *workers*; this
step additionally shards the *sequence axis of each example* over a second
mesh axis, with every self-attention running as blockwise ring attention
(:mod:`mercury_tpu.parallel.sequence`). Context length then scales with the
``seq`` axis size — no device ever holds a full sequence or an ``[L, L]``
score matrix. The reference has no long-context machinery (SURVEY.md §5);
this is the beyond-parity extension that makes long sequences first-class.

Gradient-reduction subtlety (pinned by ``tests/test_sequence_parallel.py``):
under ``shard_map`` with replicated (``P()``) params, JAX's autodiff
automatically ``psum``s the parameter cotangents over **all** mesh axes.
Summing per-sequence-shard partials over ``seq`` is exactly the chain rule,
but over ``data`` it turns the desired mean-over-workers into a sum — so the
local loss is ``pmean``-ed over the data axis *inside* the differentiated
function, which pre-divides the cotangent and makes the automatic psum land
on the true global gradient. No hand-written gradient collective is needed.
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from jax import shard_map

from mercury_tpu.sampling.importance import per_sample_loss
from mercury_tpu.utils.tree import sum_sowed_losses


def make_dp_sp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    moe_aux_weight: float = 0.01,
) -> Callable[..., Tuple[dict, tuple, jax.Array]]:
    """Build a jitted train step over a 2-D ``(data, seq)`` mesh.

    ``model`` must be sequence-parallel-aware (``sp_axis=seq_axis`` — e.g.
    :class:`~mercury_tpu.models.TransformerClassifier`), so its attention
    rides the ring and its pooling completes over ``seq_axis`` internally.

    Returns ``step(params, opt_state, x, y) → (params, opt_state, loss)``
    with ``x: [B, T, F]`` sharded ``P(data, seq)``, ``y: [B]`` sharded
    ``P(data)``, params/opt state replicated.

    With ``model.sp_impl == "zigzag"`` the step permutes the token axis
    into :func:`~mercury_tpu.parallel.sequence.zigzag_order` inside the
    jitted program before sharding — the caller keeps feeding plain
    sequence-ordered batches, and the balanced causal ring does half the
    matmul FLOPs per hop. (Classification loss reads the pooled head, so
    no inverse permutation is needed on the way out.)
    """
    zigzag = getattr(model, "sp_impl", "ring") == "zigzag"

    def local_step(params, opt_state, x, y):
        def loss_fn(p):
            logits, state = model.apply(
                {"params": p}, x, train=True, mutable=["losses"]
            )
            # Any sowed MoE load-balancing losses join the objective. Each
            # seq shard sows a router aux from its local tokens — pmean it
            # over the seq axis so the loss stays replicated (and the
            # auto-psum of cotangents doesn't rescale the aux term).
            aux = lax.pmean(sum_sowed_losses(state), seq_axis)
            loss = jnp.mean(per_sample_loss(logits, y)) + moe_aux_weight * aux
            # pmean over data INSIDE the grad: see module docstring.
            return lax.pmean(loss, data_axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), P(data_axis, seq_axis), P(data_axis)),
        out_specs=(P(), P(), P()),
    )
    if not zigzag:
        return jax.jit(sharded, donate_argnums=(0, 1))

    from mercury_tpu.parallel.sequence import zigzag_order

    w_seq = mesh.shape[seq_axis]

    def step(params, opt_state, x, y):
        perm = jnp.asarray(zigzag_order(x.shape[1], w_seq))
        return sharded(params, opt_state, x[:, perm], y)

    return jax.jit(step, donate_argnums=(0, 1))
