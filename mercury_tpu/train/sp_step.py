"""Long-context training step on a 2-D (data × sequence) mesh.

The data-parallel Mercury step (``train/step.py``) shards *workers*; this
step additionally shards the *sequence axis of each example* over a second
mesh axis, with every self-attention running as blockwise ring attention
(:mod:`mercury_tpu.parallel.sequence`). Context length then scales with the
``seq`` axis size — no device ever holds a full sequence or an ``[L, L]``
score matrix. The reference has no long-context machinery (SURVEY.md §5);
this is the beyond-parity extension that makes long sequences first-class.

Gradient-reduction subtlety (pinned by ``tests/test_sequence_parallel.py``):
under ``shard_map`` with replicated (``P()``) params, JAX's autodiff
automatically ``psum``s the parameter cotangents over **all** mesh axes.
Summing per-sequence-shard partials over ``seq`` is exactly the chain rule,
but over ``data`` it turns the desired mean-over-workers into a sum — so the
local loss is ``pmean``-ed over the data axis *inside* the differentiated
function, which pre-divides the cotangent and makes the automatic psum land
on the true global gradient. No hand-written gradient collective is needed.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from mercury_tpu.compat import axis_size, donate_argnums, shard_map

from mercury_tpu.config import TrainConfig
from mercury_tpu.data.pipeline import (
    ShardStream,
    init_shard_streams,
    next_pool,
)
from mercury_tpu.sampling.importance import (
    EMAState,
    init_ema,
    per_sample_loss,
    reweighted_loss,
    select_from_pool,
)
from mercury_tpu.utils.tree import sum_sowed_losses


def make_dp_sp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    data_axis: str = "data",
    seq_axis: str = "seq",
    moe_aux_weight: float = TrainConfig.moe_aux_weight,
) -> Callable[..., Tuple[dict, tuple, jax.Array]]:
    """Build a jitted train step over a 2-D ``(data, seq)`` mesh.

    ``model`` must be sequence-parallel-aware (``sp_axis=seq_axis`` — e.g.
    :class:`~mercury_tpu.models.TransformerClassifier`), so its attention
    rides the ring and its pooling completes over ``seq_axis`` internally.

    Returns ``step(params, opt_state, x, y) → (params, opt_state, loss)``
    with ``x: [B, T, F]`` sharded ``P(data, seq)``, ``y: [B]`` sharded
    ``P(data)``, params/opt state replicated.

    With ``model.sp_impl == "zigzag"`` the step permutes the token axis
    into :func:`~mercury_tpu.parallel.sequence.zigzag_order` inside the
    jitted program before sharding — the caller keeps feeding plain
    sequence-ordered batches, and the balanced causal ring does half the
    matmul FLOPs per hop. (Classification loss reads the pooled head, so
    no inverse permutation is needed on the way out.)
    """
    zigzag = getattr(model, "sp_impl", "ring") == "zigzag"

    def local_step(params, opt_state, x, y):
        def loss_fn(p):
            logits, state = model.apply(
                {"params": p}, x, train=True, mutable=["losses"]
            )
            # Any sowed MoE load-balancing losses join the objective. Each
            # seq shard sows a router aux from its local tokens — pmean it
            # over the seq axis so the loss stays replicated (and the
            # auto-psum of cotangents doesn't rescale the aux term).
            aux = lax.pmean(sum_sowed_losses(state), seq_axis)
            loss = jnp.mean(per_sample_loss(logits, y)) + moe_aux_weight * aux
            # pmean over data INSIDE the grad: see module docstring.
            return lax.pmean(loss, data_axis)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(), P(), P(data_axis, seq_axis), P(data_axis)),
        out_specs=(P(), P(), P()),
    )
    if not zigzag:
        return jax.jit(sharded, donate_argnums=donate_argnums(0, 1))

    from mercury_tpu.parallel.sequence import zigzag_order

    w_seq = mesh.shape[seq_axis]

    def step(params, opt_state, x, y):
        perm = jnp.asarray(zigzag_order(x.shape[1], w_seq))
        return sharded(params, opt_state, x[:, perm], y)

    return jax.jit(step, donate_argnums=donate_argnums(0, 1))


class SpMercuryState(NamedTuple):
    """State for the dp×sp Mercury step: model/opt replicated, per-data-
    worker sampler state (the seq axis sees each data row replicated, so
    every seq rank of a worker draws identically)."""

    params: dict
    opt_state: tuple
    ema: EMAState          # [Wd]-stacked
    stream: ShardStream    # [Wd]-stacked
    rng: jax.Array         # [Wd] keys


def init_sp_mercury_state(
    rng: jax.Array, model, tx, sample_batch: jax.Array,
    n_data_workers: int, shard_len: int,
) -> SpMercuryState:
    from mercury_tpu.train.state import init_worker_sampler_state

    init_key, stream_key, worker_key = jax.random.split(rng, 3)
    # Init OUTSIDE the mesh: an sp_axis model would call lax.axis_size on
    # an unbound axis — the axis-free clone has identical param shapes.
    init_model = (model.clone(sp_axis=None)
                  if getattr(model, "sp_axis", None) is not None else model)
    params = init_model.init(init_key, sample_batch, train=False)["params"]
    ema, stream, rng_keys = init_worker_sampler_state(
        stream_key, worker_key, n_data_workers, shard_len
    )
    return SpMercuryState(
        params=params,
        opt_state=tx.init(params),
        ema=ema,
        stream=stream,
        rng=rng_keys,
    )


def make_dp_sp_mercury_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    batch_size: int,
    presample_batches: int = 10,
    is_alpha: float = 0.5,
    ema_alpha: float = 0.9,
    moe_aux_weight: float = TrainConfig.moe_aux_weight,
    data_axis: str = "data",
    seq_axis: str = "seq",
    telemetry: bool = False,
    io_constraints: bool = True,
) -> Callable[..., Tuple["SpMercuryState", dict]]:
    """The FULL Mercury IS algorithm on a 2-D ``data × seq`` mesh —
    completing the composition matrix's IS×SP cell (IS×TP and IS×PP
    exist in ``train/step.py`` / ``train/pp_step.py``).

    Per step, per data worker: stream a candidate pool from its shard,
    score it with one sequence-parallel inference forward (ring /
    Ulysses / zigzag attention per ``model.sp_impl``), EMA-smooth with
    the cross-worker psum (north-star statistic), draw the train batch,
    and run the reweighted backward through the same sequence-parallel
    program. Sampler state rides the data axis only: every seq rank of a
    worker holds identical (EMA, stream, RNG) rows and therefore draws
    identical batches — the selection is computed redundantly instead of
    communicated, which costs nothing (it is a few hundred scalars) and
    keeps the step free of host-side coordination, the same trick the
    fused dp step uses for its per-worker divergence.

    Memory scaling note: the SP win here is in ACTIVATIONS — attention
    runs blockwise over seq windows, so no rank materializes an [L, L]
    score matrix or full-sequence activations. The raw INPUT arrays are
    replicated (each device gathers its pool rows then slices its seq
    window), matching the dp step's ``data_placement="replicated"``
    contract — input-side scaling would come from sharding x_train over
    seq, a data-placement change orthogonal to this step.

    With ``model.moe_experts`` set, the router's sowed load-balancing
    aux (collected via ``mutable=["losses"]``) joins the training
    objective scaled by ``moe_aux_weight``; the scoring forward discards
    it (selection is by per-sample loss, as in the dp step).

    Returns ``step(state, x_train, y_train) → (state, metrics)`` with
    ``x_train: [N, T, F]`` / ``y_train: [N]`` replicated (each device
    slices its own seq window; zigzag models get the token permutation
    applied inside the jitted program, like
    :func:`make_dp_sp_train_step`). ``T`` must divide by the seq axis
    size.

    ``telemetry=True`` adds the fused dp step's sampler-health scalars
    (``sampler/ess``, ``sampler/clip_frac``, ``sampler/ema_drift``,
    ``train/grad_norm`` — see ``obs/diagnostics.py``) to the metrics
    dict; gated at trace time, so the default traces the original
    program.

    SHARDING CONTRACT (graftlint Layer 3): ``x_train``/``y_train`` are
    pinned replicated (``P()``) with ``with_sharding_constraint`` at the
    step boundary — the replicated-input contract above made explicit,
    so a sharded caller array reshards once, visibly, instead of GSPMD
    re-laying-out the interior. ``io_constraints=False`` drops the pins
    (and the plan's ``sharding_constraints`` budget with them).
    """
    pool_size = presample_batches * batch_size
    w_seq = mesh.shape[seq_axis]
    zigzag = getattr(model, "sp_impl", "ring") == "zigzag"
    moe = getattr(model, "moe_experts", None) is not None

    def local_step(state: SpMercuryState, x_train, y_train):
        si = lax.axis_index(seq_axis)
        t = x_train.shape[1]
        if t % w_seq != 0:
            # Silent truncation here would quietly train on different
            # math than the unsharded run.
            raise ValueError(
                f"sequence length {t} must divide by the {seq_axis!r} "
                f"axis size {w_seq}"
            )
        t_loc = t // w_seq
        rng = state.rng[0]
        k_stream, k_sel, k_next = jax.random.split(rng, 3)
        stream = ShardStream(perm=state.stream.perm[0],
                             cursor=state.stream.cursor[0])
        ema = EMAState(value=state.ema.value[0], count=state.ema.count[0])

        stream, slots = next_pool(stream, k_stream, pool_size)
        # This device's sequence window of each pooled sample.
        pool_x = lax.dynamic_slice_in_dim(
            x_train[slots], si * t_loc, t_loc, axis=1
        )
        pool_y = y_train[slots]

        def fwd(p, xb):
            logits, mut = model.apply(
                {"params": p}, xb, train=True, mutable=["losses"]
            )
            # Router aux (MoE): per-seq-shard token statistic, pmeaned
            # over seq so the loss stays replicated (0.0 for dense).
            aux = lax.pmean(sum_sowed_losses(mut), seq_axis)
            return logits, aux

        # mercury_scoring / mercury_grad_sync scopes anchor the jaxpr
        # auditor's per-region collective budgets (lint/audit.py).
        with jax.named_scope("mercury_scoring"):
            pool_logits, _ = fwd(state.params, pool_x)  # scoring: aux unused
            pool_losses = per_sample_loss(pool_logits, pool_y)
        sel = select_from_pool(
            k_sel, pool_losses, ema, batch_size,
            is_alpha=is_alpha, ema_alpha=ema_alpha, axis_name=data_axis,
        )
        batch_x = pool_x[sel.selected]
        batch_y = pool_y[sel.selected]

        def loss_fn(p):
            logits, aux = fwd(p, batch_x)
            losses = per_sample_loss(logits, batch_y)
            total = reweighted_loss(losses, sel.scaled_probs)
            if moe:
                total = total + moe_aux_weight * aux
            return total

        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        # Explicit gradient collectives (this shard_map runs with vma
        # checking off — the PRNG-driven sampler state defeats the
        # replication inference, so nothing is automatic here). With vma
        # off, the in-model sequence pmean transposes as a plain psum,
        # inflating EVERY rank-local cotangent by W_seq (pre-pmean params
        # via the doubled pooled cotangent, post-pmean head params via
        # their redundant full partials) — so one uniform normalization
        # lands everything: psum over both axes divided by W_data·W_seq
        # (the data division is the grad MEAN over workers, ≡ the fused
        # dp step's allreduce_mean_tree). Pinned against the unsharded
        # step by TestDpSpMercuryStep.
        with jax.named_scope("mercury_grad_sync"):
            grads = jax.tree.map(
                lambda g: lax.psum(g, (data_axis, seq_axis))
                / (axis_size(data_axis) * axis_size(seq_axis)),
                grads,
            )
        loss = lax.pmean(loss, data_axis)
        with jax.named_scope("mercury_optimizer"):
            updates, opt_state = tx.update(
                grads, state.opt_state, state.params)
            params = optax.apply_updates(state.params, updates)
        new_state = SpMercuryState(
            params=params,
            opt_state=opt_state,
            ema=EMAState(value=sel.ema.value[None],
                         count=sel.ema.count[None]),
            stream=ShardStream(perm=stream.perm[None],
                               cursor=stream.cursor[None]),
            rng=k_next[None],
        )
        metrics = {
            "train/loss": loss,
            # Already the psum-reduced global mean (select_from_pool ran
            # with axis_name=data_axis) — no extra collective needed.
            "train/pool_loss": sel.avg_pool_loss,
        }
        if telemetry:
            from mercury_tpu.obs.diagnostics import (
                clip_fraction,
                ema_drift,
                ess_fraction,
                global_grad_norm,
            )

            metrics["sampler/ess"] = lax.pmean(
                ess_fraction(sel.scaled_probs), data_axis
            )
            metrics["sampler/clip_frac"] = lax.pmean(
                clip_fraction(pool_losses, sel.ema.value, is_alpha),
                data_axis,
            )
            metrics["sampler/ema_drift"] = ema_drift(
                sel.avg_pool_loss, ema.value
            )
            # grads are already the global mean (psum/W above) —
            # replicated, so the norm needs no further collective.
            metrics["train/grad_norm"] = global_grad_norm(grads)
        return new_state, metrics

    state_specs = SpMercuryState(
        params=P(), opt_state=P(),
        ema=EMAState(value=P(data_axis), count=P(data_axis)),
        stream=ShardStream(perm=P(data_axis), cursor=P(data_axis)),
        rng=P(data_axis),
    )
    sharded = shard_map(
        local_step,
        mesh=mesh,
        in_specs=(state_specs, P(), P()),
        out_specs=(state_specs, P()),
        check_vma=False,
    )
    if io_constraints:
        from jax.sharding import NamedSharding

        # SHARDING CONTRACT (see docstring): pin the replicated-input
        # contract at the boundary, outside the shard_map.
        rep_ns = NamedSharding(mesh, P())
        constrained_inner = sharded

        def sharded(state, x_train, y_train):
            x_train = jax.lax.with_sharding_constraint(x_train, rep_ns)
            y_train = jax.lax.with_sharding_constraint(y_train, rep_ns)
            return constrained_inner(state, x_train, y_train)

    if not zigzag:
        return jax.jit(sharded, donate_argnums=donate_argnums(0))

    from mercury_tpu.parallel.sequence import zigzag_order

    def step(state, x_train, y_train):
        perm = jnp.asarray(zigzag_order(x_train.shape[1], w_seq))
        return sharded(state, x_train[:, perm], y_train)

    return jax.jit(step, donate_argnums=donate_argnums(0))
