"""Deterministic fault-injection plane for the host runtime.

Mercury's premise is training on flaky fleets, so the failure paths —
a scorer worker dying, a prefetch gather raising, a checkpoint write
hitting a full disk — are product surface, not test scaffolding. This
module makes every one of them injectable on a deterministic schedule
so the supervisor's restart/degradation machinery
(``runtime/supervisor.py``) is exercised end-to-end in tier-1 tests and
the chaos CI job, with the SAME hook points production code runs.

Spec grammar (``TrainConfig.fault_spec``)::

    spec  := entry (';' entry)*
    entry := kind '@' param (',' param)*
    param := key '=' number

    "scorer_die@step=40"                     # one-shot at step 40
    "prefetch_stall@step=10,secs=2"          # stall the gather 2s once
    "ckpt_io_error@step=0,every=1"           # EVERY checkpoint write fails
    "scorer_die@step=5;scorer_die@step=9"    # two scheduled deaths

``step`` is mandatory: the entry arms at the first trainer step >= it
(:meth:`FaultPlane.note_step` advances the clock from the fit loop; the
worker threads only *read* it, so firing is deterministic in step space
even though workers run asynchronously). ``every=K`` repeats the entry
each K steps after it first fires; omitted means one-shot. Remaining
``key=value`` pairs ride along to the hook site (e.g. ``secs`` for
stalls/slowdowns).

Fault kinds and their hook points:

==================  =====================================================
``scorer_die``      ``ScorerFleet._next_chunk`` raises — kills the worker
                    thread that called it (or the trainer-thread sync
                    refresh, when the ladder has degraded that far)
``scorer_nan``      ``ScorerFleet._next_chunk`` corrupts the chunk's
                    scores to NaN (the trainer's apply guard rejects it)
``scorer_wedge``    ``ScorerService`` marks tenant ``tenant`` (default 0)
                    wedged: it stops scheduling that tenant's chunks, so
                    its staleness grows until the service SLO
                    (``slo_score_staleness_max``) walks the ladder
``prefetch_die``    ``PrefetchPipeline._prefetch_loop`` raises
``prefetch_stall``  the prefetch worker sleeps ``secs`` before gathering
``sink_wedge``      the metric drain thread sleeps ``secs`` mid-emit
``ckpt_io_error``   ``checkpoint._write_msgpack`` raises ``OSError``
                    before touching the file
``host_slow``       the fit loop sleeps ``secs`` on the trainer thread
==================  =====================================================

Zero-cost-when-disabled: every hook site is guarded by
``if faults is not None`` on a plain attribute, and no hook touches a
traced function — with ``fault_spec=""`` the compiled step program is
byte-identical (the graftlint Layer-2/3 digests enforce this).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

__all__ = ["FaultPlane", "InjectedFault", "KNOWN_KINDS", "parse_fault_spec"]

#: Every injectable fault kind; a spec naming anything else is rejected
#: at parse time (a typo'd kind would otherwise never fire, silently).
KNOWN_KINDS = frozenset({
    "scorer_die",
    "scorer_nan",
    "scorer_wedge",
    "prefetch_die",
    "prefetch_stall",
    "sink_wedge",
    "ckpt_io_error",
    "host_slow",
})


class InjectedFault(RuntimeError):
    """An injected failure — distinguishable from organic errors in
    logs and flight records, handled identically by the runtime (the
    whole point: the recovery machinery can't tell the difference)."""


class _Entry:
    """One scheduled fault instance (mutable firing state)."""

    __slots__ = ("kind", "step", "every", "args", "fired", "next_due")

    def __init__(self, kind: str, step: int, every: int,
                 args: Dict[str, float]) -> None:
        self.kind = kind
        self.step = step
        self.every = every            # 0 = one-shot
        self.args = args              # extra params for the hook site
        self.fired = 0
        self.next_due = step

    def pending(self) -> bool:
        return self.every > 0 or self.fired == 0

    def spec(self) -> Dict[str, float]:
        out = {"step": float(self.step), **self.args}
        if self.every:
            out["every"] = float(self.every)
        return out


def parse_fault_spec(spec: str) -> List[_Entry]:
    """Parse the ``kind@k=v,...;kind@...`` grammar; raises ``ValueError``
    with the offending fragment on any malformed entry."""
    entries: List[_Entry] = []
    for raw in spec.split(";"):
        raw = raw.strip()
        if not raw:
            continue
        if "@" not in raw:
            raise ValueError(
                f"fault_spec entry {raw!r}: expected 'kind@step=N[,k=v...]'")
        kind, _, params = raw.partition("@")
        kind = kind.strip()
        if kind not in KNOWN_KINDS:
            raise ValueError(
                f"fault_spec entry {raw!r}: unknown fault kind {kind!r} "
                f"(known: {', '.join(sorted(KNOWN_KINDS))})")
        args: Dict[str, float] = {}
        for pair in params.split(","):
            pair = pair.strip()
            if not pair:
                continue
            if "=" not in pair:
                raise ValueError(
                    f"fault_spec entry {raw!r}: malformed param {pair!r} "
                    "(expected key=number)")
            key, _, val = pair.partition("=")
            try:
                args[key.strip()] = float(val)
            except ValueError:
                raise ValueError(
                    f"fault_spec entry {raw!r}: param {pair!r} is not "
                    "numeric") from None
        if "step" not in args:
            raise ValueError(
                f"fault_spec entry {raw!r}: missing the mandatory "
                "'step=N' param")
        step = int(args.pop("step"))
        every = int(args.pop("every", 0))
        entries.append(_Entry(kind, step, every, args))
    return entries


class FaultPlane:
    """The armed schedule plus the step clock the hook sites fire
    against.

    Thread model: :meth:`note_step` is called once per fit-loop
    iteration on the trainer thread; :meth:`fire` is called from the
    trainer thread AND from worker threads (scorer fleet, prefetch
    pipeline, metric drain). All firing state is guarded by one lock —
    a fault scheduled once fires exactly once, no matter how many
    workers race on it.
    """

    def __init__(self, spec: str = "", journal=None) -> None:
        self._entries = parse_fault_spec(spec)
        self._lock = threading.Lock()
        self._step = 0
        self._fired_total = 0
        # Control-plane event journal (obs/events.py): every firing is
        # journaled so chaos runs are self-describing. The journal's
        # emit() takes only its own leaf lock, so calling it while
        # holding self._lock cannot deadlock. None when journaling is
        # off — and then firing stays allocation-free.
        self._journal = journal

    # --------------------------------------------------------------- clock
    def note_step(self, step: int) -> None:
        """Advance the plane's step clock (trainer thread, per
        iteration). Workers read it through :meth:`fire`."""
        with self._lock:
            self._step = int(step)

    # -------------------------------------------------------------- firing
    def fire(self, kind: str) -> Optional[Dict[str, float]]:
        """Consume the next due entry of ``kind`` at the current step.

        Returns the entry's extra args (possibly empty — still truthy
        ``is not None``) when a scheduled instance is due, else None.
        One-shot entries fire once; ``every=K`` entries re-arm K steps
        after each firing."""
        with self._lock:
            step = self._step
            for entry in self._entries:
                if entry.kind != kind or not entry.pending():
                    continue
                if step < entry.next_due:
                    continue
                entry.fired += 1
                if entry.every:
                    entry.next_due = step + entry.every
                self._fired_total += 1
                if self._journal is not None:
                    try:
                        self._journal.emit(
                            "fault/fired", step,
                            detail={"fault": entry.kind,
                                    "fired": entry.fired,
                                    "args": dict(entry.args)})
                    except Exception:
                        pass  # the plane must fire even if the journal dies
                return dict(entry.args)
        return None

    # ----------------------------------------------------------- telemetry
    def stats(self) -> Dict[str, float]:
        """Log-gate scalars (host floats; keys in obs/registry.py)."""
        with self._lock:
            armed = sum(1 for e in self._entries if e.pending())
            return {
                "fault/injected": float(self._fired_total),
                "fault/armed": float(armed),
            }

    def summary(self) -> Dict[str, object]:
        """Cumulative view for flight-record context dumps."""
        with self._lock:
            return {
                "step": self._step,
                "fired_total": self._fired_total,
                "entries": [
                    {"kind": e.kind, "fired": e.fired,
                     "pending": e.pending(), **e.spec()}
                    for e in self._entries
                ],
            }
