"""Version-compatibility shims for the jax API surface this codebase uses.

The training code targets the modern ``jax.shard_map`` entry point
(keyword ``check_vma``, manual axes named via ``axis_names``). Older jax
releases (< 0.5) ship the same machinery as
``jax.experimental.shard_map.shard_map`` with the complementary spelling:
``check_rep`` for the replication check and ``auto`` naming the axes that
stay automatic instead of the axes that go manual. Importing
:func:`shard_map` from here gives every call site one stable signature —
the modern one — regardless of which jax is installed.

This module must import nothing from the rest of the package (it is the
first thing ``parallel/__init__`` pulls in).
"""

from __future__ import annotations

try:  # jax >= 0.5: top-level export, modern keywords — pass through.
    from jax import shard_map as _shard_map

    _MODERN = True
except ImportError:  # jax < 0.5: experimental location, legacy keywords.
    from jax.experimental.shard_map import shard_map as _shard_map

    _MODERN = False

#: True on jax >= 0.5. Legacy jax has sharp edges beyond the shard_map
#: spelling — e.g. jit out_shardings on PRNG key arrays under a
#: partial-manual mesh trip a GSPMD rank-validation bug (the hidden
#: [..., 2] key payload dim is not appended to the tile assignment).
MODERN_JAX = _MODERN


def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
              axis_names=None):
    """``jax.shard_map`` with the modern keyword surface on any jax.

    ``axis_names`` names the axes the body is manual over (None = all of
    them); ``check_vma`` toggles the varying-manual-axes / replication
    check. On legacy jax these translate to ``auto`` (the complement of
    ``axis_names`` within the mesh) and ``check_rep``.
    """
    if _MODERN:
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = frozenset(axis_names)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_vma=check_vma, **kw)
    auto = frozenset()
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
    # check_rep stays off on legacy jax regardless of check_vma: the old
    # replication checker cannot see through psum_scatter/ppermute chains
    # (e.g. the sequence-parallel step on a data×seq mesh) and rejects
    # valid replicated out_specs that the modern check_vma accepts. The
    # check is advisory — partitioning semantics are unchanged.
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=False, auto=auto)


try:  # modern jax: first-class query for a named axis's size.
    from jax.lax import axis_size as axis_size  # noqa: F401
except ImportError:  # legacy jax: psum of the Python literal 1 is
    # constant-folded to the same static integer (this was the idiomatic
    # spelling before lax.axis_size existed), so shapes derived from it
    # stay static.
    def axis_size(axis_name):
        """Static size of the named mesh axis inside a shard_map body."""
        from jax import lax

        return lax.psum(1, axis_name)


def donate_argnums(*argnums):
    """Buffer-donation argnums for ``jax.jit`` — empty on legacy jax.

    Legacy jax (< 0.5) has a CPU correctness bug in the persistent
    compilation cache: an executable deserialized from a cache *hit*
    mishandles the input-output aliasing that donation sets up, so a
    donated train step can silently drop its parameter update (the same
    program compiled on a cache miss is correct). Donation is purely a
    memory optimization — disabling it on legacy jax trades peak memory
    for correctness and keeps the cache usable. Modern jax donates as
    written.
    """
    return tuple(argnums) if MODERN_JAX else ()


try:  # modern jax: cast a value's varying-manual-axes (vma) type.
    from jax.lax import pcast as pcast  # noqa: F401
except ImportError:  # legacy jax has no vma type system (and the
    # replication check above is off), so the annotation is a no-op.
    def pcast(x, axis_name, *, to):
        """Identity on legacy jax; vma cast on modern jax."""
        del axis_name, to
        return x


def register_compile_listener(callback):
    """Subscribe ``callback(event_name)`` to jax's trace/compile events.

    On jax builds that ship ``jax.monitoring``, the duration events
    ``.../jaxpr_trace_duration`` and ``.../backend_compile_duration``
    fire once per trace / per XLA compile — exactly the signal the
    retrace guard (lint/tracecheck.py) counts. Listener registration is
    permanent on these jax versions (there is no per-listener
    unregister, only a clear-all that would stomp other subscribers),
    so callers install ONE process-wide callback and gate it
    themselves.

    Returns True when the listener was installed; False on legacy jax
    without ``jax.monitoring``, where callers fall back to polling the
    jit cache via :func:`jit_cache_size`.
    """
    try:
        from jax import monitoring
    except ImportError:
        return False
    register = getattr(monitoring,
                       "register_event_duration_secs_listener", None)
    if register is None:
        return False

    def _on_event(event, duration_secs, **kwargs):
        del duration_secs, kwargs
        callback(event)

    register(_on_event)
    return True


def jit_cache_size(fn):
    """Entries in ``fn``'s jit cache, or -1 when this jax build doesn't
    expose it. The legacy-jax fallback for counting retraces: a growing
    cache across steady-state calls IS a retrace, whoever caused it."""
    probe = getattr(fn, "_cache_size", None)
    if callable(probe):
        try:
            return int(probe())
        except Exception:
            return -1
    return -1
