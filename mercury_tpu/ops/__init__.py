from mercury_tpu.ops.mercury_kernels import (  # noqa: F401
    augment_normalize_pallas,
    on_tpu,
    per_sample_nll_pallas,
    score_and_draw_pallas,
    table_refresh_draw_pallas,
)
