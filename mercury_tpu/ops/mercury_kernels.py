"""Pallas TPU kernels for the Mercury hot ops.

Four kernels cover the importance-sampling inner loop (the math of
``Trainer.update_samples``, ``pytorch_collab.py:101-117``) plus the
uint8 ingest path that feeds it:

1. :func:`per_sample_nll_pallas` — fused per-sample cross-entropy
   (log-softmax + label gather in one VMEM pass, ≡ ``F.cross_entropy(...,
   reduction='none')`` at ``:102,:133``), with a custom VJP
   (``softmax − onehot`` per sample) so it serves both the scoring pass and
   the differentiable training loss.
2. :func:`score_and_draw_pallas` — fused score smoothing → normalization →
   inverse-CDF categorical draws → ``p·N`` gather (≡ ``:111-116``), one
   VMEM-resident kernel: the cumulative distribution never round-trips to
   HBM.
3. :func:`table_refresh_draw_pallas` — fused scoretable step: age-decay +
   refresh-window scatter + smoothing + inverse-CDF draw over the whole
   persistent ``[L]`` table in one VMEM pass.
4. :func:`augment_normalize_pallas` — fused uint8 ingest: dequant →
   per-channel normalize → random crop(pad)/hflip in one VMEM pass per
   image (``_data_transforms_cifar10``, ``cifar10/data_loader.py:83-96``).
   The raw bytes enter VMEM as uint8 (4× less HBM traffic than the f32
   HLO chain it replaces) and the crop/flip are gather-free one-hot
   selections, bit-identical to ``normalize_images`` + ``augment_batch``.
   Off-TPU its wrapper dispatches to an equivalent jax-native fused chain
   instead of the interpreter (the one-hot matmuls are MXU work;
   ``use_kernel=True`` forces the kernel for interpret-mode parity tests).

Uniform variates are passed in (from ``jax.random``) rather than drawn with
the in-kernel TPU PRNG, so the draw is reproducible from a JAX key and the
kernels run identically under ``interpret=True`` on CPU (how the test suite
exercises them without a chip).

Shapes here are small-to-medium (pool up to tens of thousands, classes ≤
1024): each kernel is a single block, no grid — Mosaic pads to the (8, 128)
f32 tile internally. The win is fusion (one HBM read of the logits,
everything else in VMEM), not tiling. The draw kernel's CDF is computed in
``[T, T]`` chunks (T ≤ 512) with a running scalar prefix, so its VMEM
footprint is O(N·B + T²) rather than the O(N²) a single lower-triangular
matmul would need — a 4096-candidate pool costs a 1 MB triangle tile, not
a 64 MB square.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def on_tpu() -> bool:
    """True when the default backend is a real TPU (kernels compile via
    Mosaic); otherwise wrappers run in interpret mode."""
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return not on_tpu()


# ----------------------------------------------------------------- kernel 1
def _nll_kernel(logits_ref, labels_ref, nll_ref):
    """Fused log-softmax + one-hot gather: nll_i = lse(logits_i) − logits_i[y_i]."""
    logits = logits_ref[:].astype(jnp.float32)          # [N, C]
    m = jnp.max(logits, axis=1, keepdims=True)
    shifted = logits - m
    lse = jnp.log(jnp.sum(jnp.exp(shifted), axis=1, keepdims=True)) + m  # [N, 1]
    n, c = logits.shape
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (n, c), 1) == labels_ref[:]
    ).astype(jnp.float32)                                # labels_ref: [N, 1]
    picked = jnp.sum(logits * onehot, axis=1, keepdims=True)  # [N, 1]
    nll_ref[:] = lse - picked


def _nll_fwd_raw(logits: jax.Array, labels: jax.Array) -> jax.Array:
    n, _ = logits.shape
    return pl.pallas_call(
        _nll_kernel,
        out_shape=jax.ShapeDtypeStruct((n, 1), jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(logits, labels.reshape(-1, 1).astype(jnp.int32))[:, 0]


def _nll_bwd_kernel(logits_ref, labels_ref, g_ref, grad_ref):
    """d nll_i / d logits_i = softmax(logits_i) − onehot(y_i), scaled by g_i."""
    logits = logits_ref[:].astype(jnp.float32)
    m = jnp.max(logits, axis=1, keepdims=True)
    e = jnp.exp(logits - m)
    softmax = e / jnp.sum(e, axis=1, keepdims=True)
    n, c = logits.shape
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (n, c), 1) == labels_ref[:]
    ).astype(jnp.float32)
    grad_ref[:] = (softmax - onehot) * g_ref[:]          # g_ref: [N, 1]


@jax.custom_vjp
def per_sample_nll_pallas(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fused per-sample cross-entropy (``reduction='none'``) as a Pallas
    kernel. ``logits``: [N, C] (any float dtype), ``labels``: [N] int.
    Returns fp32 ``[N]`` losses. Differentiable w.r.t. logits.

    Runs under the ``mercury_nll_kernel`` named scope — the jaxpr auditor
    (``mercury_tpu/lint/audit.py``) keys per-region checks on these
    anchors when a TPU plan traces the Pallas path."""
    with jax.named_scope("mercury_nll_kernel"):
        return _nll_fwd_raw(logits, labels)


def _vjp_fwd(logits, labels):
    return _nll_fwd_raw(logits, labels), (logits, labels)


def _vjp_bwd(residual, g):
    logits, labels = residual
    n, _ = logits.shape
    grad = pl.pallas_call(
        _nll_bwd_kernel,
        out_shape=jax.ShapeDtypeStruct(logits.shape, jnp.float32),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        interpret=_interpret(),
    )(logits, labels.reshape(-1, 1).astype(jnp.int32),
      g.reshape(-1, 1).astype(jnp.float32))
    return grad.astype(logits.dtype), None


per_sample_nll_pallas.defvjp(_vjp_fwd, _vjp_bwd)


# ----------------------------------------------------------------- kernel 2
def _pow2_divisor(n: int, cap: int = 512) -> int:
    """Largest power-of-two divisor of ``n``, capped."""
    t = cap
    while t > 1 and n % t != 0:
        t //= 2
    return t


def _cdf_chunk(n: int) -> int:
    """CDF chunk size: the largest power-of-two divisor of ``n``, capped
    at 512 — chunks tile the pool exactly and the in-kernel triangle mask
    stays ≤ 1 MB regardless of pool size.

    A pool whose largest power-of-two divisor is tiny (e.g. 625) would
    unroll n/t near-scalar chunks into the Mosaic program; instead, such
    pools fall back to the single [n, n] triangle when it fits VMEM
    comfortably (n ≤ 1024 → ≤ 4 MB) — larger awkward pools are padded to
    a 512-multiple by the wrapper before reaching the kernel."""
    t = _pow2_divisor(n)
    if t < 64 and n <= 1024:
        return n
    return t


def _inverse_cdf_draw(probs, u, true_n: int):
    """Chunked inverse-CDF categorical draw, in-kernel shared math.

    ``probs``: [N, 1] normalized; ``u``: [1, B] iid U(0,1). Returns the
    drawn indices [1, B] int32, clamped to the REAL pool (< ``true_n``).

    Mosaic notes: ``cumsum`` has no TC lowering, so each chunk's local CDF
    is a lower-triangular matmul (MXU) over a ``[T, T]`` tile, offset by
    the running scalar prefix of the chunks before it. The inverse-CDF
    count ``idx_b = #{j: cdf_j <= u_b}`` decomposes exactly over chunks
    (each chunk contributes its own count), so chunking changes the VMEM
    footprint — O(T²) instead of O(N²) — and nothing else. The loop over
    N/T chunks is a static Python unroll (straight-line Mosaic program).
    """
    n = probs.shape[0]
    b = u.shape[1]
    t = _cdf_chunk(n)
    row = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
    lower = (col <= row).astype(jnp.float32)              # [T, T]

    # Inverse-CDF sampling ≡ multinomial-with-replacement (:114):
    # idx_b = #{ j : cdf_j <= u_b }, accumulated chunk by chunk with the
    # global prefix carried as a scalar.
    counts = jnp.zeros((1, b), jnp.int32)
    prefix = jnp.zeros((), jnp.float32)
    for c in range(n // t):
        pc = probs[c * t:(c + 1) * t, :]                  # [T, 1]
        cdf_c = prefix + jnp.dot(
            lower, pc, preferred_element_type=jnp.float32
        )                                                 # [T, 1]
        counts = counts + jnp.sum(
            (cdf_c <= u).astype(jnp.int32), axis=0, keepdims=True
        )
        prefix = prefix + jnp.sum(pc)
    # Clamp to the REAL pool: padded rows (wrapper-added, score 1e-12)
    # carry ~zero probability, and the clamp guarantees a draw can never
    # land on one even at u → 1.
    return jnp.minimum(counts, true_n - 1)                # [1, B]


def _scaled_probs_gather(probs, idx, true_n: int):
    """``scaled_b = p[idx_b]·N`` via one-hot mask-and-reduce (gather-free;
    [N, B] is O(N·B) — pool·batch, not pool², so it stays unchunked).
    N is the REAL pool size: the p·N reweight contract (:116) is about
    the candidate count the caller drew from, not the padded tile."""
    n = probs.shape[0]
    b = idx.shape[1]
    onehot = (
        jax.lax.broadcasted_iota(jnp.int32, (n, b), 0) == idx
    ).astype(jnp.float32)                                 # [N, B]
    return jnp.sum(onehot * (probs * true_n), axis=0, keepdims=True)


def _score_draw_kernel(
    losses_ref, ema_ref, uniforms_ref,
    probs_ref, selected_ref, scaled_ref,
    *, alpha: float, true_n: int,
):
    """score → normalize → chunked inverse-CDF draw → p·N gather, all in
    VMEM.

    ``losses_ref``: [N, 1]; ``ema_ref``: [1, 1] (SMEM); ``uniforms_ref``:
    [1, B] iid U(0,1). Outputs: normalized probs [N, 1], selected pool
    positions [1, B] int32, scaled probs p·N [1, B].
    """
    losses = losses_ref[:]                                # [N, 1]
    scores = jnp.maximum(losses + alpha * ema_ref[0, 0], 1e-12)  # :111
    total = jnp.sum(scores)
    probs = scores / total                                # :112
    probs_ref[:] = probs

    u = uniforms_ref[:]                                   # [1, B]
    idx = _inverse_cdf_draw(probs, u, true_n)
    selected_ref[:] = idx
    scaled_ref[:] = _scaled_probs_gather(probs, idx, true_n)  # p·N (:116)


def score_and_draw_pallas(
    key: jax.Array,
    losses: jax.Array,
    ema_value: jax.Array,
    batch_size: int,
    alpha: float = 0.5,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Fused Mercury selection given per-candidate losses and the (already
    updated, possibly psum-synced) EMA value.

    Returns ``(probs [N], selected [B] int32, scaled_probs [B])`` matching
    the jax-native ``importance_probs`` + ``draw_with_replacement`` +
    ``p·N`` pipeline (``mercury_tpu.sampling.importance``).
    """
    n = losses.shape[0]
    n_pad = n
    if _pow2_divisor(n) < 64 and n > 1024:
        # Awkward large pool (tiny power-of-two divisor): pad to the next
        # 512-multiple so the chunked CDF tiles exactly. Pad losses of
        # -1e30 clamp to score 1e-12 (≈ zero probability); the kernel's
        # idx clamp and p·N scale both use the true n.
        n_pad = -(-n // 512) * 512
        losses = jnp.concatenate([
            losses.astype(jnp.float32),
            jnp.full((n_pad - n,), -1e30, jnp.float32),
        ])
    uniforms = jax.random.uniform(key, (1, batch_size), jnp.float32)
    kernel = functools.partial(_score_draw_kernel, alpha=alpha, true_n=n)
    # Auditor anchor (see per_sample_nll_pallas): the fused selection
    # kernel is one named region in the traced program.
    with jax.named_scope("mercury_score_draw_kernel"):
        probs, selected, scaled = pl.pallas_call(
            kernel,
            out_shape=(
                jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
                jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
                jax.ShapeDtypeStruct((1, batch_size), jnp.float32),
            ),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ],
            out_specs=(
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
                pl.BlockSpec(memory_space=pltpu.VMEM),
            ),
            interpret=_interpret(),
        )(
            losses.reshape(-1, 1).astype(jnp.float32),
            ema_value.reshape(1, 1).astype(jnp.float32),
            uniforms,
        )
    return probs[:n, 0], selected[0, :], scaled[0, :]


# ----------------------------------------------------------------- kernel 3
def _table_refresh_draw_kernel(
    table_ref, slots_ref, rscores_ref, ema_ref, uniforms_ref,
    table_out_ref, probs_ref, selected_ref, scaled_ref,
    *, alpha: float, decay: float, true_n: int,
):
    """Fused score-table step (``sampler="scoretable"``): age-decay the
    whole table toward the EMA mean, scatter the freshly scored refresh
    window in, smooth/normalize over ALL slots, and draw the train batch —
    one VMEM pass over the persistent ``[L]`` table, no HBM round trip
    between the decay, the scatter, and the CDF.

    ``table_ref``: [N, 1] persistent scores; ``slots_ref``/``rscores_ref``:
    [1, R] refresh window (slot ids < true_n, fresh scores);
    ``ema_ref``: [1, 1] (SMEM); ``uniforms_ref``: [1, B]. Outputs: the
    refreshed table [N, 1], normalized probs [N, 1], selected slots
    [1, B] int32, scaled probs p·L [1, B].

    The scatter is a one-hot mask-and-reduce over [N, R] (R ≪ N — the
    whole point of the refresh window), with duplicate slots averaged —
    exactly ``sampling.scoretable.scatter_mean``. Padded rows (wrapper-
    added past ``true_n``) are re-floored to -1e30 every call so the decay
    can never resurrect them into the distribution.
    """
    mu = ema_ref[0, 0]
    table = table_ref[:]                                  # [N, 1]
    n = table.shape[0]
    # Staleness decay: entries refreshed a steps ago sit γ^a of the way
    # back to the EMA mean — stale extremes fade, nothing starves.
    decayed = mu + (table - mu) * decay
    rows = jax.lax.broadcasted_iota(jnp.int32, (n, 1), 0)
    decayed = jnp.where(rows < true_n, decayed, -1e30)

    slots = slots_ref[:]                                  # [1, R]
    rscores = rscores_ref[:]                              # [1, R]
    hit = (
        jax.lax.broadcasted_iota(jnp.int32, (n, slots.shape[1]), 0) == slots
    ).astype(jnp.float32)                                 # [N, R]
    sums = jnp.sum(hit * rscores, axis=1, keepdims=True)  # [N, 1]
    counts = jnp.sum(hit, axis=1, keepdims=True)          # [N, 1]
    refreshed = jnp.where(
        counts > 0, sums / jnp.maximum(counts, 1.0), decayed
    )
    table_out_ref[:] = refreshed

    scores = jnp.maximum(refreshed + alpha * mu, 1e-12)
    probs = scores / jnp.sum(scores)
    probs_ref[:] = probs

    idx = _inverse_cdf_draw(probs, uniforms_ref[:], true_n)
    selected_ref[:] = idx
    scaled_ref[:] = _scaled_probs_gather(probs, idx, true_n)  # p·L


def table_refresh_draw_pallas(
    key: jax.Array,
    scores: jax.Array,
    refresh_slots: jax.Array,
    refresh_scores: jax.Array,
    ema_value: jax.Array,
    batch_size: int,
    alpha: float = 0.5,
    decay: float = 0.98,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Fused scoretable decay + scatter-refresh + full-table draw.

    Returns ``(new_scores [L], probs [L], selected [B] int32,
    scaled_probs [B])``, matching the jax-native
    ``sampling.scoretable.table_refresh_draw`` (same decay/scatter/probs
    bit math; draws use the same inverse-CDF machinery as
    :func:`score_and_draw_pallas`, reproducible from the JAX key).
    """
    n = scores.shape[0]
    n_pad = n
    scores = scores.astype(jnp.float32)
    if _pow2_divisor(n) < 64 and n > 1024:
        # Same awkward-size rule as score_and_draw_pallas: pad to a
        # 512-multiple; pad rows carry -1e30 (score floor, never drawn)
        # and are re-floored in-kernel each call, then sliced off here —
        # the persistent table the caller carries stays [L].
        n_pad = -(-n // 512) * 512
        scores = jnp.concatenate([
            scores, jnp.full((n_pad - n,), -1e30, jnp.float32)
        ])
    uniforms = jax.random.uniform(key, (1, batch_size), jnp.float32)
    kernel = functools.partial(
        _table_refresh_draw_kernel, alpha=alpha, decay=decay, true_n=n
    )
    r = refresh_slots.shape[0]
    new_table, probs, selected, scaled = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.int32),
            jax.ShapeDtypeStruct((1, batch_size), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        interpret=_interpret(),
    )(
        scores.reshape(-1, 1),
        refresh_slots.reshape(1, r).astype(jnp.int32),
        refresh_scores.reshape(1, r).astype(jnp.float32),
        ema_value.reshape(1, 1).astype(jnp.float32),
        uniforms,
    )
    return new_table[:n, 0], probs[:n, 0], selected[0, :], scaled[0, :]


# ----------------------------------------------------------------- kernel 4
def _augment_norm_kernel(
    raw_ref, mean_ref, std_ref, oy_ref, ox_ref, flip_ref, out_ref,
    *, pad: int, out_dtype,
):
    """Fused dequant → normalize → crop/flip for ONE image (grid over the
    batch): the raw uint8 block is read once, everything else stays in
    VMEM.

    ``raw_ref``: [1, H, W, C] uint8; ``mean_ref``/``std_ref``: [1, C] f32
    per-channel constants; ``oy_ref``/``ox_ref``/``flip_ref``: [1, 1] SMEM
    int32 — this image's crop offsets (0..2·pad) and flip bit.

    Bit-exactness contract (vs ``normalize_images`` + ``augment_batch``):
    normalize is elementwise so it commutes exactly with the crop/flip
    gathers, and the unfused path pads AFTER normalizing — out-of-bounds
    pixels are literal 0.0 in normalized space, which the one-hot
    selection reproduces for free (no source row/col matches → the
    mask-and-reduce sums to zero). The crop and the flip fold into one
    column selection: ``src_x = (W-1-x if flip else x) + ox - pad``
    (crop-then-flip ≡ flipped-column crop). One-hot × value sums are
    IEEE-exact — each output pixel is one picked value plus signed zeros.
    """
    x = raw_ref[0].astype(jnp.float32) / 255.0            # [H, W, C]
    xn = (x - mean_ref[0][None, None, :]) / std_ref[0][None, None, :]
    h, w, _ = xn.shape
    oy = oy_ref[0, 0]
    ox = ox_ref[0, 0]
    flip = flip_ref[0, 0]

    # Row select: out1[y] = padded[y + oy] = xn[y + oy - pad] (0.0 OOB).
    src_y = jax.lax.broadcasted_iota(jnp.int32, (h, h), 1) + oy - pad
    rsel = (jax.lax.broadcasted_iota(jnp.int32, (h, h), 0) == src_y
            ).astype(jnp.float32)                         # [Y_src, y_out]
    out1 = jnp.sum(rsel[:, :, None, None] * xn[:, None, :, :], axis=0)

    # Column select with the flip folded in (see docstring).
    x_out = jax.lax.broadcasted_iota(jnp.int32, (w, w), 1)
    x_eff = jnp.where(flip != 0, w - 1 - x_out, x_out)
    csel = (jax.lax.broadcasted_iota(jnp.int32, (w, w), 0) == x_eff + ox - pad
            ).astype(jnp.float32)                         # [X_src, x_out]
    out = jnp.sum(csel[None, :, :, None] * out1[:, :, None, :], axis=1)
    # + 0.0 canonicalizes the all-(-0.0) OOB corner to the unfused path's
    # +0.0 pad value; every other pixel is unchanged (exact for v != 0).
    out_ref[0] = (out + 0.0).astype(out_dtype)


def augment_normalize_pallas(
    key: jax.Array,
    raw: jax.Array,
    mean,
    std,
    pad: int = 4,
    out_dtype=jnp.float32,
    use_kernel=None,
) -> jax.Array:
    """Fused uint8 ingest: dequant + per-channel normalize + random
    crop(``pad``) + horizontal flip in one VMEM pass, bit-identical (at
    f32) to ``augment_batch(key, normalize_images(raw, mean, std))``.

    ``raw``: [N, H, W, C] uint8; ``mean``/``std``: per-channel constants.
    ``out_dtype`` is applied as the LAST op on either path, so the bf16
    scoring path (``scoring_dtype="bfloat16"`` + ``fused_input``) emits
    bf16 activations directly — one rounding of the exact f32 value, never
    an f32 round trip through HBM.

    ``use_kernel=None`` picks the Mosaic kernel on real TPU (the one-hot
    selections there are MXU work and the uint8 block enters VMEM once);
    elsewhere it falls to a jax-native fused chain built from the exact
    unfused ops (``normalize_images`` → pad → ``_take_crops`` → flip) with
    the pre-drawn offsets, because the one-hot matmuls that are cheap on
    the MXU are ~H× extra FLOPs for the CPU interpreter. Tests pass
    ``use_kernel=True`` to pin the interpret-mode kernel's bit-parity.

    The crop/flip draws replay ``augment_batch``'s key consumption exactly
    (split 3 ways; ``randint`` for offsets, ``bernoulli`` for flips), so a
    trajectory is reproducible from the same JAX key on either path. Runs
    under the ``mercury_input_fuse`` named scope — the profile-attribution
    bucket (``prof/scope_frac/mercury_input_fuse``) and the jaxpr auditor
    both key on this anchor."""
    n, h, w, c = raw.shape
    # Mirror augment_batch's split even though cutout is unsupported here
    # (config validation rejects fused_input + cutout): the draw STREAM
    # must match so unfused trajectories replay bit-for-bit.
    k_crop, k_flip, _k_cut = jax.random.split(key, 3)
    off = jax.random.randint(k_crop, (n, 2), 0, 2 * pad + 1)
    flip = jax.random.bernoulli(k_flip, shape=(n,))
    if use_kernel is None:
        use_kernel = on_tpu()
    if not use_kernel:
        from mercury_tpu.data.pipeline import _take_crops, normalize_images

        with jax.named_scope("mercury_input_fuse"):
            xn = normalize_images(raw, mean, std)
            padded = jnp.pad(xn, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
            out = _take_crops(padded, off[:, 0], off[:, 1], h, w)
            out = jnp.where(flip[:, None, None, None],
                            out[:, :, ::-1, :], out)
            return out.astype(jnp.dtype(out_dtype))
    kernel = functools.partial(
        _augment_norm_kernel, pad=pad, out_dtype=jnp.dtype(out_dtype),
    )
    smem = pl.BlockSpec((1, 1), lambda i: (i, 0), memory_space=pltpu.SMEM)
    chan = pl.BlockSpec((1, c), lambda i: (0, 0), memory_space=pltpu.VMEM)
    with jax.named_scope("mercury_input_fuse"):
        return pl.pallas_call(
            kernel,
            grid=(n,),
            out_shape=jax.ShapeDtypeStruct((n, h, w, c), jnp.dtype(out_dtype)),
            in_specs=[
                pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                             memory_space=pltpu.VMEM),
                chan, chan,
                smem, smem, smem,
            ],
            out_specs=pl.BlockSpec((1, h, w, c), lambda i: (i, 0, 0, 0),
                                   memory_space=pltpu.VMEM),
            interpret=_interpret(),
        )(
            raw,
            jnp.asarray(mean, jnp.float32).reshape(1, c),
            jnp.asarray(std, jnp.float32).reshape(1, c),
            off[:, 0:1].astype(jnp.int32),
            off[:, 1:2].astype(jnp.int32),
            flip[:, None].astype(jnp.int32),
        )
