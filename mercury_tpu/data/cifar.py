"""CIFAR-10 / CIFAR-100 ingest to host arrays.

Capability parity with the reference's dataset layer: ``load_cifar10_data``
(``cifar10/data_loader.py:114-123``) and the torchvision-backed
``CIFAR10_truncated`` (``cifar10/datasets.py:39-96``) / ``My_CIFAR10``
(``util.py:240-273``). The reference downloads via torchvision; this
environment has no network egress, so we read the standard on-disk formats
(python-pickle batches or an ``.npz`` cache) from a data directory, and fall
back to a deterministic, *learnable* synthetic dataset so tests and smoke
benchmarks run anywhere.

Index-carrying contract: the reference's ``__getitem__`` returns
``(index, image, target)`` (``cifar10/datasets.py:93``, ``util.py:262``) so
importance scores attribute to samples. Here the whole dataset lives in
memory as arrays and every batching op carries the global index array
alongside images/labels (see ``mercury_tpu.data.pipeline``).
"""

from __future__ import annotations

import os
import pickle
import tarfile
import warnings
from typing import Optional, Tuple

import numpy as np

# Standard CIFAR channel statistics — the live non-IID transform normalizes
# with these (``cifar10/data_loader.py:83-96``).
CIFAR10_MEAN = np.array([0.49139968, 0.48215827, 0.44653124], np.float32)
CIFAR10_STD = np.array([0.24703233, 0.24348505, 0.26158768], np.float32)
CIFAR100_MEAN = np.array([0.5071, 0.4865, 0.4409], np.float32)
CIFAR100_STD = np.array([0.2673, 0.2564, 0.2762], np.float32)

_SEARCH_DIRS = ("data", os.path.expanduser("~/.cache/mercury_tpu"), "/tmp/mercury_tpu_data")


def _unpickle(f) -> dict:
    return pickle.load(f, encoding="latin1")


def _load_pickle_batches(batch_dir: str, files, label_key: str) -> Tuple[np.ndarray, np.ndarray]:
    xs, ys = [], []
    for name in files:
        with open(os.path.join(batch_dir, name), "rb") as f:
            d = _unpickle(f)
        xs.append(d["data"])
        ys.append(np.asarray(d[label_key], np.int32))
    x = np.concatenate(xs).reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # NHWC
    return np.ascontiguousarray(x, np.uint8), np.concatenate(ys)


def _try_load_cifar10(root: str):
    bdir = os.path.join(root, "cifar-10-batches-py")
    if not os.path.isdir(bdir):
        tgz = os.path.join(root, "cifar-10-python.tar.gz")
        if os.path.isfile(tgz):
            with tarfile.open(tgz) as tf:
                tf.extractall(root)
    if os.path.isdir(bdir):
        train = _load_pickle_batches(bdir, [f"data_batch_{i}" for i in range(1, 6)], "labels")
        test = _load_pickle_batches(bdir, ["test_batch"], "labels")
        return train, test
    npz = os.path.join(root, "cifar10.npz")
    if os.path.isfile(npz):
        d = np.load(npz)
        return (d["x_train"], d["y_train"].astype(np.int32)), (
            d["x_test"],
            d["y_test"].astype(np.int32),
        )
    return None


def _try_load_cifar100(root: str):
    bdir = os.path.join(root, "cifar-100-python")
    if not os.path.isdir(bdir):
        tgz = os.path.join(root, "cifar-100-python.tar.gz")
        if os.path.isfile(tgz):
            with tarfile.open(tgz) as tf:
                tf.extractall(root)
    if os.path.isdir(bdir):
        train = _load_pickle_batches(bdir, ["train"], "fine_labels")
        test = _load_pickle_batches(bdir, ["test"], "fine_labels")
        return train, test
    npz = os.path.join(root, "cifar100.npz")
    if os.path.isfile(npz):
        d = np.load(npz)
        return (d["x_train"], d["y_train"].astype(np.int32)), (
            d["x_test"],
            d["y_test"].astype(np.int32),
        )
    return None


def synthetic_cifar(
    num_classes: int = 10,
    train_size: int = 5000,
    test_size: int = 1000,
    image_size: int = 32,
    seed: int = 0,
    difficulty: str = "uniform",
    label_noise: float = 0.0,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic learnable stand-in for CIFAR when no data is on disk.

    Each class gets a fixed random low-frequency template; samples are the
    class template plus per-sample noise and a random brightness shift, so a
    small CNN can separate classes (used by convergence smoke tests) while
    per-sample difficulty varies (so importance sampling has signal).

    ``difficulty="heavy_tail"`` draws the per-sample noise scale from a
    lognormal instead of a narrow uniform: most samples are easy, a long
    tail is very hard — the regime importance sampling is designed for
    (and where uniform sampling wastes most of its gradient budget on
    already-learned samples). ``label_noise`` flips that fraction of
    TRAIN labels to a random other class (test labels stay clean) — the
    adversarial case for loss-proportional scoring, which chases
    unlearnable samples.
    """
    rng = np.random.default_rng(seed)
    # Low-frequency class templates: upsampled 4x4 random patterns.
    small = rng.normal(0, 1, (num_classes, 4, 4, 3)).astype(np.float32)
    reps = image_size // 4
    templates = np.repeat(np.repeat(small, reps, axis=1), reps, axis=2)

    def make(n, offset, noisy_labels: bool):
        local = np.random.default_rng(seed + offset)
        y = local.integers(0, num_classes, n).astype(np.int32)
        if difficulty == "heavy_tail":
            noise_scale = np.clip(
                local.lognormal(-0.3, 1.0, (n, 1, 1, 1)), 0.1, 8.0
            ).astype(np.float32)
        elif difficulty == "uniform":
            noise_scale = local.uniform(0.3, 1.5, (n, 1, 1, 1)).astype(np.float32)
        else:
            raise ValueError(f"unknown difficulty {difficulty!r}")
        noise = local.normal(0, 1, (n, image_size, image_size, 3)).astype(np.float32)
        x = templates[y] + noise_scale * noise
        if difficulty == "heavy_tail":
            # Per-sample normalization: a global min/max would let the
            # noise tail's extreme values crush every sample's contrast
            # into a few uint8 levels, making the task unlearnable for
            # ALL strategies (no discrimination).
            lo = x.min(axis=(1, 2, 3), keepdims=True)
            hi = x.max(axis=(1, 2, 3), keepdims=True)
            x = (x - lo) / (hi - lo + 1e-8)
        else:
            x = (x - x.min()) / (x.max() - x.min() + 1e-8)
        if noisy_labels and label_noise > 0.0:
            flip = local.random(n) < label_noise
            shift = local.integers(1, num_classes, n).astype(np.int32)
            y = np.where(flip, (y + shift) % num_classes, y).astype(np.int32)
        return (x * 255).astype(np.uint8), y

    return make(train_size, 1, True), make(test_size, 2, False)


def synthetic_sequences(
    num_classes: int = 10,
    train_size: int = 5000,
    test_size: int = 1000,
    seq_len: int = 32,
    feature_dim: int = 16,
    seed: int = 0,
    difficulty: str = "uniform",
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray]]:
    """Deterministic learnable sequence dataset ``[N, T, F]`` float32 — a
    stand-in for the speech/audio workloads the reference's ``MyLSTM``
    targets (``pytorch_model.py:208-241``; never wired to training there).

    Each class is a fixed random frequency/phase pattern per feature
    channel; samples add per-sample noise at varying scale so importance
    sampling has signal.

    ``difficulty="hard_minority"`` (the round-4 flagship experiment task):
    85% of samples carry the class signal across the whole sequence; 15%
    carry it ONLY in the final 6 timesteps (zero elsewhere) at reduced
    amplitude — clean labels, fully learnable (the signal is
    deterministic), but structurally harder: the model must attend to a
    narrow window instead of pooling the whole sequence. The easy bulk
    interpolates quickly (per-sample gradients collapse there — measured,
    ``results_grad_variance.jsonl``), after which the minority carries
    essentially all remaining gradient signal: the regime where
    loss-proportional selection (``pytorch_collab.py:89-117``) should pay
    and uniform sampling wastes ~85% of each batch.
    """
    rng = np.random.default_rng(seed)
    freqs = rng.uniform(0.5, 4.0, (num_classes, feature_dim)).astype(np.float32)
    phases = rng.uniform(0, 2 * np.pi, (num_classes, feature_dim)).astype(np.float32)
    t = np.arange(seq_len, dtype=np.float32)[None, :, None]  # [1, T, 1]

    def make(n, offset):
        local = np.random.default_rng(seed + offset)
        y = local.integers(0, num_classes, n).astype(np.int32)
        base = np.sin(
            2 * np.pi * freqs[y][:, None, :] * t / seq_len + phases[y][:, None, :]
        )  # [n, T, F]
        if difficulty == "hard_minority":
            hard = local.random(n) < 0.15
            win = max(seq_len // 5, 2)
            window = (np.arange(seq_len) >= seq_len - win)[None, :, None]
            keep = np.where(hard[:, None, None], window, True)
            base = np.where(keep, base, 0.0)
            base = np.where(hard[:, None, None], 0.6 * base, base)
            noise_scale = np.full((n, 1, 1), 0.25, np.float32)
        else:
            noise_scale = local.uniform(0.2, 1.0, (n, 1, 1)).astype(np.float32)
        noise = local.normal(0, 1, (n, seq_len, feature_dim)).astype(np.float32)
        return (base + noise_scale * noise).astype(np.float32), y

    return make(train_size, 1), make(test_size, 2)


def find_data_dir(explicit: Optional[str] = None) -> Optional[str]:
    """Resolve the dataset root: explicit arg → $MERCURY_TPU_DATA → defaults."""
    candidates = []
    if explicit:
        candidates.append(explicit)
    env = os.environ.get("MERCURY_TPU_DATA")
    if env:
        candidates.append(env)
    candidates.extend(_SEARCH_DIRS)
    for c in candidates:
        if os.path.isdir(c):
            return c
    return None


def load_dataset(
    name: str = "cifar10",
    data_dir: Optional[str] = None,
    allow_synthetic: bool = True,
    synthetic_train_size: int = 5000,
    synthetic_test_size: int = 1000,
    seed: int = 0,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray], dict]:
    """Load ``(x_train, y_train), (x_test, y_test), info``.

    Images are uint8 NHWC; labels int32. ``info`` records num_classes,
    normalization stats, and whether data is synthetic.
    """
    name = name.lower()
    # Synthetic variants: (num_classes, difficulty, label_noise).
    # - synthetic: the easy smoke/CI stand-in;
    # - synthetic_hard: the sample-efficiency benchmark task — 20 classes,
    #   heavy-tailed per-sample difficulty (lognormal noise scale: a long
    #   tail of hard samples), 5% train-label noise, clean test labels;
    #   built to DISCRIMINATE sampling strategies (easy tasks saturate
    #   before any strategy differentiates — round 1's failure mode);
    # - synthetic_tail: boundary probe — same heavy tail, CLEAN labels,
    #   isolating whether label noise is what erases the IS advantage.
    _SYNTH = {
        "synthetic": (10, "uniform", 0.0),
        "synthetic_tail": (20, "heavy_tail", 0.0),
        "synthetic_hard": (20, "heavy_tail", 0.05),
    }
    if name in _SYNTH:
        num_classes, difficulty, label_noise = _SYNTH[name]
        train, test = synthetic_cifar(
            num_classes, synthetic_train_size, synthetic_test_size,
            seed=seed, difficulty=difficulty, label_noise=label_noise,
        )
        return train, test, {
            "num_classes": num_classes,
            "mean": CIFAR10_MEAN,
            "std": CIFAR10_STD,
            "synthetic": True,
        }

    if name in ("digits", "digits_imb", "digits_seq", "digits_seq_imb"):
        # The one REAL image dataset guaranteed on disk in a sealed
        # environment: scikit-learn's bundled handwritten-digits set
        # (UCI ML Optical Recognition of Handwritten Digits — 1,797 real
        # 8×8 grayscale scans, shipped inside the sklearn wheel, no
        # download). Small, but its signal is real: the north-star
        # time-to-target comparison (BASELINE.md rows 1-3) runs on it
        # with honest provenance when CIFAR bytes are absent. Upscaled
        # to 32×32×3 so the CIFAR-shaped models/augmentation apply
        # unchanged; split 80/20 deterministically in ``seed``.
        #
        # ``digits_imb``: the class-IMBALANCED variant built for the
        # round-4 flagship experiment — the regime the reference's paper
        # actually claims (informative hard examples): classes 5–9 keep
        # only 10% of their TRAIN samples (≈14 each), the test split
        # stays balanced. Uniform sampling sees a rare-class example in
        # ~5% of draws; loss-proportional selection re-weights toward
        # them exactly when they are hard-but-learnable. Measure with
        # per-class accuracy over the rare classes
        # (``Trainer.per_class_accuracy``).
        #
        # ``digits_seq`` / ``digits_seq_imb``: the SAME real scans as
        # FOUND sequence data (round-4 verdict: stress the win regime on
        # a task the builder didn't shape). Each 8×8 scan becomes its
        # raw length-64 scanline sequence ``[64, 1]`` — no windowing, no
        # amplitude tuning, no constructed minority structure; whatever
        # makes a sample hard for a sequence model is a property of the
        # real handwriting. ``_imb`` applies the identical classes-5–9 ×
        # 10% protocol established for the image variant (a rarity
        # mechanism fixed BEFORE this experiment, not tuned for it).
        from sklearn.datasets import load_digits as _load_digits

        d = _load_digits()
        as_seq = name.startswith("digits_seq")
        imbalanced = name.endswith("_imb")
        labels = d.target.astype(np.int32)
        rng_d = np.random.default_rng(seed)
        order = rng_d.permutation(len(labels))
        n_test = len(labels) // 5
        test_idx, train_idx = order[:n_test], order[n_test:]
        if imbalanced:
            ytr = labels[train_idx]
            keep = np.ones(len(train_idx), bool)
            for c in range(5, 10):
                idx = np.where(ytr == c)[0]
                n_keep = max(int(round(0.1 * len(idx))), 8)
                keep[rng_d.permutation(idx)[n_keep:]] = False
            train_idx = train_idx[keep]
        if as_seq:
            # Raw scanline sequences in [0, 1]; standardized by the
            # train split's scalar stats via the normal pipeline path
            # (float sequences skip the /255 branch).
            x = (d.images / d.images.max()).astype(np.float32)
            x = x.reshape(len(x), 64, 1)
            mean = x[train_idx].mean(keepdims=False).reshape(1)
            std = np.maximum(x[train_idx].std(), 1e-3).reshape(1)
            mean = mean.astype(np.float32)
            std = std.astype(np.float32)
            train = (x[train_idx], labels[train_idx])
            test = (x[test_idx], labels[test_idx])
        else:
            imgs = (d.images / d.images.max() * 255.0).astype(np.uint8)
            imgs = np.repeat(np.repeat(imgs, 4, axis=1), 4, axis=2)  # 8→32
            imgs = np.repeat(imgs[..., None], 3, axis=-1)            # gray→RGB
            train = (imgs[train_idx], labels[train_idx])
            test = (imgs[test_idx], labels[test_idx])
            flat = imgs[train_idx].astype(np.float32) / 255.0
            mean = flat.mean(axis=(0, 1, 2)).astype(np.float32)
            std = np.maximum(flat.std(axis=(0, 1, 2)), 1e-3).astype(np.float32)
        return train, test, {
            "num_classes": 10,
            "mean": mean,
            "std": std,
            "synthetic": False,
        }

    if name in ("synthetic_seq", "synthetic_seq_hard"):
        num_classes = 10
        train, test = synthetic_sequences(
            num_classes, synthetic_train_size, synthetic_test_size, seed=seed,
            difficulty=("hard_minority" if name == "synthetic_seq_hard"
                        else "uniform"),
        )
        # Sequences are already float; normalization is identity.
        return train, test, {
            "num_classes": num_classes,
            "mean": np.zeros((1,), np.float32),
            "std": np.ones((1,), np.float32),
            "synthetic": True,
        }

    if name not in ("cifar10", "cifar100"):
        raise ValueError(f"unknown dataset {name!r}")
    num_classes = 10 if name == "cifar10" else 100
    mean, std = (CIFAR10_MEAN, CIFAR10_STD) if name == "cifar10" else (CIFAR100_MEAN, CIFAR100_STD)

    root = find_data_dir(data_dir)
    loaded = None
    if root is not None:
        loader = _try_load_cifar10 if name == "cifar10" else _try_load_cifar100
        loaded = loader(root)
    if loaded is not None:
        train, test = loaded
        return train, test, {"num_classes": num_classes, "mean": mean, "std": std, "synthetic": False}

    if not allow_synthetic:
        raise FileNotFoundError(
            f"no {name} data found under {root or _SEARCH_DIRS}; set MERCURY_TPU_DATA"
        )
    warnings.warn(
        f"no {name} data found on disk — substituting the deterministic "
        "synthetic dataset. Set MERCURY_TPU_DATA (or pass data_dir) to train "
        "on real data, or allow_synthetic=False to make this an error.",
        stacklevel=2,
    )
    train, test = synthetic_cifar(
        num_classes, synthetic_train_size, synthetic_test_size, seed=seed
    )
    return train, test, {"num_classes": num_classes, "mean": mean, "std": std, "synthetic": True}
