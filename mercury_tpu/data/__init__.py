from mercury_tpu.data.cifar import load_dataset  # noqa: F401
from mercury_tpu.data.imagefolder import load_image_folder, pil_to_numpy  # noqa: F401
from mercury_tpu.data.partition import (  # noqa: F401
    load_partition,
    partition_data,
    record_class_histograms,
    save_partition,
)
from mercury_tpu.data.transforms import (  # noqa: F401
    augment_batch_iid,
    eval_transform_iid,
    truncate_channels,
)
from mercury_tpu.data.pipeline import (  # noqa: F401
    Batch,
    ShardedDataset,
    augment_batch,
    make_sharded_dataset,
    normalize_images,
)
