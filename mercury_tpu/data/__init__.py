from mercury_tpu.data.cifar import load_dataset  # noqa: F401
from mercury_tpu.data.partition import (  # noqa: F401
    partition_data,
    record_class_histograms,
)
from mercury_tpu.data.pipeline import (  # noqa: F401
    Batch,
    ShardedDataset,
    augment_batch,
    make_sharded_dataset,
    normalize_images,
)
