"""Non-IID data partitioning.

Capability parity with ``partition_data`` (``cifar10/data_loader.py:126-173``)
— the FedML-style per-class Dirichlet partitioner with the same sharp-edged
semantics the reference has (they affect convergence comparability,
SURVEY.md §7 "hard parts"):

- ``homo``: random equal split (``data_loader.py:132-136``).
- ``hetero``: for every class, draw Dirichlet(α) proportions over workers,
  **mask workers already holding ≥ N/n samples** (the ``p·(len(idx_j)<N/n)``
  capacity mask, ``:153``), renormalize, split the class's shuffled indices at
  the cumulative proportions — and **retry the entire assignment until every
  shard has ≥ min_size (10) samples** (``:145``).

Also provides the per-client class-histogram logging of
``record_net_data_stats`` (``:46-54``).
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np


def partition_homo(n_samples: int, n_workers: int, rng: np.random.Generator) -> List[np.ndarray]:
    """Random equal split (``cifar10/data_loader.py:132-136``)."""
    idxs = rng.permutation(n_samples)
    return [np.sort(s).astype(np.int64) for s in np.array_split(idxs, n_workers)]


def partition_dirichlet(
    labels: np.ndarray,
    n_workers: int,
    alpha: float,
    rng: np.random.Generator,
    min_size: int = 10,
    max_retries: int = 1000,
) -> List[np.ndarray]:
    """Per-class Dirichlet(α) partition with capacity masking and a
    retry-until-balanced loop (``cifar10/data_loader.py:138-161``)."""
    labels = np.asarray(labels)
    n = labels.shape[0]
    classes = np.unique(labels)
    target = n / n_workers  # capacity threshold N/n (data_loader.py:153)

    for _ in range(max_retries):
        shards: List[List[np.ndarray]] = [[] for _ in range(n_workers)]
        sizes = np.zeros(n_workers, dtype=np.int64)
        for k in classes:
            idx_k = np.flatnonzero(labels == k)
            rng.shuffle(idx_k)
            proportions = rng.dirichlet(np.repeat(alpha, n_workers))
            # Capacity mask: workers already at/above the fair share get 0
            # of this class (data_loader.py:153).
            proportions = proportions * (sizes < target)
            s = proportions.sum()
            if s == 0:  # all workers full for this class — spread evenly
                proportions = np.full(n_workers, 1.0 / n_workers)
            else:
                proportions = proportions / s
            cuts = (np.cumsum(proportions) * len(idx_k)).astype(int)[:-1]
            for w, part in enumerate(np.split(idx_k, cuts)):
                shards[w].append(part)
                sizes[w] += len(part)
        if sizes.min() >= min_size:
            return [np.sort(np.concatenate(s)).astype(np.int64) for s in shards]
    raise RuntimeError(
        f"Dirichlet partition failed to reach min shard size {min_size} "
        f"after {max_retries} retries (α={alpha}, workers={n_workers})"
    )


def partition_data(
    labels: np.ndarray,
    n_workers: int,
    mode: str = "hetero",
    alpha: float = 0.5,
    seed: int = 102,
    min_size: int = 10,
    partition_file: str = None,
) -> List[np.ndarray]:
    """Dispatch matching ``partition_data`` (``cifar10/data_loader.py:126``).

    ``mode``: ``"homo"`` (IID, ``:132-136``), ``"hetero"`` (Dirichlet
    non-IID, ``:138-161``), or ``"hetero-fix"`` (pre-computed partition
    from ``partition_file``, ``:163-169``). Returns a list of sorted
    global-index arrays, one per worker; generated shards are disjoint and
    cover the dataset.
    """
    rng = np.random.default_rng(seed)
    n = int(np.asarray(labels).shape[0])
    if mode == "homo":
        return partition_homo(n, n_workers, rng)
    if mode == "hetero":
        return partition_dirichlet(labels, n_workers, alpha, rng, min_size=min_size)
    if mode == "hetero-fix":
        if partition_file is None:
            raise ValueError("mode='hetero-fix' requires partition_file")
        shards = load_partition(partition_file)
        if len(shards) != n_workers:
            raise ValueError(
                f"partition file has {len(shards)} shards, need {n_workers}"
            )
        return shards
    raise ValueError(
        f"unknown partition mode {mode!r} (use 'homo', 'hetero', or 'hetero-fix')"
    )


def save_partition(path: str, shards: List[np.ndarray]) -> None:
    """Persist a partition to an ``.npz`` for the fixed-partition workflow
    (the reference's ``hetero-fix`` mode reads pre-computed per-client
    index maps from files, ``cifar10/data_loader.py:16-43,163-169`` — the
    files themselves are absent from the repo, so the format here is our
    own, with a writer so it is actually usable)."""
    np.savez(path, **{f"worker_{i}": np.asarray(s, np.int64) for i, s in enumerate(shards)})


def load_partition(path: str) -> List[np.ndarray]:
    """Inverse of :func:`save_partition` (``hetero-fix`` read path,
    ``cifar10/data_loader.py:163-169``)."""
    with np.load(path) as data:
        keys = sorted(data.files, key=lambda k: int(k.split("_")[1]))
        return [data[k].astype(np.int64) for k in keys]


def record_class_histograms(
    labels: np.ndarray, shards: List[np.ndarray]
) -> List[Dict[int, int]]:
    """Per-worker class histograms (``cifar10/data_loader.py:46-54``)."""
    out = []
    for shard in shards:
        vals, counts = np.unique(np.asarray(labels)[shard], return_counts=True)
        out.append({int(v): int(c) for v, c in zip(vals, counts)})
    return out
