"""On-device data pipeline: index-carrying batches, jit'd augmentation, and
per-worker presampling streams.

Replaces the reference's loader stack — ``get_dataloader_CIFAR10``
(``cifar10/data_loader.py:177-211``), the index-carrying datasets
(``cifar10/datasets.py:39-96``, ``util.py:240-273``), the wrapping
presampling iterator ``Trainer.get_next`` (``pytorch_collab.py:74-82``) and
the transforms ``_data_transforms_cifar10``
(``cifar10/data_loader.py:79-109``) — with a TPU-first design: the whole
dataset lives in device memory as arrays; "loading" a batch is a gather by
index inside the jitted step; augmentation is pure ``jax.random`` ops fused
into the same XLA program. No host↔device transfer per step.

The index-carrying contract (``(index, image, target)``,
``cifar10/datasets.py:93``) becomes the :class:`Batch` NamedTuple whose
``index`` field travels with every batch so importance scores attribute to
global sample ids.
"""

from __future__ import annotations

import dataclasses
from typing import List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Batch(NamedTuple):
    """Index-carrying batch (mirror of the ``(index, img, target)`` tuple
    contract, ``cifar10/datasets.py:77-93``)."""

    index: jax.Array  # [B] int32 — global sample ids
    image: jax.Array  # [B, H, W, C] float
    label: jax.Array  # [B] int32


class ShardStream(NamedTuple):
    """Carried jit state for one worker's wrapping, shuffled presampling
    stream (functional replacement of ``Trainer.get_next``'s infinite
    iterator, ``pytorch_collab.py:74-82``)."""

    perm: jax.Array    # [L] int32 — current epoch permutation of shard slots
    cursor: jax.Array  # [] int32 — next unread slot


def normalize_images(images: jax.Array, mean: np.ndarray, std: np.ndarray) -> jax.Array:
    """uint8 NHWC → normalized float (``cifar10/data_loader.py:83-96``:
    ``ToTensor`` + ``Normalize(mean, std)``). Float inputs (e.g. feature
    sequences ``[N, T, F]``) skip the /255 scaling; mean/std broadcast over
    the trailing axis."""
    if images.dtype == jnp.uint8:
        x = images.astype(jnp.float32) / 255.0
    else:
        x = images.astype(jnp.float32)
    return (x - jnp.asarray(mean)) / jnp.asarray(std)


def _take_crops(images: jax.Array, oy: jax.Array, ox: jax.Array, out_h: int, out_w: int) -> jax.Array:
    """Crop every image ``i`` of ``[N, H, W, C]`` at its own offset
    ``(oy[i], ox[i])`` with two batched ``take_along_axis`` gathers — the
    whole batch crops in two vectorized HBM reads instead of N per-image
    dynamic slices (which lower to N serialized gathers on TPU)."""
    idx_y = oy[:, None] + jnp.arange(out_h)[None, :]              # [N, out_h]
    idx_x = ox[:, None] + jnp.arange(out_w)[None, :]              # [N, out_w]
    rows = jnp.take_along_axis(images, idx_y[:, :, None, None], axis=1)
    return jnp.take_along_axis(rows, idx_x[:, None, :, None], axis=2)


def random_crop_batch(key: jax.Array, images: jax.Array, pad: int) -> jax.Array:
    """Zero-pad by ``pad`` then crop back to the original size at a random
    per-image offset (``transforms.RandomCrop(32, padding=4)``,
    ``cifar10/data_loader.py:85``), fully batched."""
    n, h, w, _ = images.shape
    padded = jnp.pad(images, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    off = jax.random.randint(key, (n, 2), 0, 2 * pad + 1)
    return _take_crops(padded, off[:, 0], off[:, 1], h, w)


def random_crop_to_batch(key: jax.Array, images: jax.Array, out: int) -> jax.Array:
    """Random crop of ``[N, H, W, C]`` down to ``out×out`` with no padding
    (the IID path crops a larger resized image, ``exp_dataset.py:26-27``)."""
    n, h, w, _ = images.shape
    oy = jax.random.randint(key, (n,), 0, h - out + 1)
    # graftlint: disable=GL101 -- fold_in(key, 1) is a stream disjoint from the raw key; raw+folded pairing is deliberate to keep recorded augmentation trajectories stable
    ox = jax.random.randint(jax.random.fold_in(key, 1), (n,), 0, w - out + 1)
    return _take_crops(images, oy, ox, out, out)


def hflip_batch(key: jax.Array, images: jax.Array) -> jax.Array:
    """Per-image random horizontal flip, p=0.5
    (``cifar10/data_loader.py:86``)."""
    flip = jax.random.bernoulli(key, shape=(images.shape[0],))
    return jnp.where(flip[:, None, None, None], images[:, :, ::-1, :], images)


def cutout_batch(key: jax.Array, images: jax.Array, length: int) -> jax.Array:
    """Square cutout mask (``Cutout``, ``cifar10/data_loader.py:57-76`` —
    defined in the reference but not wired into its transform; exposed here
    behind a flag). Centers are uniform over the image; squares clip at the
    borders, exactly like the reference's ``np.clip`` logic."""
    n, h, w, _ = images.shape
    cy = jax.random.randint(key, (n,), 0, h)
    # graftlint: disable=GL101 -- fold_in(key, 1) is a stream disjoint from the raw key; raw+folded pairing is deliberate to keep recorded augmentation trajectories stable
    cx = jax.random.randint(jax.random.fold_in(key, 1), (n,), 0, w)
    ys = jnp.arange(h)[None, :, None]
    xs = jnp.arange(w)[None, None, :]
    half = length // 2
    cy, cx = cy[:, None, None], cx[:, None, None]
    mask = (ys >= cy - half) & (ys < cy + half) & (xs >= cx - half) & (xs < cx + half)
    return jnp.where(mask[..., None], 0.0, images)


def augment_batch(
    key: jax.Array,
    images: jax.Array,
    pad: int = 4,
    use_cutout: bool = False,
    cutout_length: int = 16,
) -> jax.Array:
    """Jit'd train-time augmentation: random crop (pad 4) + horizontal flip
    [+ optional cutout] — the live non-IID pipeline of
    ``_data_transforms_cifar10`` (``cifar10/data_loader.py:83-96``), run
    on-device as whole-batch ops (3 RNG draws + 2 batched gathers for the
    full pool, no per-image key splitting)."""
    k_crop, k_flip, k_cut = jax.random.split(key, 3)
    out = random_crop_batch(k_crop, images, pad)
    out = hflip_batch(k_flip, out)
    if use_cutout:
        out = cutout_batch(k_cut, out, cutout_length)
    return out


def next_pool(
    stream: ShardStream,
    key: jax.Array,
    pool_size: int,
) -> Tuple[ShardStream, jax.Array]:
    """Pull the next ``pool_size`` slot positions from a wrapping shuffled
    stream.

    Functional mirror of the reference's presampling iterator: a shuffled
    DataLoader consumed batch-by-batch, recreated (reshuffled) when
    exhausted (``Trainer.get_next``, ``pytorch_collab.py:74-82``). Returns
    the advanced stream state and ``pool_size`` slot indices into the shard.
    """
    length = stream.perm.shape[0]
    needs_reshuffle = stream.cursor + pool_size > length
    perm = jax.lax.cond(
        needs_reshuffle,
        lambda: jax.random.permutation(key, length).astype(stream.perm.dtype),
        lambda: stream.perm,
    )
    cursor = jnp.where(needs_reshuffle, 0, stream.cursor)
    slots = jax.lax.dynamic_slice(perm, (cursor,), (pool_size,))
    return ShardStream(perm=perm, cursor=cursor + pool_size), slots


@dataclasses.dataclass
class ShardedDataset:
    """Device-resident dataset with per-worker shards.

    The reference ships each fork a pickled per-worker presampling loader
    plus shared global loaders (``pytorch_collab.py:282-289``). Here, in
    single-controller SPMD, the full train/test arrays are device-resident
    (replicated) and each worker's shard is a row of a ``[W, L]`` index
    matrix — shards of unequal length (Dirichlet!) are cyclically tiled to
    the max length ``L`` so shapes are static for XLA.
    """

    x_train: jax.Array        # [N, H, W, C] uint8 (un-normalized; normalize in-step)
    y_train: jax.Array        # [N] int32
    x_test: jax.Array         # [Nt, H, W, C] uint8
    y_test: jax.Array         # [Nt] int32
    shard_indices: jax.Array  # [W, L] int32 — global ids, cyclically padded
    shard_sizes: jax.Array    # [W] int32 — true (unpadded) shard lengths
    mean: np.ndarray
    std: np.ndarray
    num_classes: int
    synthetic: bool = True    # False when loaded from real on-disk bytes

    @property
    def n_train(self) -> int:
        return int(self.x_train.shape[0])

    @property
    def n_test(self) -> int:
        return int(self.x_test.shape[0])

    @property
    def n_workers(self) -> int:
        return int(self.shard_indices.shape[0])

    def gather_batch(self, indices: jax.Array, train: bool = True) -> Batch:
        """Gather a normalized batch by global index (the in-graph analogue
        of dataset ``__getitem__`` + collate)."""
        x = self.x_train if train else self.x_test
        y = self.y_train if train else self.y_test
        images = normalize_images(x[indices], self.mean, self.std)
        return Batch(index=indices.astype(jnp.int32), image=images, label=y[indices])


def make_sharded_dataset(
    train: Tuple[np.ndarray, np.ndarray],
    test: Tuple[np.ndarray, np.ndarray],
    shards: List[np.ndarray],
    mean: np.ndarray,
    std: np.ndarray,
    num_classes: int,
    synthetic: bool = True,
    device_resident: bool = True,
) -> ShardedDataset:
    """Build a :class:`ShardedDataset` from host arrays + partition output.

    Cyclic tiling of short shards keeps shapes static without biasing much:
    each sample of a short shard simply appears ⌈L/len⌉ times in its row —
    the same effect as the reference's wrapping presampling iterator
    re-traversing a short shard more often per global step.
    """
    x_train, y_train = train
    x_test, y_test = test
    max_len = max(len(s) for s in shards)
    rows = []
    for s in shards:
        reps = int(np.ceil(max_len / len(s)))
        rows.append(np.tile(s, reps)[:max_len])
    shard_indices = np.stack(rows).astype(np.int32)
    shard_sizes = np.array([len(s) for s in shards], np.int32)
    # device_resident=False (data_placement="sharded"): the full train
    # arrays stay host-side — the step consumes materialized per-worker
    # shard arrays instead, and eval gathers from the host copy.
    conv_x = jnp.asarray if device_resident else np.asarray
    conv_y = ((lambda a: jnp.asarray(a, jnp.int32)) if device_resident
              else (lambda a: np.asarray(a, np.int32)))
    return ShardedDataset(
        x_train=conv_x(x_train),
        y_train=conv_y(y_train),
        x_test=jnp.asarray(x_test),
        y_test=jnp.asarray(y_test, jnp.int32),
        shard_indices=jnp.asarray(shard_indices),
        shard_sizes=jnp.asarray(shard_sizes),
        mean=mean,
        std=std,
        num_classes=num_classes,
        synthetic=synthetic,
    )


def init_shard_streams(key: jax.Array, n_workers: int, shard_len: int) -> ShardStream:
    """Initial per-worker stream state, stacked on a leading worker axis
    (sharded over the mesh in the SPMD step)."""
    keys = jax.random.split(key, n_workers)
    perms = jax.vmap(lambda k: jax.random.permutation(k, shard_len).astype(jnp.int32))(keys)
    return ShardStream(perm=perms, cursor=jnp.zeros((n_workers,), jnp.int32))


def eval_batches(
    n: int, batch_size: int
) -> List[Tuple[np.ndarray, int]]:
    """Host-side fixed-size eval batching plan: list of (index array, valid
    count); the last batch wraps (padding samples are masked out by the
    caller using the valid count). Mirrors ``Trainer.evaluate``'s full-pass
    semantics (``pytorch_collab.py:201-234``) with static shapes."""
    out = []
    for start in range(0, n, batch_size):
        end = min(start + batch_size, n)
        idx = np.arange(start, start + batch_size) % n
        out.append((idx.astype(np.int32), end - start))
    return out
