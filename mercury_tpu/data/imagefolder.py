"""Directory-of-images ingest with the index-carrying contract.

Capability parity with ``SampleImageFolder`` (``util.py:162-181`` — an
``ImageFolder`` whose ``__getitem__`` returns ``(index, sample, target)``
so non-CIFAR image datasets plug into the importance sampler) and the image
loading backends ``pil_loader``/``default_loader``
(``cifar10/datasets.py:15-36``) and the ``ToNumpy`` transform
(``util.py:73-91``).

TPU-first shape: instead of a lazy per-item loader feeding host worker
processes, the whole folder is decoded once into device-ready arrays
(images resized to a uniform square), after which batching is the same
in-graph gather as CIFAR — the index column is implicit in array order.
PIL is an optional dependency; importing this module without it raises
only when used.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".pgm", ".tif",
                  ".tiff", ".webp")


def pil_to_numpy(img) -> np.ndarray:
    """PIL image → HWC uint8 array (``ToNumpy``, ``util.py:73-91``)."""
    img = img.convert("RGB")
    return np.asarray(img, dtype=np.uint8)


def _load_image(path: str, size: Optional[int]) -> np.ndarray:
    from PIL import Image  # optional dependency (pil_loader, datasets.py:22-27)

    with Image.open(path) as img:
        if size is not None:
            img = img.resize((size, size))
        return pil_to_numpy(img)


def find_classes(root: str) -> List[str]:
    """Sorted class-subdirectory names (ImageFolder convention)."""
    return sorted(
        d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d))
    )


def list_image_folder(root: str) -> Tuple[List[str], np.ndarray, List[str]]:
    """Enumerate ``root/<class>/<image>`` WITHOUT decoding: ``(paths,
    labels, class_names)`` in the same deterministic order
    :func:`load_image_folder` decodes in (classes sorted, files sorted
    within class) — so a path index here IS the global sample index the
    sampler attributes scores to. The lazy half of the eager loader,
    shared with ``data/stream.py``'s ``ImageFolderSource`` (which decodes
    only the rows a step actually selects)."""
    classes = find_classes(root)
    if not classes:
        raise FileNotFoundError(f"no class subdirectories under {root!r}")
    paths, labels = [], []
    for label, cls in enumerate(classes):
        cdir = os.path.join(root, cls)
        for fname in sorted(os.listdir(cdir)):
            if os.path.splitext(fname)[1].lower() in IMG_EXTENSIONS:
                paths.append(os.path.join(cdir, fname))
                labels.append(label)
    if not paths:
        raise FileNotFoundError(f"no images with {IMG_EXTENSIONS} under {root!r}")
    return paths, np.asarray(labels, np.int32), classes


def load_image_folder(
    root: str, image_size: Optional[int] = 32
) -> Tuple[np.ndarray, np.ndarray, List[str]]:
    """Decode ``root/<class>/<image>`` into ``(images, labels, class_names)``.

    Images are uint8 NHWC (resized to ``image_size`` square when given);
    labels are int32 class indices; sample order (= the global index the
    sampler attributes scores to) is deterministic: classes sorted, files
    sorted within class — the stable analogue of the reference's
    index-carrying ``(index, sample, target)`` tuples (``util.py:165-181``).
    """
    paths, labels, classes = list_image_folder(root)
    images = [_load_image(p, image_size) for p in paths]
    return np.stack(images), labels, classes


def load_imagefolder_dataset(
    root: str, image_size: Optional[int] = 32, test_fraction: float = 0.1,
    seed: int = 0,
) -> Tuple[Tuple[np.ndarray, np.ndarray], Tuple[np.ndarray, np.ndarray], dict]:
    """Full-dataset ingest for training: ``(train, test, info)``.

    Layout: ``root/train/<class>/...`` and ``root/test/<class>/...``
    (torchvision convention). With no ``train``/``test`` subdirs,
    ``root/<class>/...`` is split ``1−test_fraction``/``test_fraction``
    with a seeded shuffle. Normalization stats are computed from the train
    split. This is what turns :func:`load_image_folder` (the
    ``SampleImageFolder`` parity shim) into a first-class Trainer dataset:
    ``TrainConfig(dataset="imagefolder", data_dir=root)``.
    """
    train_dir = os.path.join(root, "train")
    test_dir = os.path.join(root, "test")
    if os.path.isdir(train_dir) and os.path.isdir(test_dir):
        x_tr, y_tr, classes = load_image_folder(train_dir, image_size)
        x_te, y_te, test_classes = load_image_folder(test_dir, image_size)
        if test_classes != classes:
            raise ValueError(
                f"train/test class mismatch: {classes} vs {test_classes}"
            )
    else:
        x, y, classes = load_image_folder(root, image_size)
        rng = np.random.default_rng(seed)
        perm = rng.permutation(len(x))
        n_test = max(int(len(x) * test_fraction), 1)
        te, tr = perm[:n_test], perm[n_test:]
        x_tr, y_tr, x_te, y_te = x[tr], y[tr], x[te], y[te]
    mean = (x_tr.astype(np.float32) / 255.0).mean(axis=(0, 1, 2))
    std = (x_tr.astype(np.float32) / 255.0).std(axis=(0, 1, 2)) + 1e-6
    return (x_tr, y_tr), (x_te, y_te), {
        "num_classes": len(classes),
        "classes": classes,
        "mean": mean,
        "std": std,
        "synthetic": False,
    }
