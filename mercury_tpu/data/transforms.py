"""Extended on-device transforms: the reference's IID-path augmentation and
eval transforms, plus channel truncation.

Capability parity with ``load_cifar10``'s IID pipeline (``exp_dataset.py:
23-77``): train transform ``Resize(35) → RandomCrop(32) → HFlip →
RandomAffine(±10°, scale 0.9-1.1)`` (``:25-32``) and test transform
``Resize(33) → RandomCrop(32)`` (``:63-68``); and with
``CIFAR10_truncated.truncate_channel`` (``cifar10/datasets.py:71-75``) —
zeroing the G/B channels of selected samples.

All transforms are pure ``jax.random`` whole-batch functions, so they
fuse into the train step like the non-IID pipeline in
``mercury_tpu.data.pipeline``. The affine warp is inverse-mapped bilinear
resampling as batched gathers — the array-native equivalent of
torchvision's ``RandomAffine``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from mercury_tpu.data.pipeline import hflip_batch, random_crop_to_batch


def resize_batch(images: jax.Array, size: int) -> jax.Array:
    """Bilinear resize to ``size×size`` (``transforms.Resize``)."""
    n, _, _, c = images.shape
    return jax.image.resize(images, (n, size, size, c), method="bilinear")


@functools.lru_cache(maxsize=None)
def _centered_grid(h: int, w: int):
    """Host-side center-relative f32 meshgrid for ``affine_batch``, cached
    per (h, w): rebuilding it with ``jnp`` on every call re-emitted an
    iota+broadcast chain into each retrace. As numpy constants they embed
    once per compiled program and cost nothing across retraces."""
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    ys, xs = np.meshgrid(np.arange(h, dtype=np.float32),
                         np.arange(w, dtype=np.float32), indexing="ij")
    return ys - cy, xs - cx


def affine_batch(
    key: jax.Array,
    images: jax.Array,
    max_rotate_deg: float,
    scale_min: float,
    scale_max: float,
) -> jax.Array:
    """Per-image random rotation + isotropic scale about the image center
    (``RandomAffine(10, scale=(0.9, 1.1))``, ``exp_dataset.py:29-31``),
    fully batched: 2 RNG draws for the whole batch, inverse-mapped bilinear
    resampling as four batched gathers with edge clamping (equivalent to
    ``map_coordinates(order=1, mode="nearest")`` per image, without N
    per-image key splits / warps)."""
    n, h, w, c = images.shape
    k1, k2 = jax.random.split(key)
    theta = jnp.deg2rad(
        jax.random.uniform(k1, (n,), minval=-max_rotate_deg, maxval=max_rotate_deg)
    )
    scale = jax.random.uniform(k2, (n,), minval=scale_min, maxval=scale_max)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    yc_np, xc_np = _centered_grid(h, w)
    yc, xc = yc_np[None], xc_np[None]                    # [1, h, w]
    # Inverse map: rotate by -θ, scale by 1/s.
    cos_t = jnp.cos(theta)[:, None, None]
    sin_t = jnp.sin(theta)[:, None, None]
    inv = (1.0 / scale)[:, None, None]
    src_y = (cos_t * yc + sin_t * xc) * inv + cy          # [n, h, w]
    src_x = (-sin_t * yc + cos_t * xc) * inv + cx

    y0 = jnp.floor(src_y)
    x0 = jnp.floor(src_x)
    wy = (src_y - y0)[..., None]
    wx = (src_x - x0)[..., None]
    # Clamp each neighbor independently from the UNclamped floor: for a
    # far-out-of-bounds coordinate both neighbors collapse to the same edge
    # row/col (pure edge replication, no spurious blend) — matching
    # map_coordinates(mode="nearest").
    y0i = jnp.clip(y0.astype(jnp.int32), 0, h - 1)
    y1i = jnp.clip(y0.astype(jnp.int32) + 1, 0, h - 1)
    x0i = jnp.clip(x0.astype(jnp.int32), 0, w - 1)
    x1i = jnp.clip(x0.astype(jnp.int32) + 1, 0, w - 1)

    flat = images.reshape(n, h * w, c)

    def sample(yi, xi):
        idx = (yi * w + xi).reshape(n, h * w, 1)
        return jnp.take_along_axis(flat, idx, axis=1).reshape(n, h, w, c)

    return (
        (1 - wy) * (1 - wx) * sample(y0i, x0i)
        + (1 - wy) * wx * sample(y0i, x1i)
        + wy * (1 - wx) * sample(y1i, x0i)
        + wy * wx * sample(y1i, x1i)
    )


def augment_batch_iid(
    key: jax.Array,
    images: jax.Array,
    resize_to: int = 35,
    crop_to: int = 32,
    max_rotate_deg: float = 10.0,
    scale_range: tuple = (0.9, 1.1),
) -> jax.Array:
    """The IID-path train augmentation (``exp_dataset.py:25-32``):
    resize → random crop → hflip → random affine."""
    k_crop, k_flip, k_aff = jax.random.split(key, 3)
    out = resize_batch(images, resize_to)
    out = random_crop_to_batch(k_crop, out, crop_to)
    out = hflip_batch(k_flip, out)
    return affine_batch(k_aff, out, max_rotate_deg,
                        scale_range[0], scale_range[1])


def eval_transform_iid(
    key: jax.Array, images: jax.Array, resize_to: int = 33, crop_to: int = 32
) -> jax.Array:
    """The IID-path test transform (``exp_dataset.py:63-68``):
    resize(33) → random crop(32)."""
    out = resize_batch(images, resize_to)
    return random_crop_to_batch(key, out, crop_to)


def truncate_channels(
    images: jax.Array, sample_mask: jax.Array, keep_channel: int = 0
) -> jax.Array:
    """Zero all but ``keep_channel`` for samples where ``sample_mask`` is
    True (``CIFAR10_truncated.truncate_channel``,
    ``cifar10/datasets.py:71-75`` — the reference zeroes G and B, keeping
    R, for a selected index range)."""
    c = images.shape[-1]
    ch_keep = (jnp.arange(c) == keep_channel)
    zeroed = images * ch_keep.astype(images.dtype)
    return jnp.where(sample_mask[:, None, None, None], zeroed, images)
