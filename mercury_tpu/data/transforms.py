"""Extended on-device transforms: the reference's IID-path augmentation and
eval transforms, plus channel truncation.

Capability parity with ``load_cifar10``'s IID pipeline (``exp_dataset.py:
23-77``): train transform ``Resize(35) → RandomCrop(32) → HFlip →
RandomAffine(±10°, scale 0.9-1.1)`` (``:25-32``) and test transform
``Resize(33) → RandomCrop(32)`` (``:63-68``); and with
``CIFAR10_truncated.truncate_channel`` (``cifar10/datasets.py:71-75``) —
zeroing the G/B channels of selected samples.

All transforms are pure ``jax.random`` functions vmapped per sample, so
they fuse into the train step like the non-IID pipeline in
``mercury_tpu.data.pipeline``. The affine warp is inverse-mapped bilinear
resampling (``jax.scipy.ndimage.map_coordinates``) — the array-native
equivalent of torchvision's ``RandomAffine``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.ndimage import map_coordinates

from mercury_tpu.data.pipeline import hflip_batch, random_crop_to_batch


def resize_batch(images: jax.Array, size: int) -> jax.Array:
    """Bilinear resize to ``size×size`` (``transforms.Resize``)."""
    n, _, _, c = images.shape
    return jax.image.resize(images, (n, size, size, c), method="bilinear")


def _affine_one(
    key: jax.Array,
    img: jax.Array,
    max_rotate_deg: float,
    scale_min: float,
    scale_max: float,
) -> jax.Array:
    """Random rotation + isotropic scale about the image center
    (``RandomAffine(10, scale=(0.9, 1.1))``, ``exp_dataset.py:29-31``).

    Output pixel (y, x) samples the input at the inverse-transformed
    location; out-of-bounds reads clamp to the edge (order-1 bilinear).
    """
    h, w, _ = img.shape
    k1, k2 = jax.random.split(key)
    theta = jnp.deg2rad(
        jax.random.uniform(k1, (), minval=-max_rotate_deg, maxval=max_rotate_deg)
    )
    scale = jax.random.uniform(k2, (), minval=scale_min, maxval=scale_max)
    cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
    ys, xs = jnp.meshgrid(jnp.arange(h, dtype=jnp.float32),
                          jnp.arange(w, dtype=jnp.float32), indexing="ij")
    yc, xc = ys - cy, xs - cx
    # Inverse map: rotate by -θ, scale by 1/s.
    cos_t, sin_t = jnp.cos(theta), jnp.sin(theta)
    inv = 1.0 / scale
    src_y = (cos_t * yc + sin_t * xc) * inv + cy
    src_x = (-sin_t * yc + cos_t * xc) * inv + cx
    coords = jnp.stack([src_y, src_x])

    def warp_channel(ch):
        return map_coordinates(ch, coords, order=1, mode="nearest")

    return jnp.stack([warp_channel(img[..., c]) for c in range(img.shape[-1])],
                     axis=-1)


def augment_batch_iid(
    key: jax.Array,
    images: jax.Array,
    resize_to: int = 35,
    crop_to: int = 32,
    max_rotate_deg: float = 10.0,
    scale_range: tuple = (0.9, 1.1),
) -> jax.Array:
    """The IID-path train augmentation (``exp_dataset.py:25-32``):
    resize → random crop → hflip → random affine."""
    k_crop, k_flip, k_aff = jax.random.split(key, 3)
    n = images.shape[0]
    out = resize_batch(images, resize_to)
    out = random_crop_to_batch(k_crop, out, crop_to)
    out = hflip_batch(k_flip, out)
    out = jax.vmap(_affine_one, in_axes=(0, 0, None, None, None))(
        jax.random.split(k_aff, n), out, max_rotate_deg,
        scale_range[0], scale_range[1],
    )
    return out


def eval_transform_iid(
    key: jax.Array, images: jax.Array, resize_to: int = 33, crop_to: int = 32
) -> jax.Array:
    """The IID-path test transform (``exp_dataset.py:63-68``):
    resize(33) → random crop(32)."""
    out = resize_batch(images, resize_to)
    return random_crop_to_batch(key, out, crop_to)


def truncate_channels(
    images: jax.Array, sample_mask: jax.Array, keep_channel: int = 0
) -> jax.Array:
    """Zero all but ``keep_channel`` for samples where ``sample_mask`` is
    True (``CIFAR10_truncated.truncate_channel``,
    ``cifar10/datasets.py:71-75`` — the reference zeroes G and B, keeping
    R, for a selected index range)."""
    c = images.shape[-1]
    ch_keep = (jnp.arange(c) == keep_channel)
    zeroed = images * ch_keep.astype(images.dtype)
    return jnp.where(sample_mask[:, None, None, None], zeroed, images)
