"""Host-streaming input: row sources + the double-buffered prefetch pipeline.

``data_placement="host_stream"`` (``train/step.py::hs_body``) splits each
step's dataflow in two: the *selection* (which global rows to train on)
runs in-graph ``prefetch_depth`` steps ahead and is emitted as a small
int32 index output, while the *pixels* never enter the graph — a
background thread gathers the selected rows from a host-resident (or
memory-mapped / lazily-decoded) source into a pre-allocated staging
buffer and ``jax.device_put``\\ s them with the step's batch sharding
while the intervening steps execute. Only the score table (4·N bytes)
must live in HBM for importance sampling; the pixel array does not — the
sampling-plane/training-plane split of arXiv:1511.06481.

Two row sources implement the same two-method protocol (``row_shape`` /
``dtype`` attributes, ``gather(gidx, out)``):

- :class:`HostStreamSource` — rows of an in-memory uint8 array or an
  ``np.memmap`` (datasets larger than host RAM page in on demand);
- :class:`ImageFolderSource` — lazily-decoded ``root/<class>/<image>``
  rows (the streaming half of ``data/imagefolder.py``: only the rows a
  step actually selects are ever decoded).

Both optionally spread the gather/decode over ``decode_workers`` threads
(PIL decode and ``memmap`` page-ins release the GIL).

:class:`PrefetchPipeline` owns the worker thread and the bounded ready
queue; the Trainer drives it pop→step→push (``Trainer._host_stream_step``)
and folds :meth:`stats` (``data/stall_s``, ``data/queue_depth``,
``data/h2d_bytes``) into the step metrics.
"""

from __future__ import annotations

import queue
import threading
import time
import traceback
from typing import Any, Dict, Optional, Tuple

import numpy as np

from mercury_tpu.faults import InjectedFault
from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.data.stream")

__all__ = ["HostStreamSource", "ImageFolderSource", "PrefetchPipeline"]


class HostStreamSource:
    """Rows from a host-resident array the device never holds.

    ``x`` is any ``[N, ...]`` array-like with numpy fancy indexing — an
    in-memory ``np.ndarray`` or an ``np.memmap`` over a raw row file
    (uint8 pixel archives mmap directly; the OS pages rows in as the
    gather touches them, so the working set is the prefetch window, not
    the dataset). With ``decode_workers > 0`` the gather is chunked over
    a thread pool — numpy's gather loop releases the GIL, and memmap
    page faults overlap across threads.
    """

    def __init__(self, x, decode_workers: int = 0) -> None:
        if getattr(x, "ndim", 0) < 1:
            raise ValueError("HostStreamSource needs an [N, ...] array")
        self._x = x
        self.row_shape: Tuple[int, ...] = tuple(x.shape[1:])
        self.dtype = np.dtype(x.dtype)
        self._workers = max(int(decode_workers), 0)
        self._pool = None
        if self._workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                self._workers, thread_name_prefix="mercury-gather"
            )

    def __len__(self) -> int:
        return int(self._x.shape[0])

    def gather(self, gidx: np.ndarray, out: np.ndarray) -> None:
        """Fill ``out[i] = x[gidx[i]]`` for flat global row ids."""
        n = int(gidx.shape[0])
        if self._pool is None:
            out[:n] = self._x[gidx]
            return
        chunk = -(-n // self._workers)

        def fill(lo: int) -> None:
            hi = min(lo + chunk, n)
            out[lo:hi] = self._x[gidx[lo:hi]]

        # list() propagates worker exceptions here, on the caller.
        list(self._pool.map(fill, range(0, n, chunk)))

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


class ImageFolderSource:
    """Lazily-decoded ``root/<class>/<image>`` rows.

    The streaming counterpart of ``data/imagefolder.py``'s eager loader:
    the same deterministic enumeration (``list_image_folder`` — classes
    sorted, files sorted within class, so global index ``i`` here is the
    same sample the eager array's row ``i`` holds), but decode happens
    per-gather, only for the rows a step selected. ``image_size`` is
    mandatory: the staging buffers are pre-allocated, so the row shape
    must be known without decoding the whole folder.
    """

    def __init__(self, root: str, image_size: int = 32,
                 decode_workers: int = 0) -> None:
        from mercury_tpu.data.imagefolder import list_image_folder

        if image_size is None:
            raise ValueError(
                "ImageFolderSource needs a fixed image_size (staging "
                "buffers are pre-allocated)"
            )
        self._paths, self.labels, self.classes = list_image_folder(root)
        self._size = int(image_size)
        self.row_shape = (self._size, self._size, 3)
        self.dtype = np.dtype(np.uint8)
        self._workers = max(int(decode_workers), 0)
        self._pool = None
        if self._workers > 0:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                self._workers, thread_name_prefix="mercury-decode"
            )

    def __len__(self) -> int:
        return len(self._paths)

    def gather(self, gidx: np.ndarray, out: np.ndarray) -> None:
        from mercury_tpu.data.imagefolder import _load_image

        def decode(i: int) -> None:
            out[i] = _load_image(self._paths[int(gidx[i])], self._size)

        n = int(gidx.shape[0])
        if self._pool is None:
            for i in range(n):
                decode(i)
            return
        list(self._pool.map(decode, range(n)))

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None


_STOP = object()
_FAILED = object()


class PrefetchPipeline:
    """Bounded double-buffered host→device prefetch.

    ``push(idx)`` hands the worker thread a ``[W, S]`` index array — the
    train step's third output, usually still an in-flight device value;
    the worker (not the training thread) blocks on it, gathers the rows
    into a pre-allocated staging buffer, and commits them to the device
    with the step's batch sharding. ``pop()`` returns the oldest committed
    batch; the input-attributable part of its wait (the host gather +
    H2D dispatch after the selection materialized — see :meth:`pop`) is
    the *stall*, the number the whole design exists to drive to zero:
    with ``depth`` selections in flight (the cold-start prime pushes
    ``depth`` of them), the gather+H2D for step t+depth overlaps the
    compute of steps t…t+depth-1.

    The queue is bounded at ``depth`` committed batches; the driver's
    pop→step→push loop keeps exactly ``depth`` items in flight, so memory
    is ``(depth+1)`` staging-buffer-sized slabs, independent of dataset
    size. Worker exceptions re-raise on the next :meth:`pop`.

    Multi-controller (``local_workers`` given): the pipeline becomes this
    host's shard of a per-process fleet. ``batch_shape`` stays the GLOBAL
    ``(W, S)``; the staging slabs shrink to this host's worker rows, the
    worker gathers only those rows (splitting a global ``[W, S]`` index
    output host-locally via its addressable shards), and the commit
    assembles the global batch with ``jax.make_array_from_callback`` —
    each process transfers only its addressable shards, so zero pixel
    bytes ever cross hosts.
    """

    def __init__(self, source, batch_shape: Tuple[int, int], sharding,
                 depth: int = 2, pop_timeout_s: float = 300.0,
                 tracer=None, local_workers=None, faults=None,
                 generation: int = 0) -> None:
        if depth < 1:
            raise ValueError(f"depth must be >= 1, got {depth}")
        if tracer is None:
            from mercury_tpu.obs.trace import NULL_TRACER

            tracer = NULL_TRACER
        self._tracer = tracer
        self.source = source
        self.depth = int(depth)
        self._batch_shape = tuple(batch_shape)  # (W, S)
        self._sharding = sharding
        self._pop_timeout_s = float(pop_timeout_s)
        w, s = self._batch_shape
        # Multi-controller: slabs hold only this host's worker rows of the
        # global [W, S] batch; _staging_row maps global row → slab row for
        # the drain split and the global-array assembly callback.
        self._local_workers = (None if local_workers is None
                               else np.asarray(local_workers, np.int64))
        if self._local_workers is None:
            slab_rows = w
            self._staging_row = None
        else:
            slab_rows = int(self._local_workers.shape[0])
            self._staging_row = {
                int(g): i for i, g in enumerate(self._local_workers)
            }
        # depth+1 rotating staging slabs: the worker gathers into slab i
        # while the commit copies out of slabs i-1…i-depth are still in
        # flight, so publishing a batch never has to wait for the device.
        self._staging = [
            np.empty((slab_rows, s) + tuple(source.row_shape), source.dtype)
            for _ in range(self.depth + 1)
        ]
        self._inflight: list = [None] * (self.depth + 1)
        self._slot = 0
        import jax

        # The commit copy: device_put of a host buffer may alias it
        # zero-copy on CPU backends, and the staging slab is REUSED for a
        # later batch — the identity jit with pinned out_shardings forces
        # a real device-owned copy (the Trainer._recommit_state idiom),
        # after which the slab is free again.
        self._commit = jax.jit(lambda x: x, out_shardings=sharding)
        self._work: "queue.Queue[Any]" = queue.Queue()
        self._ready: "queue.Queue[Any]" = queue.Queue(maxsize=self.depth)
        self._exc: Optional[BaseException] = None
        self._exc_tb: Optional[str] = None
        # Fault-injection plane (mercury_tpu/faults.py); None when
        # disabled — the worker's hook sites are plain attribute checks.
        self._faults = faults
        self.total_stall_s = 0.0
        self.total_wait_s = 0.0
        self.total_h2d_bytes = 0
        self.pops = 0
        self._last_stall_s = 0.0
        self._last_h2d_bytes = 0
        self._closed = False
        # Supervisor restarts build a REPLACEMENT pipeline; the -rN name
        # suffix keeps respawns distinguishable from leaks in the Layer C
        # thread census.
        self.generation = int(generation)
        suffix = f"-r{self.generation}" if self.generation else ""
        self._thread = threading.Thread(
            target=self._prefetch_loop, name=f"mercury-prefetch{suffix}",
            daemon=True,
        )
        self._thread.start()

    # ------------------------------------------------------------- driving
    def push(self, idx) -> None:
        """Enqueue one selection's indices ([W, S], device or host array).
        Never blocks the training thread: the device sync on ``idx``
        happens on the worker."""
        if self._closed:
            raise RuntimeError("push() on a closed PrefetchPipeline")
        self._work.put(idx)

    def pop(self):
        """The oldest committed device batch ([W, S, ...], sharded as
        constructed). Blocks while the worker catches up.

        Two waits are accounted separately. ``total_wait_s`` is the raw
        time blocked here — most of it is the worker waiting for the
        *producing step's* output to materialize, time the device spends
        on useful compute (the lookahead pipeline's normal cadence, not a
        problem). ``total_stall_s`` is the input-attributable part: the
        host-side publish lag (gather + H2D dispatch after the index
        materialized), clipped to the time actually waited — the number
        that must stay near zero for the overlap claim to hold."""
        # Fail FAST and attributably: the worker publishes a poisoned
        # item (_FAILED) on death, but up to ``depth`` committed batches
        # can sit ahead of it in the ready queue — checking the failure
        # latch first surfaces the death (with the worker's traceback)
        # within one step instead of ``depth`` steps or a pop timeout
        # later. The supervisor's restart path relies on this promptness
        # to rebuild the pipeline before the selection ring drifts.
        if self._exc is not None:
            raise self._worker_death()
        t0 = time.monotonic()
        try:
            item = self._ready.get(timeout=self._pop_timeout_s)
        except queue.Empty:
            if self._exc is not None:
                raise self._worker_death()
            raise TimeoutError(
                f"no prefetched batch within {self._pop_timeout_s:.0f}s "
                "(did the driver forget to push()?)"
            )
        waited = time.monotonic() - t0
        self.total_wait_s += waited
        self.pops += 1
        if item is _FAILED:
            raise self._worker_death()
        batch, host_lag_s = item
        self.total_stall_s += min(waited, host_lag_s)
        return batch

    def _worker_death(self) -> RuntimeError:
        """The attributable death error: the worker's own traceback rides
        in the message (the exception context alone loses it — the worker
        thread's stack is gone by the time pop() re-raises here)."""
        err = RuntimeError(
            "prefetch worker died:\n" + (self._exc_tb or "<no traceback>"))
        err.__cause__ = self._exc
        return err

    def alive(self) -> bool:
        """Liveness for the supervisor: open, worker thread running, no
        failure latched. Lock-free reads of published flags."""
        return (not self._closed and self._exc is None
                and self._thread.is_alive())

    def stats(self) -> Dict[str, float]:
        """Interval telemetry since the previous call (the
        ``AsyncMetricWriter`` contract: per-log-window deltas), plus the
        instantaneous ready-queue depth."""
        stall = self.total_stall_s - self._last_stall_s
        h2d = self.total_h2d_bytes - self._last_h2d_bytes
        self._last_stall_s = self.total_stall_s
        self._last_h2d_bytes = self.total_h2d_bytes
        return {
            "data/stall_s": stall,
            "data/queue_depth": float(self._ready.qsize()),
            "data/h2d_bytes": float(h2d),
            "threads/queue_depth/prefetch": float(self._ready.qsize()),
        }

    def summary(self) -> Dict[str, float]:
        """Cumulative, NON-consuming counters (unlike :meth:`stats`,
        which returns per-interval deltas and advances the interval
        markers) — safe for out-of-band readers like flight-record
        dumps."""
        return {
            "depth": float(self.depth),
            "queue_depth": float(self._ready.qsize()),
            "pops": float(self.pops),
            "total_stall_s": self.total_stall_s,
            "total_wait_s": self.total_wait_s,
            "total_h2d_bytes": float(self.total_h2d_bytes),
        }

    def reset(self) -> None:
        """Discard queued work and committed batches (checkpoint-restore
        refill: the restored ``pending_sel`` re-seeds the ring, so every
        in-flight batch is for the wrong trajectory)."""
        self._drain(self._work)
        self._drain(self._ready)

    @staticmethod
    def _drain(q: "queue.Queue[Any]") -> None:
        while True:
            try:
                q.get_nowait()
            except queue.Empty:
                return

    def close(self, timeout: float = 30.0) -> None:
        if self._closed:
            return
        self._closed = True
        self._work.put(_STOP)
        # The worker may be parked in _publish waiting for ready-queue
        # room; draining the committed batches gives it space to notice
        # _closed and exit instead of riding out its timeout slices.
        self._drain(self._ready)
        self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            _log.warning(
                "prefetch thread %r still alive %.0fs after close() — "
                "abandoning it wedged (daemon)",
                self._thread.name, timeout)
        close = getattr(self.source, "close", None)
        if close is not None:
            close()

    # -------------------------------------------------------------- worker
    def _local_rows(self, idx) -> np.ndarray:
        """This host's rows of one selection's indices, as host int32/64.

        Accepts the three shapes a multi-controller driver can push: a
        global ``[W, S]`` jax.Array sharded over the data axis (the step's
        in-flight third output — only the addressable shards are readable
        here, and they ARE this host's rows), a host ``[W, S]`` array
        (sliced by ``local_workers``), or an already-local ``[Wl, S]``
        array (passed through). Single-pipeline mode is a plain asarray.
        """
        if self._local_workers is None:
            return np.asarray(idx)  # graftlint: disable=GL114 -- absorbing the index sync off the training thread is this worker's purpose
        if hasattr(idx, "addressable_shards") and not getattr(
                idx, "is_fully_addressable", True):
            rows: Dict[int, np.ndarray] = {}
            for sh in idx.addressable_shards:
                start = sh.index[0].start or 0
                data = np.asarray(sh.data)  # graftlint: disable=GL114 -- absorbing the index sync off the training thread is this worker's purpose
                for j in range(data.shape[0]):
                    rows[start + j] = data[j]
            return np.stack([rows[int(g)] for g in self._local_workers])
        arr = np.asarray(idx)  # graftlint: disable=GL114 -- absorbing the index sync off the training thread is this worker's purpose
        if arr.shape[0] == self._local_workers.shape[0] \
                and arr.shape[0] != self._batch_shape[0]:
            return arr
        return arr[self._local_workers]

    def _assemble(self, staging: np.ndarray):
        """Per-host slab → global ``[W, S, ...]`` array: each addressable
        device's block is served from this host's staging rows via the
        global-row map, so the construction never touches (or waits for)
        another host's pixels."""
        import jax

        w, s = self._batch_shape
        shape = (w, s) + tuple(self.source.row_shape)
        row_of = self._staging_row

        def cb(idx):
            rows = range(*idx[0].indices(w))
            block = np.stack([staging[row_of[r]] for r in rows])
            return block[(slice(None),) + tuple(idx[1:])]

        return jax.make_array_from_callback(shape, self._sharding, cb)

    def _publish(self, item) -> bool:
        """Bounded-wait put onto the ready queue with a close() escape
        hatch: a full queue means the trainer is behind — wait for room
        in short slices so a shutdown never wedges the producer against
        a queue nobody will drain again. Returns False when the
        pipeline closed before the item could be published."""
        while not self._closed:
            try:
                self._ready.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _prefetch_loop(self) -> None:
        import jax

        tracer = self._tracer
        tracer.register_thread("prefetch")
        while True:
            idx = self._work.get()
            if idx is _STOP:
                return
            try:
                if self._faults is not None:
                    if self._faults.fire("prefetch_die") is not None:
                        raise InjectedFault(
                            "prefetch_die: injected prefetch-worker death")
                    stall = self._faults.fire("prefetch_stall")
                    if stall is not None:
                        time.sleep(float(stall.get("secs", 1.0)))
                slot = self._slot
                self._slot = (slot + 1) % len(self._staging)
                staging = self._staging[slot]
                prev = self._inflight[slot]
                if prev is not None:
                    # Writing into the slab before its previous commit
                    # copy landed would corrupt that batch. depth+1 slabs
                    # back, the copy is all but certainly done — this is a
                    # fence, not a wait, and it bounds only this worker.
                    with tracer.span("stream/slab_fence", cat="stream"):
                        prev.block_until_ready()  # graftlint: disable=GL114 -- staging-slab reuse fence; blocks only this worker
                # The one real sync this thread exists to absorb: idx is
                # the step's in-flight index output, and materializing it
                # here means the TRAINING thread never waits for it. In
                # multi-controller mode this is also the drain-side split:
                # only this host's rows of the global selection are read.
                with tracer.span("stream/wait_indices", cat="stream"):
                    idx_h = self._local_rows(idx)
                t_ready = time.monotonic()
                with tracer.span("stream/gather", cat="stream",
                                 rows=int(idx_h.size)):
                    self.source.gather(
                        idx_h.reshape(-1),
                        staging.reshape(
                            (-1,) + tuple(self.source.row_shape)))
                with tracer.span("stream/h2d", cat="stream",
                                 bytes=int(staging.nbytes)):
                    if self._local_workers is None:
                        batch = jax.device_put(staging, self._sharding)
                    else:
                        batch = self._assemble(staging)
                    batch = self._commit(batch)
                self._inflight[slot] = batch
                self.total_h2d_bytes += int(staging.nbytes)
                # Published async: the commit is enqueued device work the
                # consuming step serializes behind naturally — blocking on
                # it here would charge device-queue time as stall. The
                # host lag rides along for pop()'s stall attribution.
                self._publish((batch, time.monotonic() - t_ready))
            except BaseException as exc:  # surfaced on the next pop()
                self._exc_tb = traceback.format_exc()
                self._exc = exc
                self._publish(_FAILED)
                return
