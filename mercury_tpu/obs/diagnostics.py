"""In-graph sampler-health diagnostics.

Mercury's value proposition is that importance sampling buys variance
reduction worth more than its scoring cost. These are the device-computed
scalars that make that tradeoff visible *live*, from inside the fused
step — no extra host syncs, no second program:

- :func:`ess_fraction` — normalized effective sample size of the
  importance weights, the canonical "is the IS estimator healthy" signal
  (ESS → 1 means the draw is near-uniform; ESS → 1/B means one sample
  dominates the batch and the variance reduction has inverted). This is
  the quantity Katharopoulos & Fleuret (arXiv:1803.00942) build their
  IS-on/off switch from.
- :func:`clip_fraction` — fraction of candidate scores that hit the
  numerical floor in :func:`~mercury_tpu.sampling.importance.
  importance_probs`. Nonzero means the score distribution has collapsed
  (all-zero losses with a zero EMA) and the draw is silently uniform.
- :func:`ema_drift` — fresh score mean minus the pre-update EMA: how far
  the running smoothing statistic lags the data. Large sustained drift
  means the EMA horizon is mismatched to the loss decay rate.
- :func:`table_age_summary` — min/mean/max staleness (in refresh sweeps)
  of the scoretable sampler's entries, derived from the round-robin
  cursor. Stale scores silently destroy the IS benefit (Alain et al.,
  arXiv:1511.06481), and the scoretable sampler is structurally exposed
  to staleness — this is its warning light.

Everything here is pure jittable jnp math, safe inside ``shard_map``.
All of it is gated behind ``TrainConfig.telemetry`` at trace time, so
with telemetry off none of these ops exist in the compiled step.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from mercury_tpu.sampling.importance import SCORE_FLOOR, smoothed_scores


def ess_fraction(scaled_probs: jax.Array) -> jax.Array:
    """Normalized effective sample size of the drawn batch's importance
    weights: ``(Σw)² / (B·Σw²)`` with ``w_i = 1/(N·p_i)`` (the reweight
    the training loss actually applies).

    Returns a float32 scalar in ``(0, 1]``: 1.0 means uniform weights
    (the uniform baseline's unit weights land exactly there), ``1/B``
    means a single sample carries the whole batch."""
    w = 1.0 / scaled_probs.astype(jnp.float32)
    b = scaled_probs.shape[0]
    return jnp.square(jnp.sum(w)) / (b * jnp.sum(jnp.square(w)) + 1e-30)


def clip_fraction(scores: jax.Array, ema_value: jax.Array,
                  alpha: float = 0.5) -> jax.Array:
    """Fraction of candidates whose smoothed score ``loss + α·EMA`` sits
    at/below the ``importance_probs`` floor — i.e. was clipped before
    normalization. float32 scalar in ``[0, 1]``."""
    s = smoothed_scores(scores, ema_value, alpha)
    return jnp.mean((s <= SCORE_FLOOR).astype(jnp.float32))


def ema_drift(fresh_mean: jax.Array, ema_prev: jax.Array) -> jax.Array:
    """Signed drift of the fresh score mean from the pre-update EMA."""
    return fresh_mean.astype(jnp.float32) - ema_prev.astype(jnp.float32)


def table_age_summary(
    cursor: jax.Array, n_slots: int, refresh_size: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(min, mean, max) age of the score table's entries, in refresh
    sweeps (≈ steps), derived from the round-robin cursor.

    ``cursor`` is the start of the window refreshed THIS step, so slots
    ``[cursor, cursor+R)`` have age 0 and the slot just behind the window
    is the oldest. This is the cursor-derived upper bound: the free
    write-back of the just-trained batch re-scores a few extra slots each
    step, which this summary deliberately ignores (it tracks the
    *guaranteed* refresh schedule, not the lucky draws)."""
    ages = table_ages(cursor, n_slots, refresh_size)
    return jnp.min(ages), jnp.mean(ages), jnp.max(ages)


def table_ages(cursor: jax.Array, n_slots: int,
               refresh_size: int) -> jax.Array:
    """Per-slot age ``[L]`` (float32, in refresh sweeps) behind the
    newest refreshed slot ``cursor + R - 1``: slots inside this step's
    window age 0, the window refreshed one step ago age 1, …"""
    newest = cursor + refresh_size - 1
    behind = jnp.mod(newest - jnp.arange(n_slots), n_slots)
    return (behind // refresh_size).astype(jnp.float32)


def global_grad_norm(grads) -> jax.Array:
    """L2 norm of a (post-allreduce) gradient pytree — float32 scalar."""
    leaves = jax.tree_util.tree_leaves(grads)
    return jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                        for g in leaves) + 0.0)
