"""Telemetry subsystem: in-graph sampler-health diagnostics, non-blocking
metric streaming, and run accounting.

Layers (see ``docs/DESIGN.md`` §15 and ``docs/OBSERVABILITY.md``):

1. :mod:`~mercury_tpu.obs.diagnostics` — device-computed health scalars
   (ESS, clip rate, EMA drift, score-table staleness, grad norm) emitted
   from inside the fused step, gated by ``TrainConfig.telemetry`` so they
   compile away when disabled.
2. :mod:`~mercury_tpu.obs.writer` — :class:`AsyncMetricWriter`: bounded
   queue + background drain thread, drop-oldest with a counted
   ``dropped`` stat, fan-out to JSONL / TensorBoard / stdout-heartbeat
   sinks, plus per-host shard sinks (``metrics.h{p}.jsonl``).
3. :mod:`~mercury_tpu.obs.manifest` / :mod:`~mercury_tpu.obs.accounting`
   — the run manifest written at trainer start, and live steps/s /
   examples/s / MFU on the log cadence.
4. :mod:`~mercury_tpu.obs.trace` / :mod:`~mercury_tpu.obs.anomaly` —
   layer 2 (``docs/OBSERVABILITY.md``): the ring-buffered host span
   tracer (Chrome-trace/Perfetto export) and the flight recorder +
   anomaly engine (non-finite loss, slow-step, ESS collapse, stall
   breach, MFU floor, cross-host straggler → ``flight_record_*.json``
   + optional on-demand profiler capture).
5. :mod:`~mercury_tpu.obs.registry` — the central metric-key registry;
   every tag the training path emits must be listed there (enforced by
   ``python -m mercury_tpu.lint --layer metrics``).
6. :mod:`~mercury_tpu.obs.events` / :mod:`~mercury_tpu.obs.serve` —
   the control-plane black box: the append-only causal event journal
   (``events.h{p}.jsonl``, every supervisor/scorer/fault/elastic/
   checkpoint/anomaly decision with ``parent_id`` links) and the live
   ``/healthz`` + ``/statusz`` + ``/metricsz`` scrape endpoint.
7. :mod:`~mercury_tpu.obs.aggregate` / :mod:`~mercury_tpu.obs.profile_parse`
   / :mod:`~mercury_tpu.obs.report` — layer 3: cross-host shard
   aggregation (``host/*`` metrics + straggler detection), offline
   device-time attribution of profiler captures, and the run-report /
   regression CLI (``python -m mercury_tpu.obs.report``).

Imports here are LAZY (PEP 562): ``mercury_tpu.obs.report`` and
``mercury_tpu.obs.profile_parse`` are offline tools that must run on
machines with no jax installed, so importing this package must not pull
:mod:`~mercury_tpu.obs.diagnostics` (which imports jax at module level).
``from mercury_tpu.obs import AsyncMetricWriter`` still works — the
submodule loads on first attribute access.
"""

import importlib
from typing import TYPE_CHECKING

#: public name -> defining submodule (relative). The eager star-imports
#: this replaces pulled jax into every consumer of the stdlib-only parts.
_LAZY_ATTRS = {
    "FLIGHT_RECORD_SCHEMA": "anomaly",
    "AnomalyEngine": "anomaly",
    "device_memory_stats": "anomaly",
    "METRIC_KEYS": "registry",
    "EVENT_KINDS": "registry",
    "RECORD_FIELDS": "registry",
    "is_registered": "registry",
    "NULL_TRACER": "trace",
    "NullTracer": "trace",
    "SpanTracer": "trace",
    "journal_lane_events": "trace",
    "merge_events_into_trace": "trace",
    "EVENT_SCHEMA": "events",
    "EventJournal": "events",
    "journal_filename": "events",
    "load_events": "events",
    "parent_chain": "events",
    "read_journal": "events",
    "validate_event": "events",
    "OPENMETRICS_CONTENT_TYPE": "serve",
    "StatusServer": "serve",
    "parse_openmetrics": "serve",
    "render_openmetrics": "serve",
    "PEAK_FLOPS": "accounting",
    "ThroughputMeter": "accounting",
    "analytic_flops_per_step": "accounting",
    "peak_flops": "accounting",
    "clip_fraction": "diagnostics",
    "ema_drift": "diagnostics",
    "ess_fraction": "diagnostics",
    "global_grad_norm": "diagnostics",
    "table_age_summary": "diagnostics",
    "table_ages": "diagnostics",
    "build_run_manifest": "manifest",
    "git_revision": "manifest",
    "write_run_manifest": "manifest",
    "AsyncMetricWriter": "writer",
    "HeartbeatSink": "writer",
    "HeartbeatShardSink": "writer",
    "JsonlSink": "writer",
    "TensorBoardSink": "writer",
    "try_tensorboard_sink": "writer",
    "HostShardAggregator": "aggregate",
    "StragglerWindow": "aggregate",
    "merge_host_stats": "aggregate",
    "BREAKDOWN_SCHEMA": "profile_parse",
    "attribute_device_time": "profile_parse",
    "parse_profile": "profile_parse",
    "scope_frac_metrics": "profile_parse",
    "write_breakdown": "profile_parse",
}

__all__ = sorted(_LAZY_ATTRS)


def __getattr__(name: str):
    module = _LAZY_ATTRS.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(
        importlib.import_module(f"{__name__}.{module}"), name)
    globals()[name] = value  # cache: next access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(__all__))


if TYPE_CHECKING:  # static analyzers see the real names
    from mercury_tpu.obs.aggregate import (  # noqa: F401
        HostShardAggregator,
        StragglerWindow,
        merge_host_stats,
    )
    from mercury_tpu.obs.anomaly import (  # noqa: F401
        FLIGHT_RECORD_SCHEMA,
        AnomalyEngine,
        device_memory_stats,
    )
    from mercury_tpu.obs.accounting import (  # noqa: F401
        PEAK_FLOPS,
        ThroughputMeter,
        analytic_flops_per_step,
        peak_flops,
    )
    from mercury_tpu.obs.diagnostics import (  # noqa: F401
        clip_fraction,
        ema_drift,
        ess_fraction,
        global_grad_norm,
        table_age_summary,
        table_ages,
    )
    from mercury_tpu.obs.manifest import (  # noqa: F401
        build_run_manifest,
        git_revision,
        write_run_manifest,
    )
    from mercury_tpu.obs.profile_parse import (  # noqa: F401
        BREAKDOWN_SCHEMA,
        attribute_device_time,
        parse_profile,
        scope_frac_metrics,
        write_breakdown,
    )
    from mercury_tpu.obs.events import (  # noqa: F401
        EVENT_SCHEMA,
        EventJournal,
        journal_filename,
        load_events,
        parent_chain,
        read_journal,
        validate_event,
    )
    from mercury_tpu.obs.registry import (  # noqa: F401
        EVENT_KINDS,
        METRIC_KEYS,
        RECORD_FIELDS,
        is_registered,
    )
    from mercury_tpu.obs.serve import (  # noqa: F401
        OPENMETRICS_CONTENT_TYPE,
        StatusServer,
        parse_openmetrics,
        render_openmetrics,
    )
    from mercury_tpu.obs.trace import (  # noqa: F401
        NULL_TRACER,
        NullTracer,
        SpanTracer,
        journal_lane_events,
        merge_events_into_trace,
    )
    from mercury_tpu.obs.writer import (  # noqa: F401
        AsyncMetricWriter,
        HeartbeatShardSink,
        HeartbeatSink,
        JsonlSink,
        TensorBoardSink,
        try_tensorboard_sink,
    )
