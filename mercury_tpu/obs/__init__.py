"""Telemetry subsystem: in-graph sampler-health diagnostics, non-blocking
metric streaming, and run accounting.

Three layers (see ``docs/DESIGN.md`` §15):

1. :mod:`~mercury_tpu.obs.diagnostics` — device-computed health scalars
   (ESS, clip rate, EMA drift, score-table staleness, grad norm) emitted
   from inside the fused step, gated by ``TrainConfig.telemetry`` so they
   compile away when disabled.
2. :mod:`~mercury_tpu.obs.writer` — :class:`AsyncMetricWriter`: bounded
   queue + background drain thread, drop-oldest with a counted
   ``dropped`` stat, fan-out to JSONL / TensorBoard / stdout-heartbeat
   sinks.
3. :mod:`~mercury_tpu.obs.manifest` / :mod:`~mercury_tpu.obs.accounting`
   — the run manifest written at trainer start, and live steps/s /
   examples/s / MFU on the log cadence.
"""

from mercury_tpu.obs.accounting import (
    PEAK_FLOPS,
    ThroughputMeter,
    analytic_flops_per_step,
    peak_flops,
)
from mercury_tpu.obs.diagnostics import (
    clip_fraction,
    ema_drift,
    ess_fraction,
    global_grad_norm,
    table_age_summary,
    table_ages,
)
from mercury_tpu.obs.manifest import (
    build_run_manifest,
    git_revision,
    write_run_manifest,
)
from mercury_tpu.obs.writer import (
    AsyncMetricWriter,
    HeartbeatSink,
    JsonlSink,
    TensorBoardSink,
    try_tensorboard_sink,
)

__all__ = [
    "PEAK_FLOPS",
    "ThroughputMeter",
    "analytic_flops_per_step",
    "peak_flops",
    "clip_fraction",
    "ema_drift",
    "ess_fraction",
    "global_grad_norm",
    "table_age_summary",
    "table_ages",
    "build_run_manifest",
    "git_revision",
    "write_run_manifest",
    "AsyncMetricWriter",
    "HeartbeatSink",
    "JsonlSink",
    "TensorBoardSink",
    "try_tensorboard_sink",
]
