"""Telemetry subsystem: in-graph sampler-health diagnostics, non-blocking
metric streaming, and run accounting.

Three layers (see ``docs/DESIGN.md`` §15):

1. :mod:`~mercury_tpu.obs.diagnostics` — device-computed health scalars
   (ESS, clip rate, EMA drift, score-table staleness, grad norm) emitted
   from inside the fused step, gated by ``TrainConfig.telemetry`` so they
   compile away when disabled.
2. :mod:`~mercury_tpu.obs.writer` — :class:`AsyncMetricWriter`: bounded
   queue + background drain thread, drop-oldest with a counted
   ``dropped`` stat, fan-out to JSONL / TensorBoard / stdout-heartbeat
   sinks.
3. :mod:`~mercury_tpu.obs.manifest` / :mod:`~mercury_tpu.obs.accounting`
   — the run manifest written at trainer start, and live steps/s /
   examples/s / MFU on the log cadence.
4. :mod:`~mercury_tpu.obs.trace` / :mod:`~mercury_tpu.obs.anomaly` —
   layer 2 (``docs/OBSERVABILITY.md``): the ring-buffered host span
   tracer (Chrome-trace/Perfetto export) and the flight recorder +
   anomaly engine (non-finite loss, slow-step, ESS collapse, stall
   breach, MFU floor → ``flight_record_*.json`` + optional on-demand
   profiler capture).
5. :mod:`~mercury_tpu.obs.registry` — the central metric-key registry;
   every tag the training path emits must be listed there (enforced by
   ``python -m mercury_tpu.lint --layer metrics``).
"""

from mercury_tpu.obs.anomaly import (
    FLIGHT_RECORD_SCHEMA,
    AnomalyEngine,
    device_memory_stats,
)
from mercury_tpu.obs.registry import (
    METRIC_KEYS,
    RECORD_FIELDS,
    is_registered,
)
from mercury_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    SpanTracer,
)
from mercury_tpu.obs.accounting import (
    PEAK_FLOPS,
    ThroughputMeter,
    analytic_flops_per_step,
    peak_flops,
)
from mercury_tpu.obs.diagnostics import (
    clip_fraction,
    ema_drift,
    ess_fraction,
    global_grad_norm,
    table_age_summary,
    table_ages,
)
from mercury_tpu.obs.manifest import (
    build_run_manifest,
    git_revision,
    write_run_manifest,
)
from mercury_tpu.obs.writer import (
    AsyncMetricWriter,
    HeartbeatSink,
    JsonlSink,
    TensorBoardSink,
    try_tensorboard_sink,
)

__all__ = [
    "FLIGHT_RECORD_SCHEMA",
    "AnomalyEngine",
    "device_memory_stats",
    "METRIC_KEYS",
    "RECORD_FIELDS",
    "is_registered",
    "NULL_TRACER",
    "NullTracer",
    "SpanTracer",
    "PEAK_FLOPS",
    "ThroughputMeter",
    "analytic_flops_per_step",
    "peak_flops",
    "clip_fraction",
    "ema_drift",
    "ess_fraction",
    "global_grad_norm",
    "table_age_summary",
    "table_ages",
    "build_run_manifest",
    "git_revision",
    "write_run_manifest",
    "AsyncMetricWriter",
    "HeartbeatSink",
    "JsonlSink",
    "TensorBoardSink",
    "try_tensorboard_sink",
]
