"""Throughput and MFU accounting — the ``benchmarks/mfu_sweep.py``
numbers, available live on the log cadence instead of only offline.

- :data:`PEAK_FLOPS` — per-device-kind peak (bf16) FLOP/s table (moved
  here from ``mfu_sweep`` so the live path and the offline sweep share
  one source of truth).
- :func:`analytic_flops_per_step` — XLA's cost analysis of the LOWERED
  fused step program (a re-trace, never an XLA compile — see the
  function docstring).
- :class:`ThroughputMeter` — steps/s, examples/s, and the MFU estimate
  between log ticks, as host-side floats ready to merge into the metric
  record.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

PEAK_FLOPS = {
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v4": 275e12,
    "TPU v6": 918e12,
}


def peak_flops(device_kind: Optional[str]) -> Optional[float]:
    """Peak FLOP/s for a device kind, or None when unknown (CPU, new
    TPU generations not yet tabulated)."""
    if not device_kind:
        return None
    return next((v for k, v in PEAK_FLOPS.items()
                 if device_kind.startswith(k)), None)


def analytic_flops_per_step(step_fn, *args, scan_steps: int = 1
                            ) -> Optional[float]:
    """FLOPs of ONE step of the jitted ``step_fn`` per XLA's cost
    analysis (divided by ``scan_steps`` for chunked programs). Returns
    None when the backend offers no cost model.

    Analyzes the LOWERED module, never ``.compile()``: the AOT compile
    path does not share the jit executable cache, so asking the compiled
    program would silently rebuild the entire fused step (minutes of
    XLA time for a ResNet-scale scan program on CPU) just to read one
    number. Unoptimized-HLO FLOPs are what the MFU estimate needs."""
    try:
        cost = step_fn.lower(*args).cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        flops = float(cost.get("flops", 0.0))
    except Exception:
        return None
    if flops <= 0.0:
        return None
    return flops / max(scan_steps, 1)


class ThroughputMeter:
    """Rolling steps/s, examples/s, and MFU between log ticks.

    ``tick(step)`` returns the ``perf/*`` scalars for the interval since
    the previous tick — host floats, no device work. MFU is analytic
    FLOPs × steps/s against the device's tabulated peak; when either is
    unknown (e.g. CPU) it reports 0.0 and the manifest's
    ``peak_flops: null`` marks the estimate as not meaningful."""

    def __init__(self, examples_per_step: float,
                 flops_per_step: Optional[float] = None,
                 device_kind: Optional[str] = None) -> None:
        if device_kind is None:
            try:
                import jax

                device_kind = jax.devices()[0].device_kind
            except Exception:
                device_kind = None
        self.examples_per_step = float(examples_per_step)
        self.flops_per_step = flops_per_step
        self.peak = peak_flops(device_kind)
        self._last_step: Optional[int] = None
        self._last_t = 0.0

    def reset(self, step: int, now: Optional[float] = None) -> None:
        self._last_step = int(step)
        self._last_t = time.perf_counter() if now is None else now

    def tick(self, step: int, now: Optional[float] = None
             ) -> Dict[str, float]:
        now = time.perf_counter() if now is None else now
        if self._last_step is None:
            self.reset(step, now)
            return {}
        dt = max(now - self._last_t, 1e-9)
        steps = max(step - self._last_step, 1)
        self._last_step, self._last_t = int(step), now
        steps_per_s = steps / dt
        out = {
            "perf/steps_per_s": steps_per_s,
            "perf/examples_per_s": steps_per_s * self.examples_per_step,
            "time/step": dt / steps,
            "time/images_per_sec": steps_per_s * self.examples_per_step,
        }
        if self.flops_per_step:
            out["perf/flops_per_step"] = self.flops_per_step
        mfu = 0.0
        if self.flops_per_step and self.peak:
            mfu = self.flops_per_step * steps_per_s / self.peak
        out["perf/mfu"] = mfu
        return out
