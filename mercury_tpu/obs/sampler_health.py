"""Distribution-level sampler observability (the sensory half of the
self-tuning sampler control plane, ROADMAP item 3).

The scalar telemetry (``sampler/ess``, ``clip_frac``, table ages) sees the
importance sampler only through moments; this module sees the
*distributions*:

- :func:`log_bin_histogram` — fixed log-spaced-bin histogram, pure
  jittable jnp (the ``obs/diagnostics.py`` idiom: safe inside shard_map,
  traced only under ``config.telemetry``). The step emits the score
  table's and the per-batch IS weights' histograms as per-bin scalar
  metrics (``sampler_dist/score_hist/bNN`` / ``sampler_dist/w_hist/bNN``)
  — per-bin scalars, not a vector, because the async writer reduces every
  record value with ``np.mean`` (obs/writer.py ``_to_host_record``).
- the **selection-count ledger** (``MercuryState.sel_counts``, ``[W, L]``
  int32): the step scatter-adds the trained slots each step; the
  host-side :class:`SamplerHealthMonitor` fetches it on the log cadence
  and derives coverage, a selection Gini, per-class selection spread, and
  an empirical-vs-expected inclusion-bias audit against the live table's
  normalized scores.
- the **grad-variance probe** (``config.variance_probe_every``): the step
  runs one extra scoring-model microbatch pass and emits
  ``sampler_dist/var_ratio`` — the estimated IS-vs-uniform gradient-norm
  second-moment ratio, the gate signal of Katharopoulos & Fleuret
  (arXiv:1803.00942): sustained ``>= 1`` means importance sampling is
  currently *losing* to uniform. :func:`variance_probe_ratio` is the pure
  estimator the step calls, kept here so the CPU cross-validation against
  ``benchmarks/grad_variance.py`` tests one definition.

Everything host-side is numpy-only on fetched arrays — nothing here ever
touches the traced program, and with ``telemetry=False`` neither the
ledger nor the histograms exist at all (Layer-2/3 digest-enforced).
"""

from __future__ import annotations

import math
from typing import Dict, Optional

import numpy as np

# --- in-graph half ---------------------------------------------------------

#: Fixed bin count shared by every emitted histogram. Fixed (not a config
#: knob) because each bin is its own registered metric key
#: (``obs/registry.py`` is exact-match) and the flight recorder / report
#: renderers index bins positionally.
HIST_BINS = 16
#: Log-spaced edges for the score-table histogram: scores are per-sample
#: CE losses / grad-norm bounds, floored at SCORE_FLOOR=1e-12 and rarely
#: above ~1e2; out-of-range values clamp into the end bins, so counts
#: always total the table length.
SCORE_HIST_LO, SCORE_HIST_HI = 1e-6, 1e2
#: Log-spaced edges for the IS-weight histogram. ``scaled_probs = L·p``
#: is the *inverse* of the reweight (loss_i / scaled_probs_i): 1.0 is the
#: uniform weight, the interesting tails sit orders of magnitude away on
#: either side.
WEIGHT_HIST_LO, WEIGHT_HIST_HI = 1e-4, 1e4


def log_bin_histogram(x, lo: float, hi: float, bins: int = HIST_BINS):
    """Histogram of ``x`` over ``bins`` log-spaced bins spanning
    ``[lo, hi)``; values below ``lo`` clamp into bin 0 and values at or
    above ``hi`` into the last bin, so ``sum(counts) == x.size`` always.
    Pure jittable jnp — safe inside shard_map; psum the result over the
    data axis for a global histogram."""
    import jax.numpy as jnp

    x = jnp.asarray(x, jnp.float32).reshape(-1)
    lo_l, hi_l = math.log(lo), math.log(hi)
    idx = jnp.floor(
        (jnp.log(jnp.maximum(x, lo)) - lo_l) / (hi_l - lo_l) * bins
    ).astype(jnp.int32)
    idx = jnp.clip(idx, 0, bins - 1)
    return jnp.zeros((bins,), jnp.int32).at[idx].add(1)


def log_bin_histogram_np(x, lo: float, hi: float,
                         bins: int = HIST_BINS) -> np.ndarray:
    """Numpy reference for :func:`log_bin_histogram` — same clamp-into-end
    -bins semantics, same f32 arithmetic (the bit-match test pins the two
    together)."""
    x = np.asarray(x, np.float32).reshape(-1)
    lo_l, hi_l = math.log(lo), math.log(hi)
    idx_f = np.floor(
        (np.log(np.maximum(x, np.float32(lo))) - np.float32(lo_l))
        / np.float32(hi_l - lo_l) * np.float32(bins)
    )
    # Clip BEFORE the int cast: numpy's float→int32 cast of +inf wraps to
    # INT32_MIN while XLA's saturates to INT32_MAX — clipping in float
    # space makes +inf land in the last bin in both implementations.
    idx = np.nan_to_num(np.clip(idx_f, 0, bins - 1), nan=0.0).astype(
        np.int32)
    return np.bincount(idx, minlength=bins).astype(np.int32)


def hist_bin_edges(lo: float, hi: float,
                   bins: int = HIST_BINS) -> np.ndarray:
    """The ``bins + 1`` log-spaced edges the histograms above bin by —
    for report axes and docs, host-side only."""
    return np.exp(np.linspace(math.log(lo), math.log(hi), bins + 1))


def hist_keys(family: str, bins: int = HIST_BINS):
    """The per-bin metric keys a histogram family emits, in bin order —
    one definition shared by the step emitters, the anomaly engine's
    flight-record attachment, and the report renderer."""
    return tuple(f"sampler_dist/{family}/b{i:02d}" for i in range(bins))


def variance_probe_ratio(grad_norms, scaled_probs, eps: float = 1e-30):
    """The ``sampler_dist/var_ratio`` estimator, on one IS-drawn
    microbatch: per-example grad-norm (bound) ``g_i`` and the draw-time
    ``scaled_probs_i = L·p_i``.

    With samples drawn from ``p``, ``mean((g/(L·p))²)`` estimates the IS
    gradient estimator's second moment ``E_p[(g/(L·p))²]`` directly, and
    ``mean(g²/(L·p))`` estimates the uniform estimator's second moment
    ``E_unif[g²]`` by the same unbiased reweighting the loss uses. Their
    ratio follows ``benchmarks/grad_variance.py``'s convention
    (``ratio < 1`` ⇔ importance sampling wins); uniform weights give
    exactly 1. Second moments, not centered variances — the shared mean
    term cancels in the regime the gate cares about (1803.00942 §3 makes
    the same approximation). jnp in, jnp out (also valid on numpy)."""
    import jax.numpy as jnp

    g = jnp.asarray(grad_norms, jnp.float32)
    sp = jnp.maximum(jnp.asarray(scaled_probs, jnp.float32), eps)
    m_is = jnp.mean(jnp.square(g / sp))
    m_unif = jnp.mean(jnp.square(g) / sp)
    return m_is / jnp.maximum(m_unif, eps)


# --- host-side half --------------------------------------------------------


def ledger_global_counts(counts_wl: np.ndarray,
                         shard_indices: np.ndarray,
                         n_samples: int) -> np.ndarray:
    """Aggregate the ``[W, L]`` per-slot ledger to per-SAMPLE counts
    ``[n]``: cyclic-tiling duplicates (one sample owning several slots of
    a row) and cross-worker ownership both SUM — unlike the score carry's
    last-wins, a count is additive."""
    out = np.zeros((n_samples,), np.int64)
    np.add.at(out, np.asarray(shard_indices).reshape(-1),
              np.asarray(counts_wl, np.int64).reshape(-1))
    return out


def gini(counts: np.ndarray) -> float:
    """Gini coefficient of the selection-count distribution: 0 = every
    sample drawn equally often, →1 = all draws on a vanishing fraction.
    Standard mean-absolute-difference form on sorted counts."""
    c = np.sort(np.asarray(counts, np.float64))
    n = c.size
    total = c.sum()
    if n == 0 or total <= 0:
        return 0.0
    cum = np.cumsum(c)
    # G = (n + 1 - 2·sum(cum)/total) / n
    return float((n + 1 - 2.0 * cum.sum() / total) / n)


def class_spread(counts_global: np.ndarray, labels: np.ndarray,
                 num_classes: int,
                 starvation_share: float = 0.2) -> Dict[str, float]:
    """Per-class selection spread: each class's share of total draws over
    its share of the dataset (1.0 = drawn proportionally). A class whose
    ratio sits below ``starvation_share`` counts as starved — the
    ``class_starvation`` trigger fires on the count."""
    labels = np.asarray(labels)
    counts_global = np.asarray(counts_global, np.float64)
    total = counts_global.sum()
    sel_per_class = np.zeros((num_classes,), np.float64)
    np.add.at(sel_per_class, labels, counts_global)
    data_per_class = np.bincount(labels, minlength=num_classes).astype(
        np.float64)
    present = data_per_class > 0
    if total <= 0 or not present.any():
        return {"class_share_min": 1.0, "class_share_max": 1.0,
                "class_starved": 0.0}
    ratio = (sel_per_class[present] / total) / (
        data_per_class[present] / labels.size)
    return {
        "class_share_min": float(ratio.min()),
        "class_share_max": float(ratio.max()),
        "class_starved": float(np.sum(ratio < starvation_share)),
    }


def bias_audit(counts_wl: np.ndarray, probs_wl: np.ndarray,
               threshold: float = 5.0) -> Dict[str, float]:
    """Empirical-vs-expected inclusion-bias audit: observed per-slot
    selection frequency against the table's CURRENT normalized scores.

    χ²-style drift stat per degree of freedom:
    ``mean_slots((obs − exp)² / max(exp, 1))`` with
    ``exp = draws_w · p_w[slot]`` per worker row — ≈1 when the draws
    track the table (multinomial noise), growing without bound as the
    observed frequencies drift from the distribution the table claims.
    Not an exact test (the table evolves while the ledger accumulates —
    that drift is precisely what the stat surfaces); ``threshold`` sets
    the ``bias_ok`` verdict the report prints."""
    counts = np.asarray(counts_wl, np.float64)
    probs = np.asarray(probs_wl, np.float64)
    if counts.ndim == 1:
        counts, probs = counts[None], probs[None]
    draws = counts.sum(axis=1, keepdims=True)
    if counts.size == 0 or draws.sum() <= 0:
        return {"bias_chi2": 0.0, "bias_ok": 1.0}
    exp = draws * probs
    stat = float(np.mean(np.square(counts - exp) / np.maximum(exp, 1.0)))
    return {"bias_chi2": stat, "bias_ok": 1.0 if stat < threshold else 0.0}


def table_probs_np(scores: np.ndarray, ema_value: np.ndarray,
                   alpha: float) -> np.ndarray:
    """Numpy mirror of ``sampling.scoretable.table_probs`` (smoothed →
    floored → normalized, per worker row) so the audit never has to trace
    anything. ``scores`` ``[W, L]``, ``ema_value`` ``[W]``."""
    from mercury_tpu.sampling.importance import SCORE_FLOOR

    smoothed = np.asarray(scores, np.float64) + alpha * np.asarray(
        ema_value, np.float64)[:, None]
    clipped = np.maximum(smoothed, SCORE_FLOOR)
    return clipped / clipped.sum(axis=1, keepdims=True)


def sparkline(values, width: Optional[int] = None) -> str:
    """Unicode sparkline of a histogram (▁▂▃▄▅▆▇█), for the report's
    sampler-health section. Empty bins render as the lowest glyph; all
    -zero input renders flat."""
    blocks = "▁▂▃▄▅▆▇█"
    v = np.asarray(list(values), np.float64)
    if width is not None and v.size > width:
        v = v[:width]
    if v.size == 0:
        return ""
    top = v.max()
    if top <= 0:
        return blocks[0] * v.size
    idx = np.minimum((v / top * (len(blocks) - 1)).astype(int),
                     len(blocks) - 1)
    return "".join(blocks[i] for i in idx)


class SamplerHealthMonitor:
    """Host-side ledger→metrics derivation, merged into the log-gate
    record like ``StreamPipeline.stats()`` — one device fetch of the
    ``[W, L]`` int32 ledger (plus the score table for the bias audit) per
    log tick, numpy from there.

    Single-controller only (the ledger is a global array; a
    multi-process run cannot ``device_get`` non-addressable shards) —
    the Trainer simply doesn't construct one when
    ``jax.process_count() > 1``, mirroring the async scorer fleet's
    constraint."""

    def __init__(self, shard_indices: np.ndarray, labels: np.ndarray,
                 num_classes: int, is_alpha: float,
                 starvation_share: float = 0.2,
                 bias_threshold: float = 5.0):
        self._sidx = np.asarray(shard_indices)
        self._labels = np.asarray(labels)
        self._n = int(self._labels.size)
        self._num_classes = int(num_classes)
        self._alpha = float(is_alpha)
        self._starvation_share = float(starvation_share)
        self._bias_threshold = float(bias_threshold)

    def stats(self, state) -> Dict[str, float]:
        import jax

        if state.sel_counts is None:
            return {}
        counts = np.asarray(jax.device_get(state.sel_counts))
        out: Dict[str, float] = {}
        global_counts = ledger_global_counts(counts, self._sidx, self._n)
        out["sampler_dist/frac_never_selected"] = float(
            np.mean(global_counts == 0))
        out["sampler_dist/gini"] = gini(global_counts)
        spread = class_spread(global_counts, self._labels,
                              self._num_classes, self._starvation_share)
        out["sampler_dist/class_share_min"] = spread["class_share_min"]
        out["sampler_dist/class_share_max"] = spread["class_share_max"]
        out["sampler_dist/class_starved"] = spread["class_starved"]
        if state.scoretable is not None:
            scores = np.asarray(jax.device_get(state.scoretable.scores))
            ema = np.asarray(jax.device_get(state.ema.value))
            audit = bias_audit(
                counts, table_probs_np(scores, ema, self._alpha),
                self._bias_threshold,
            )
            out["sampler_dist/bias_chi2"] = audit["bias_chi2"]
            out["sampler_dist/bias_ok"] = audit["bias_ok"]
        return out
