"""Central metric-key registry: every tag the training path may emit.

One flat ``key → one-line meaning`` dict, stdlib-only (graftlint's
metric-key layer AST-parses this file without importing jax — keep it a
pure literal plus trivial helpers). The registry is the contract between
the emitters (``train/step.py``, ``train/trainer.py``, ``data/stream.py``,
``sampling/scorer_fleet.py``, ``obs/*``) and the consumers (sinks, dashboards, the anomaly engine,
``docs/API.md``'s glossary): a key that is not here is a lint error, so a
renamed or fat-fingered metric fails CI instead of silently forking the
stream (``python -m mercury_tpu.lint --layer metrics``).
"""

from __future__ import annotations

from typing import Dict

#: Metric tags proper — ``prefix/name``, one row per scalar in the
#: stream. Grouped families (``sampler/table_age_{min,mean,max}``) are
#: spelled out: the registry is exact-match, expansion lives in docs.
METRIC_KEYS: Dict[str, str] = {
    # train/* — the step's own scalars
    "train/loss": "selected-batch reweighted loss (chunk mean under scan)",
    "train/acc": "selected-batch accuracy",
    "train/pool_loss": "mean score over the candidate pool",
    "train/sparse_rate": "gradient-compression sparsity (0 when off)",
    "train/moe_aux": "MoE load-balancing aux loss (0 when off)",
    "train/grad_norm": "global L2 norm of the post-allreduce gradient",
    "train/eval_loss": "train-split eval loss (inference mode)",
    "train/eval_acc": "train-split eval accuracy (inference mode)",
    # test/* — eval pass over the held-out split
    "test/eval_loss": "test-split eval loss (inference mode)",
    "test/eval_acc": "test-split eval accuracy (inference mode)",
    # sampler/* — importance-sampling health (telemetry=True only)
    "sampler/ess": "normalized effective sample size of the IS weights",
    "sampler/clip_frac": "fraction of candidate scores at/below the floor",
    "sampler/ema_drift": "fresh score mean minus pre-update EMA",
    "sampler/table_age_min": "scoretable: youngest entry age (sweeps)",
    "sampler/table_age_mean": "scoretable: mean entry age (sweeps)",
    "sampler/table_age_max": "scoretable: oldest entry age (sweeps)",
    "sampler/score_staleness_mean":
        "async refresh: mean applied-chunk age (steps) since last tick",
    "sampler/score_staleness_max":
        "async refresh: oldest applied-chunk age (steps) since last tick",
    "sampler/refresh_lag_chunks":
        "async refresh: scored chunks queued but not yet applied",
    "sampler/chunks_rejected":
        "cumulative non-finite score chunks rejected by the apply guard",
    "sampler/is_active":
        "1 while importance sampling drives the draw; 0 once degraded "
        "to uniform (supervisor ladder level 3)",
    # sampler_dist/* — distribution-level sampler health
    # (obs/sampler_health.py). The in-graph half (histogram bins,
    # var_ratio) exists only under telemetry=True with the scoretable
    # sampler; the host-side half (coverage, gini, class spread, bias
    # audit) is derived from the selection-count ledger at the log gate
    # by SamplerHealthMonitor (single-controller runs).
    "sampler_dist/var_ratio":
        "grad-variance probe: IS/uniform grad-norm second-moment ratio "
        "(>= 1 means IS is losing; -1 on off-cadence steps)",
    "sampler_dist/frac_never_selected":
        "fraction of the dataset never drawn for training so far",
    "sampler_dist/gini":
        "Gini coefficient of per-sample selection counts (0 uniform)",
    "sampler_dist/class_share_min":
        "smallest per-class selection share over data share",
    "sampler_dist/class_share_max":
        "largest per-class selection share over data share",
    "sampler_dist/class_starved":
        "classes whose selection/data share ratio is below the floor",
    "sampler_dist/bias_chi2":
        "chi-square-per-slot drift of observed draws vs table probs",
    "sampler_dist/bias_ok":
        "1 while the inclusion-bias audit is within threshold, else 0",
    # score-table histogram, 16 log-spaced bins over [1e-6, 1e2);
    # under/overflow clamps into the end bins (counts total the table)
    "sampler_dist/score_hist/b00": "score-table histogram bin 0 count",
    "sampler_dist/score_hist/b01": "score-table histogram bin 1 count",
    "sampler_dist/score_hist/b02": "score-table histogram bin 2 count",
    "sampler_dist/score_hist/b03": "score-table histogram bin 3 count",
    "sampler_dist/score_hist/b04": "score-table histogram bin 4 count",
    "sampler_dist/score_hist/b05": "score-table histogram bin 5 count",
    "sampler_dist/score_hist/b06": "score-table histogram bin 6 count",
    "sampler_dist/score_hist/b07": "score-table histogram bin 7 count",
    "sampler_dist/score_hist/b08": "score-table histogram bin 8 count",
    "sampler_dist/score_hist/b09": "score-table histogram bin 9 count",
    "sampler_dist/score_hist/b10": "score-table histogram bin 10 count",
    "sampler_dist/score_hist/b11": "score-table histogram bin 11 count",
    "sampler_dist/score_hist/b12": "score-table histogram bin 12 count",
    "sampler_dist/score_hist/b13": "score-table histogram bin 13 count",
    "sampler_dist/score_hist/b14": "score-table histogram bin 14 count",
    "sampler_dist/score_hist/b15": "score-table histogram bin 15 count",
    # per-batch IS-weight (scaled_probs) histogram, 16 log-spaced bins
    # over [1e-4, 1e4); 1.0 is the uniform weight
    "sampler_dist/w_hist/b00": "IS-weight histogram bin 0 count",
    "sampler_dist/w_hist/b01": "IS-weight histogram bin 1 count",
    "sampler_dist/w_hist/b02": "IS-weight histogram bin 2 count",
    "sampler_dist/w_hist/b03": "IS-weight histogram bin 3 count",
    "sampler_dist/w_hist/b04": "IS-weight histogram bin 4 count",
    "sampler_dist/w_hist/b05": "IS-weight histogram bin 5 count",
    "sampler_dist/w_hist/b06": "IS-weight histogram bin 6 count",
    "sampler_dist/w_hist/b07": "IS-weight histogram bin 7 count",
    "sampler_dist/w_hist/b08": "IS-weight histogram bin 8 count",
    "sampler_dist/w_hist/b09": "IS-weight histogram bin 9 count",
    "sampler_dist/w_hist/b10": "IS-weight histogram bin 10 count",
    "sampler_dist/w_hist/b11": "IS-weight histogram bin 11 count",
    "sampler_dist/w_hist/b12": "IS-weight histogram bin 12 count",
    "sampler_dist/w_hist/b13": "IS-weight histogram bin 13 count",
    "sampler_dist/w_hist/b14": "IS-weight histogram bin 14 count",
    "sampler_dist/w_hist/b15": "IS-weight histogram bin 15 count",
    # perf/* — throughput accounting between log ticks
    "perf/steps_per_s": "steps per second since the previous log tick",
    "perf/examples_per_s": "examples per second since the previous log tick",
    "perf/flops_per_step": "XLA cost-analysis FLOPs of the fused step",
    "perf/mfu": "model FLOPs utilization against the device peak",
    # time/* — legacy aliases kept for dashboard continuity
    "time/step": "seconds per step (legacy alias)",
    "time/images_per_sec": "examples per second (legacy alias)",
    # data/* — host_stream input pipeline
    "data/stall_s": "input-attributable pop() wait since the last log tick",
    "data/queue_depth": "committed prefetch batches ready at log time",
    "data/h2d_bytes": "staged host-to-device bytes since the last log tick",
    # scorer/* — the async scorer fleet (sampling/scorer_fleet.py) and
    # the scorer service front (sampling/scorer_service.py). The
    # service emits the aggregates plus one stream per tenant t0..t3
    # (scorer_tenants is capped at 4 so the per-tenant keys stay an
    # exact-match enumeration).
    "scorer/throughput": "async refresh: rows scored per second by the fleet",
    "scorer/queue_depth":
        "scorer service: ready chunks queued across all tenants",
    "scorer/staleness":
        "scorer service: max tenant staleness, steps since the latest "
        "delivered chunk's snapshot",
    "scorer/slo_breaches":
        "scorer service: cumulative SLO breach events across tenants",
    "scorer/throughput/t0": "scorer service: tenant 0 rows per second",
    "scorer/throughput/t1": "scorer service: tenant 1 rows per second",
    "scorer/throughput/t2": "scorer service: tenant 2 rows per second",
    "scorer/throughput/t3": "scorer service: tenant 3 rows per second",
    "scorer/queue_depth/t0": "scorer service: tenant 0 ready-queue depth",
    "scorer/queue_depth/t1": "scorer service: tenant 1 ready-queue depth",
    "scorer/queue_depth/t2": "scorer service: tenant 2 ready-queue depth",
    "scorer/queue_depth/t3": "scorer service: tenant 3 ready-queue depth",
    "scorer/staleness/t0": "scorer service: tenant 0 staleness (steps)",
    "scorer/staleness/t1": "scorer service: tenant 1 staleness (steps)",
    "scorer/staleness/t2": "scorer service: tenant 2 staleness (steps)",
    "scorer/staleness/t3": "scorer service: tenant 3 staleness (steps)",
    "scorer/slo_breaches/t0": "scorer service: tenant 0 SLO breach events",
    "scorer/slo_breaches/t1": "scorer service: tenant 1 SLO breach events",
    "scorer/slo_breaches/t2": "scorer service: tenant 2 SLO breach events",
    "scorer/slo_breaches/t3": "scorer service: tenant 3 SLO breach events",
    # obs/* — the metric stream observing itself
    "obs/dropped": "cumulative records dropped by the bounded queue",
    # anomaly/* — flight-recorder health accounting
    "anomaly/triggers": "cumulative anomaly triggers fired this run",
    # host/* — cross-host aggregates merged onto host 0's records
    # (obs/aggregate.py; multi-process runs only)
    "host/reporting": "hosts whose telemetry shard has data this pass",
    "host/min/step_time_s": "fastest host's latest seconds per step",
    "host/max/step_time_s": "slowest host's latest seconds per step",
    "host/spread/step_time_s": "max-min cross-host seconds per step",
    "host/min/stall_s": "smallest per-host input stall this interval",
    "host/max/stall_s": "largest per-host input stall this interval",
    "host/spread/stall_s": "max-min cross-host input stall",
    "host/min/queue_depth": "shallowest per-host prefetch queue",
    "host/max/queue_depth": "deepest per-host prefetch queue",
    "host/spread/queue_depth": "max-min cross-host prefetch queue depth",
    "host/straggler_ratio": "max/median per-host step time (rolling)",
    # prof/* — offline device-time attribution folded back after an
    # anomaly-armed profiler capture (obs/profile_parse.py)
    "prof/scope_frac/mercury_scoring": "device-time share: scoring scope",
    "prof/scope_frac/mercury_grad_sync": "device-time share: grad sync",
    "prof/scope_frac/mercury_augmentation":
        "device-time share: augmentation scope",
    "prof/scope_frac/mercury_input_fuse":
        "device-time share: fused uint8 ingest kernel",
    "prof/scope_frac/mercury_optimizer": "device-time share: optimizer",
    "prof/scope_frac/unattributed":
        "device-time share outside every named scope",
    "prof/h2d_overlap_frac": "H2D copy time hidden under device compute",
    "prof/idle_frac": "device-lane idle gaps over the capture span",
    # threads/* — host thread-fleet liveness (obs/writer.py
    # host_thread_stats + per-queue depths merged at the log gate);
    # audited by graftlint Layer C against lint/thread_manifest.json
    "threads/alive": "live python threads in this process",
    "threads/daemon": "live daemon threads (the worker fleet)",
    "threads/queue_depth/metrics": "async metric records pending drain",
    "threads/queue_depth/prefetch": "committed prefetch batches pending",
    "threads/queue_depth/scorer": "scored chunks pending application",
    # lint/* — runtime retrace guard (lint/tracecheck.py), emitted at the
    # log gate only while Trainer.arm_retrace_guard() has a monitor armed
    "lint/retrace_events": "jaxpr traces observed since the last log tick",
    "lint/compile_count": "XLA backend compiles observed since the last tick",
    # fault/* — deterministic fault-injection plane (faults.py), emitted
    # at the log gate only when config.fault_spec is non-empty
    "fault/injected": "cumulative faults fired by the injection plane",
    "fault/armed": "fault schedule entries still pending (not yet fired)",
    # supervisor/* — host supervisor (runtime/supervisor.py), emitted at
    # the log gate only when config.supervise is on
    "supervisor/level":
        "degradation ladder level: 0 async, 1 sync, 2 frozen, 3 uniform",
    "supervisor/restarts": "cumulative successful unit restarts",
    "supervisor/degradations": "cumulative one-level ladder descents",
    "supervisor/recoveries": "cumulative one-level ladder ascents",
    "supervisor/units_down": "registered units currently failing liveness",
    "supervisor/slo_breaches":
        "cumulative registered-SLO breach events (rising edges)",
    "supervisor/slo_latched":
        "registered SLOs currently latched (breached and not released)",
    "supervisor/probe_pinned":
        "1 while a latched SLO pins the recovery probe, else 0",
    # checkpoint/* — durable checkpoint writer (train/checkpoint.py)
    "checkpoint/write_failures":
        "cumulative failed checkpoint write attempts (retries included)",
    # plan/* — auto-planner (plan/auto.py via train/trainer.py)
    "plan/candidates_considered":
        "plans the auto-planner enumerated for this run's decision",
    "plan/replan_count":
        "cumulative elastic re-plan evaluations since construction",
}

#: Control-plane event kinds (``obs/events.py`` journal rows). Same
#: contract as METRIC_KEYS: a PURE literal (graftlint Layer M parses it
#: with ``ast.literal_eval``), every kind emitted somewhere in the
#: package (GLM04 errors otherwise), every kind documented in the
#: docs/OBSERVABILITY.md kind catalog. ``subsystem/name`` shape; the
#: subsystem names the journal lane in the merged Perfetto timeline.
EVENT_KINDS: Dict[str, str] = {
    # supervisor/* — ladder + restart lifecycle (runtime/supervisor.py)
    "supervisor/slo_breach":
        "a registered SLO latched (rising edge); roots a breach episode",
    "supervisor/slo_release":
        "a latched SLO stopped breaching; parent = the breach event",
    "supervisor/degrade":
        "one-level ladder descent; parent = breach/exhaustion/probe event",
    "supervisor/recover":
        "one-level ladder ascent; parent = the successful probe",
    "supervisor/restart": "a dead host unit was restarted successfully",
    "supervisor/restart_failed": "a unit restart attempt raised",
    "supervisor/exhausted":
        "a unit ran out of restart budget; parent = the failed restart",
    "supervisor/probe_ok":
        "recovery probe succeeded; parent = the degrade it is probing",
    "supervisor/probe_failed":
        "recovery probe raised; parent = the degrade it is probing",
    # scorer/* — multi-tenant scorer service (sampling/scorer_service.py)
    "scorer/tenant_admitted": "a tenant queue was admitted at startup",
    "scorer/wedged": "a tenant was wedged by the scorer_wedge fault",
    "scorer/starved":
        "a tenant's staleness/queue SLO latched (starvation decision)",
    "scorer/snapshot": "a new params snapshot opened a scoring epoch",
    # fault/* — injection plane (faults.py); chaos runs self-describe
    "fault/fired": "a scheduled fault fired at its hook point",
    # elastic/* — (W, L) resharding (train/elastic.py)
    "elastic/reshard_begin": "elastic restore started; detail has old/new W,L",
    "elastic/reshard_end": "elastic restore finished; parent = reshard_begin",
    "elastic/replan":
        "auto-planner re-evaluated the plan after a (W, L) change; "
        "detail carries both scored tables",
    # plan/* — auto-planner decision (train/trainer.py)
    "plan/selected":
        "plan resolution at construction; detail carries the scored table",
    # checkpoint/* — durable generations (train/checkpoint.py)
    "checkpoint/written": "a checkpoint generation was written durably",
    "checkpoint/verified": "a generation passed manifest verification",
    "checkpoint/fallback":
        "restore rejected a generation and fell back to an older one",
    "checkpoint/schema_drift":
        "a restored manifest's state_schema_sha differs from HEAD's",
    # anomaly/* — flight recorder (obs/anomaly.py)
    "anomaly/triggered":
        "an anomaly trigger fired; detail carries the flight-record path",
}

#: Bookkeeping fields that ride along in every record but are not metric
#: tags (no ``prefix/`` namespace, never plotted as series of their own).
RECORD_FIELDS = ("step", "time", "epoch")


def is_registered(key: str) -> bool:
    """True when ``key`` is a known metric tag or bookkeeping field."""
    return key in METRIC_KEYS or key in RECORD_FIELDS
