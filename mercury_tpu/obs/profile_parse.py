"""Offline device-time attribution for profiler captures — jax-free.

PR 6's anomaly engine arms ``jax.profiler`` capture windows, and the
ROADMAP's MFU campaign needs to know where the other ~98% of device time
goes — but the captures were written to disk and never analyzed. This
module closes that loop entirely offline: it parses the capture
(Chrome-trace JSON, gzipped or not, or the raw ``*.xplane.pb``
protobuf via a minimal wire-format reader — no tensorboard, no
tensorflow, no jax), buckets device-lane events by the ``op_name``
scope annotations that graftlint Layers 2/3 already enforce
(``mercury_scoring``, ``mercury_grad_sync``, ``mercury_augmentation``,
``mercury_optimizer``), and emits ``device_time_breakdown.json``:

- per-scope device-time fraction (every unmatched event lands in an
  explicit ``unattributed`` bucket — no silently dropped time);
- H2D overlap fraction — how much of the host-to-device copy time is
  hidden under device compute (the host_stream pipeline's whole job);
- idle gaps — device-lane span minus busy time, the "devices waiting
  on the host" signal MFU alone cannot separate from "slow kernels".

The trainer folds the result back into the metric stream as
``prof/scope_frac/*`` after a capture window closes; ``bench.py``
attaches it to its records; ``obs/report.py`` renders it. The CLI:

    python -m mercury_tpu.obs.profile_parse CAPTURE \\
        --out device_time_breakdown.json

where CAPTURE is a trace file or a profile directory (the newest
capture inside is discovered).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
from typing import Any, Dict, Iterable, Iterator, List, Optional, Tuple

#: Schema tag for ``device_time_breakdown.json``; bump on shape changes.
BREAKDOWN_SCHEMA = "mercury_device_time_breakdown_v1"

#: Scope buckets, in match priority order — the named-scope anchors the
#: step factories emit (lint/audit.py::SCOPES plus the augmentation and
#: optimizer scopes). First substring hit wins, so a nested
#: ``mercury_scoring/mercury_augmentation`` event attributes to the
#: outer anchor listed first.
SCOPES: Tuple[str, ...] = (
    "mercury_scoring",
    "mercury_grad_sync",
    "mercury_augmentation",
    "mercury_input_fuse",
    "mercury_optimizer",
)

#: The explicit catch-all bucket: device-lane time that matched no scope
#: is still counted, never dropped.
UNATTRIBUTED = "unattributed"

#: Breakdown bucket -> metric key (pure literals: graftlint Layer M
#: checks emitted keys against the registry by AST, and f-string-built
#: keys would be invisible to it).
_SCOPE_METRIC_KEYS: Dict[str, str] = {
    "mercury_scoring": "prof/scope_frac/mercury_scoring",
    "mercury_grad_sync": "prof/scope_frac/mercury_grad_sync",
    "mercury_augmentation": "prof/scope_frac/mercury_augmentation",
    "mercury_input_fuse": "prof/scope_frac/mercury_input_fuse",
    "mercury_optimizer": "prof/scope_frac/mercury_optimizer",
    UNATTRIBUTED: "prof/scope_frac/unattributed",
}

_H2D_MARKERS = ("memcpy", "infeed", "h2d", "hosttodevice", "transfer")


# --------------------------------------------------------------- loading
def _read_maybe_gz(path: str) -> bytes:
    with open(path, "rb") as f:
        data = f.read()
    if data[:2] == b"\x1f\x8b":
        data = gzip.decompress(data)
    return data


def load_chrome_events(path: str) -> List[dict]:
    """Raw Chrome trace events from ``path`` (``.json`` / ``.json.gz``;
    either the ``{"traceEvents": [...]}`` envelope or a bare list)."""
    doc = json.loads(_read_maybe_gz(path).decode("utf-8"))
    if isinstance(doc, dict):
        events = doc.get("traceEvents", [])
    else:
        events = doc
    return [e for e in events if isinstance(e, dict)]


# ------------------------------------------------- xplane.pb wire reader
# A minimal protobuf wire-format walker — enough of
# tsl/profiler/protobuf/xplane.proto to pull (plane name, line name,
# event name, timestamp, duration) out of a raw capture without any
# protobuf runtime. Field numbers are stable public API of the profiler.
def _varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _wire_fields(buf: memoryview) -> Iterator[Tuple[int, int, Any]]:
    """Yield ``(field_number, wire_type, value)``; length-delimited
    values come back as memoryviews, scalars as ints."""
    pos = 0
    end = len(buf)
    while pos < end:
        key, pos = _varint(buf, pos)
        field, wtype = key >> 3, key & 0x7
        if wtype == 0:  # varint
            value, pos = _varint(buf, pos)
        elif wtype == 1:  # fixed64
            value = int.from_bytes(buf[pos:pos + 8], "little")
            pos += 8
        elif wtype == 2:  # length-delimited
            length, pos = _varint(buf, pos)
            value = buf[pos:pos + length]
            pos += length
        elif wtype == 5:  # fixed32
            value = int.from_bytes(buf[pos:pos + 4], "little")
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield field, wtype, value


def _decode_xevent(buf: memoryview) -> Dict[str, int]:
    ev = {"metadata_id": 0, "offset_ps": 0, "duration_ps": 0}
    for field, _, value in _wire_fields(buf):
        if field == 1:
            ev["metadata_id"] = int(value)
        elif field == 2:
            ev["offset_ps"] = int(value)
        elif field == 3:
            ev["duration_ps"] = int(value)
    return ev


def _decode_xline(buf: memoryview) -> Dict[str, Any]:
    line: Dict[str, Any] = {"name": "", "timestamp_ns": 0, "events": []}
    for field, _, value in _wire_fields(buf):
        if field == 2:
            line["name"] = bytes(value).decode("utf-8", "replace")
        elif field == 3:
            line["timestamp_ns"] = int(value)
        elif field == 4:
            line["events"].append(_decode_xevent(value))
        elif field == 11 and not line["name"]:
            line["name"] = bytes(value).decode("utf-8", "replace")
    return line


def _decode_metadata_entry(buf: memoryview) -> Tuple[int, str]:
    """One ``map<int64, XEventMetadata>`` entry -> ``(id, name)``."""
    key = 0
    name = ""
    for field, _, value in _wire_fields(buf):
        if field == 1:
            key = int(value)
        elif field == 2:
            for f2, _, v2 in _wire_fields(value):
                if f2 == 2:
                    name = bytes(v2).decode("utf-8", "replace")
    return key, name


def _decode_xplane(buf: memoryview) -> Dict[str, Any]:
    plane: Dict[str, Any] = {"name": "", "lines": [], "event_names": {}}
    for field, _, value in _wire_fields(buf):
        if field == 2:
            plane["name"] = bytes(value).decode("utf-8", "replace")
        elif field == 3:
            plane["lines"].append(_decode_xline(value))
        elif field == 4:
            k, name = _decode_metadata_entry(value)
            plane["event_names"][k] = name
    return plane


def load_xplane_events(path: str) -> List[dict]:
    """Normalized events (Chrome-shaped dicts) from a raw
    ``*.xplane.pb`` capture."""
    buf = memoryview(_read_maybe_gz(path))
    events: List[dict] = []
    pid = 0
    for field, _, value in _wire_fields(buf):
        if field != 1:  # XSpace.planes
            continue
        plane = _decode_xplane(value)
        pid += 1
        tid = 0
        for line in plane["lines"]:
            tid += 1
            t0_us = line["timestamp_ns"] / 1e3
            for ev in line["events"]:
                name = plane["event_names"].get(ev["metadata_id"], "")
                events.append({
                    "ph": "X",
                    "name": name,
                    "ts": t0_us + ev["offset_ps"] / 1e6,
                    "dur": ev["duration_ps"] / 1e6,
                    "pid": pid,
                    "tid": tid,
                    "_pname": plane["name"],
                    "_tname": line["name"],
                })
    return events


# ----------------------------------------------------------- discovery
_CHROME_PATTERNS = ("*.trace.json.gz", "*.trace.json", "trace.json",
                    "trace.json.gz")
_XPLANE_PATTERNS = ("*.xplane.pb",)


def discover_capture_files(root: str) -> List[str]:
    """Capture files under a profile directory, newest capture first.
    Chrome traces win over xplane when both exist (same data, cheaper
    parse); multiple same-format files (one per host) all return."""
    for patterns in (_CHROME_PATTERNS, _XPLANE_PATTERNS):
        found: List[str] = []
        for pat in patterns:
            found.extend(glob.glob(os.path.join(root, "**", pat),
                                   recursive=True))
        if found:
            found = sorted(set(found), key=os.path.getmtime, reverse=True)
            newest_dir = os.path.dirname(found[0])
            return sorted(f for f in found
                          if os.path.dirname(f) == newest_dir)
    return []


def load_events(path: str) -> Tuple[List[dict], str]:
    """Events + the resolved source description for ``path`` (a capture
    file or a directory to search)."""
    if os.path.isdir(path):
        files = discover_capture_files(path)
        if not files:
            raise FileNotFoundError(
                f"no trace capture (*.trace.json[.gz] or *.xplane.pb) "
                f"under {path}")
    else:
        files = [path]
    events: List[dict] = []
    for f in files:
        if f.endswith(".xplane.pb"):
            events.extend(load_xplane_events(f))
        else:
            events.extend(load_chrome_events(f))
    return events, ";".join(files)


# --------------------------------------------------------- normalization
def _lane_names(events: Iterable[dict]) -> Tuple[Dict[int, str],
                                                 Dict[Tuple[int, int], str]]:
    """``pid -> process_name`` and ``(pid, tid) -> thread_name`` from
    Chrome metadata events (xplane-normalized events carry their names
    inline instead)."""
    pnames: Dict[int, str] = {}
    tnames: Dict[Tuple[int, int], str] = {}
    for e in events:
        if e.get("ph") == "M":
            name = (e.get("args") or {}).get("name", "")
            if e.get("name") == "process_name":
                pnames[e.get("pid", 0)] = name
            elif e.get("name") == "thread_name":
                tnames[(e.get("pid", 0), e.get("tid", 0))] = name
    return pnames, tnames


def _is_device_lane(pname: str) -> bool:
    low = pname.lower()
    return ("/device:" in low or low.startswith("tpu")
            or low.startswith("gpu"))


def _merged_busy(intervals: List[Tuple[float, float]]) -> float:
    """Total covered time of possibly-overlapping ``(start, end)``."""
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def _overlap(a: List[Tuple[float, float]],
             b: List[Tuple[float, float]]) -> float:
    """Total time where interval sets ``a`` and ``b`` overlap."""
    a, b = sorted(a), sorted(b)
    i = j = 0
    total = 0.0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            total += e - s
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return total


def _searchable_text(event: dict) -> str:
    parts = [str(event.get("name", ""))]
    args = event.get("args")
    if isinstance(args, dict):
        parts.extend(str(v) for v in args.values()
                     if isinstance(v, (str, int)))
    return " ".join(parts).lower()


# ----------------------------------------------------------- attribution
def attribute_device_time(events: List[dict],
                          scopes: Tuple[str, ...] = SCOPES
                          ) -> Dict[str, Any]:
    """Bucket device-lane time by named scope; every microsecond of
    device-lane busy time lands in a scope bucket or ``unattributed``
    (the accounting identity ``attributed_frac == 1.0`` is part of the
    contract — tests pin it)."""
    pnames, tnames = _lane_names(events)

    complete = [e for e in events if e.get("ph") == "X"
                and float(e.get("dur", 0)) > 0]
    for e in complete:  # xplane events carry names inline
        e.setdefault("_pname", pnames.get(e.get("pid", 0), ""))
        e.setdefault("_tname", tnames.get(
            (e.get("pid", 0), e.get("tid", 0)), ""))

    device = [e for e in complete if _is_device_lane(e["_pname"])]

    def _is_h2d(e: dict) -> bool:
        text = (e["_tname"] + " " + str(e.get("name", ""))).lower()
        return any(m in text for m in _H2D_MARKERS)

    h2d = [e for e in complete if _is_h2d(e)]
    h2d_ids = {id(e) for e in h2d}
    device_compute = [e for e in device if id(e) not in h2d_ids]

    # The op-level lane ("XLA Ops" in both jax and TF exports) is the
    # attribution target; step/module container lanes would double-count
    # every nanosecond. When no lane is tagged, fall back to the busiest
    # single lane — deterministic, and honest about granularity.
    op_lanes = [e for e in device_compute if "xla ops" in e["_tname"].lower()]
    if op_lanes:
        compute = op_lanes
        lane_note = "xla_ops"
    elif device_compute:
        by_lane: Dict[Tuple[int, int], float] = {}
        for e in device_compute:
            key = (e.get("pid", 0), e.get("tid", 0))
            by_lane[key] = by_lane.get(key, 0.0) + float(e["dur"])
        busiest = max(by_lane, key=lambda k: by_lane[k])
        compute = [e for e in device_compute
                   if (e.get("pid", 0), e.get("tid", 0)) == busiest]
        lane_note = "busiest_device_lane"
    else:
        compute = []
        lane_note = "none"

    bucket_us: Dict[str, float] = {s: 0.0 for s in scopes}
    bucket_us[UNATTRIBUTED] = 0.0
    for e in compute:
        text = _searchable_text(e)
        for scope in scopes:
            if scope in text:
                bucket_us[scope] += float(e["dur"])
                break
        else:
            bucket_us[UNATTRIBUTED] += float(e["dur"])

    total_us = sum(float(e["dur"]) for e in compute)
    attributed_us = sum(bucket_us.values())

    compute_iv = [(float(e["ts"]), float(e["ts"]) + float(e["dur"]))
                  for e in compute]
    h2d_iv = [(float(e["ts"]), float(e["ts"]) + float(e["dur"]))
              for e in h2d]
    h2d_total = _merged_busy(h2d_iv)
    h2d_overlap = _overlap(compute_iv, h2d_iv)

    busy_us = _merged_busy(compute_iv)
    span_us = ((max(e[1] for e in compute_iv)
                - min(e[0] for e in compute_iv)) if compute_iv else 0.0)
    idle_us = max(span_us - busy_us, 0.0)

    return {
        "schema": BREAKDOWN_SCHEMA,
        "scopes": {
            name: {"time_us": round(us, 3),
                   "frac": (us / total_us if total_us else 0.0)}
            for name, us in bucket_us.items()
        },
        "total_device_time_us": round(total_us, 3),
        "attributed_frac": (attributed_us / total_us if total_us else 0.0),
        "h2d": {
            "total_us": round(h2d_total, 3),
            "overlap_us": round(h2d_overlap, 3),
            "overlap_frac": (h2d_overlap / h2d_total if h2d_total else 0.0),
        },
        "idle": {
            "span_us": round(span_us, 3),
            "busy_us": round(busy_us, 3),
            "idle_us": round(idle_us, 3),
            "idle_frac": (idle_us / span_us if span_us else 0.0),
        },
        "counts": {
            "events": len(events),
            "device_events": len(compute),
            "h2d_events": len(h2d),
            "lane": lane_note,
        },
    }


def parse_profile(path: str,
                  scopes: Tuple[str, ...] = SCOPES) -> Dict[str, Any]:
    """Load + attribute in one call; ``path`` is a capture file or a
    profile directory."""
    events, source = load_events(path)
    breakdown = attribute_device_time(events, scopes=scopes)
    breakdown["source"] = source
    return breakdown


def scope_frac_metrics(breakdown: Dict[str, Any]) -> Dict[str, float]:
    """Flatten a breakdown into registered ``prof/*`` metric floats —
    what the trainer enqueues after a capture window closes."""
    out: Dict[str, float] = {}
    for name, stats in breakdown.get("scopes", {}).items():
        key = _SCOPE_METRIC_KEYS.get(name)
        if key is not None:
            out[key] = float(stats["frac"])
    out["prof/h2d_overlap_frac"] = float(
        breakdown.get("h2d", {}).get("overlap_frac", 0.0))
    out["prof/idle_frac"] = float(
        breakdown.get("idle", {}).get("idle_frac", 0.0))
    return out


def write_breakdown(breakdown: Dict[str, Any], path: str) -> str:
    """Atomic-write the breakdown JSON; returns ``path``."""
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(breakdown, f, indent=2, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ------------------------------------------------------------------- CLI
def main(argv: Optional[List[str]] = None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m mercury_tpu.obs.profile_parse",
        description="Attribute profiler-capture device time to named "
                    "scopes (offline, jax-free).")
    p.add_argument("capture", help="trace file (.trace.json[.gz], "
                   ".xplane.pb, trace.json) or profile directory")
    p.add_argument("--out", default="device_time_breakdown.json",
                   help="output JSON path (default: %(default)s)")
    args = p.parse_args(argv)
    try:
        breakdown = parse_profile(args.capture)
    except (OSError, ValueError, json.JSONDecodeError) as exc:
        print(f"error: cannot parse {args.capture}: {exc}",
              file=sys.stderr)
        return 2
    write_breakdown(breakdown, args.out)
    total = breakdown["total_device_time_us"]
    print(f"device time: {total / 1e3:.3f} ms over "
          f"{breakdown['counts']['device_events']} events "
          f"({breakdown['counts']['lane']} lane)")
    for name, stats in sorted(breakdown["scopes"].items(),
                              key=lambda kv: -kv[1]["time_us"]):
        print(f"  {name:24s} {stats['frac']:7.2%}  "
              f"{stats['time_us'] / 1e3:10.3f} ms")
    print(f"h2d overlap: {breakdown['h2d']['overlap_frac']:.2%}   "
          f"idle: {breakdown['idle']['idle_frac']:.2%}")
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
