"""Flight recorder + anomaly engine: post-mortem state for runs that die.

A rolling ring of the last N logged steps' metric records (host floats,
captured on the :class:`~mercury_tpu.obs.writer.AsyncMetricWriter` drain
thread — zero training-thread cost) plus the span tracer's ring, dumped
as one self-contained ``flight_record_*.json`` the moment a health
trigger fires:

- **non_finite** — ``train/loss`` or ``train/grad_norm`` is NaN/Inf.
  The training path has no NaN sentinel of its own (a diverged run
  happily trains garbage forever); this is it.
- **slow_step** — a step took more than ``slow_step_factor`` × the
  rolling-median step time (fed per step by the trainer; host floats
  only). Armed only once the median window has filled, so compile
  steps and cold starts don't false-positive.
- **ess_collapse** — ``sampler/ess`` fell below the SLO floor: the IS
  weight distribution degenerated and the estimator variance is blowing
  up (the operational reading of arXiv:1511.06481's score freshness).
- **stall_breach** — host_stream input stall fraction over the log
  interval exceeded its SLO budget: the overlap design is not hiding
  the input path any more.
- **mfu_floor** — measured MFU fell below the SLO floor (evaluated only
  when the device peak is known, i.e. never on CPU hosts).
- **straggler** — the cross-host aggregator's ``host/straggler_ratio``
  (max/median per-host step time over a rolling window,
  ``obs/aggregate.py``) exceeded its factor: one host is pacing the
  whole pod. Needs cross-host telemetry, so it can only fire in
  multi-process runs (or tests that synthesize shards).
- **selection_collapse** — the selection-count ledger's Gini
  coefficient (``sampler_dist/gini``, :mod:`mercury_tpu.obs.sampler_health`)
  exceeded its ceiling: the sampler is hammering a narrow slice of the
  dataset and coverage of the rest has stalled. The dump's detail
  carries the latest score/weight histograms so the shape of the
  distribution at collapse time survives the post-mortem.
- **class_starvation** — ``sampler_dist/class_starved`` reported one
  or more classes whose selection share fell below the starvation
  floor relative to their data share: the sampler has effectively
  dropped part of the label space.
- **is_losing** — ``sampler_dist/var_ratio`` (the periodic grad-variance
  probe; arXiv:1803.00942's gate signal) stayed >= 1 for
  ``var_ratio_patience`` consecutive probe records: importance sampling
  is not reducing gradient variance versus uniform and is costing its
  overhead for nothing. Off-cadence sentinel records (ratio < 0) are
  skipped, not counted as recovery.

On trigger the engine dumps the flight record (ring, spans, config,
manifest, pipeline/pending-selection summary, device memory stats) and —
when ``profile_steps`` > 0 — arms an on-demand ``jax.profiler`` capture
window that the trainer opens for the next M steps, so the *next*
occurrence of a sporadic anomaly is captured at kernel granularity.

Triggers are debounced (``cooldown_steps`` between dumps, ``max_dumps``
per run) and counted: the cumulative count rides on every subsequent
metric record as ``anomaly/triggers`` (heartbeat-visible). When no dump
directory is configured the engine still detects and counts, it just
keeps no files.
"""

from __future__ import annotations

import json
import math
import os
import statistics
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from mercury_tpu.utils.logging import get_logger

_log = get_logger("mercury_tpu.obs.anomaly")

#: Schema tag for ``flight_record_*.json``; bump on shape changes.
FLIGHT_RECORD_SCHEMA = "mercury_flight_record_v1"


def device_memory_stats() -> Dict[str, Dict[str, int]]:
    """Per-local-device allocator stats (``bytes_in_use`` etc.), empty
    when the backend exposes none (CPU). Never raises."""
    out: Dict[str, Dict[str, int]] = {}
    try:
        import jax

        for d in jax.local_devices():
            try:
                stats = d.memory_stats()
            except Exception:
                stats = None
            if stats:
                out[f"{d.platform}:{d.id}"] = {
                    k: int(v) for k, v in stats.items()
                    if isinstance(v, (int, float))
                }
    except Exception:
        pass
    return out


def _sampler_histograms(record: Dict[str, float]) -> Dict[str, float]:
    """The record's per-bin sampler histogram keys, for attaching the
    offending distribution to a sampler-health flight record."""
    return {
        k: record[k]
        for k in sorted(record)
        if k.startswith("sampler_dist/score_hist/")
        or k.startswith("sampler_dist/w_hist/")
    }


class AnomalyEngine:
    """Continuous health evaluation + flight-record dumps.

    Two feed points, on two different threads:

    - :meth:`observe_step_time` — trainer thread, once per step: cheap
      float bookkeeping for the slow-step trigger. ~1 µs.
    - :meth:`observe_record` — metric-writer drain thread, once per
      logged record: rings the record, checks the value-based triggers,
      attaches ``anomaly/triggers``. Registered as a writer observer by
      the trainer, so it costs the training thread nothing.

    ``context_fn`` supplies the dump's run context (config, manifest,
    pipeline summary) lazily — evaluated only when a trigger actually
    fires."""

    #: Step-time samples required before slow_step arms (compile /
    #: cold-start steps would otherwise seed a garbage median).
    MIN_STEP_SAMPLES = 16

    def __init__(
        self,
        *,
        ring_steps: int = 64,
        slow_step_factor: float = 3.0,
        ess_floor: float = 0.0,
        stall_frac_max: float = 0.0,
        mfu_floor: float = 0.0,
        straggler_factor: float = 0.0,
        gini_max: float = 0.0,
        starved_classes: float = 0.0,
        var_ratio_patience: int = 0,
        cooldown_steps: int = 200,
        max_dumps: int = 8,
        dump_dir: Optional[str] = None,
        tracer=None,
        context_fn: Optional[Callable[[], Dict[str, Any]]] = None,
        profile_steps: int = 0,
        journal=None,
    ) -> None:
        if ring_steps < 1:
            raise ValueError(f"ring_steps must be >= 1, got {ring_steps}")
        self.ring: deque = deque(maxlen=int(ring_steps))
        self.slow_step_factor = float(slow_step_factor)
        self.ess_floor = float(ess_floor)
        self.stall_frac_max = float(stall_frac_max)
        self.mfu_floor = float(mfu_floor)
        self.straggler_factor = float(straggler_factor)
        self.gini_max = float(gini_max)
        self.starved_classes = float(starved_classes)
        self.var_ratio_patience = int(var_ratio_patience)
        self.cooldown_steps = int(cooldown_steps)
        self.max_dumps = int(max_dumps)
        self.dump_dir = dump_dir
        self.tracer = tracer
        self.context_fn = context_fn
        self.profile_steps = int(profile_steps)
        # Control-plane event journal (obs/events.py); None when off.
        self.journal = journal

        self.triggers = 0
        self.trigger_counts: Dict[str, int] = {}
        self.dumps: List[str] = []
        self._last_trigger_step: Optional[int] = None
        self._lock = threading.Lock()

        # Slow-step state (trainer thread only).
        self._step_times: deque = deque(maxlen=128)
        self._median_s: Optional[float] = None
        self._since_median = 0

        # Stall-fraction state (drain thread only).
        self._prev_record_time: Optional[float] = None

        # is_losing state (drain thread only): consecutive logged probe
        # records with var_ratio >= 1. Sentinel records (< 0, probe off
        # cadence) neither count nor reset; a genuine < 1 record resets.
        self._var_ratio_breaches = 0

        # Profiler arming (set under the lock, consumed by the trainer).
        self._profile_pending = 0

    # ----------------------------------------------------- trainer thread
    def observe_step_time(self, step: int, dt_s: float,
                          steps: int = 1) -> None:
        """One loop iteration's wall time (``steps`` > 1 for scanned
        chunks — the per-step time is the mean). Host floats only."""
        per_step = dt_s / max(int(steps), 1)
        self._step_times.append(per_step)
        self._since_median += 1
        # Median refresh is amortized: every 16 appends, or whenever the
        # cache is cold. statistics.median over <=128 floats is ~10 µs;
        # at one refresh per 16 steps it vanishes.
        if self._median_s is None or self._since_median >= 16:
            if len(self._step_times) >= self.MIN_STEP_SAMPLES:
                self._median_s = statistics.median(self._step_times)
            self._since_median = 0
        if (
            self.slow_step_factor > 0
            and self._median_s is not None
            and len(self._step_times) >= self.MIN_STEP_SAMPLES
            and per_step > self.slow_step_factor * self._median_s
        ):
            self._trigger(
                "slow_step", step,
                {"step_time_s": per_step,
                 "rolling_median_s": self._median_s,
                 "factor": per_step / max(self._median_s, 1e-12)},
            )

    def take_profile_request(self) -> int:
        """Steps of ``jax.profiler`` capture requested by the latest
        trigger; clears the request. Trainer-polled once per step."""
        # Lock-free fast path: a stale read costs at most one step of
        # capture latency and self-corrects on the next poll; taking the
        # lock every step would serialize the trainer against _trigger.
        if not self._profile_pending:  # graftlint: disable=GL120 -- vetted lock-free fast path; stale read self-corrects next poll, the authoritative swap below holds the lock
            return 0
        with self._lock:
            n, self._profile_pending = self._profile_pending, 0
        return n

    # ------------------------------------------------------- drain thread
    def observe_record(self, record: Dict[str, float]) -> None:
        """Ring one host metric record and evaluate the value-based
        triggers. Mutates ``record`` to attach ``anomaly/triggers``
        (the writer observer contract) once any trigger has fired."""
        step = int(record.get("step", -1))
        self.ring.append(dict(record))

        for key in ("train/loss", "train/grad_norm"):
            v = record.get(key)
            if v is not None and not math.isfinite(v):
                self._trigger("non_finite", step, {"key": key, "value": v})
                break

        ess = record.get("sampler/ess")
        if self.ess_floor > 0 and ess is not None and ess < self.ess_floor:
            self._trigger("ess_collapse", step,
                          {"ess": ess, "floor": self.ess_floor})

        stall = record.get("data/stall_s")
        now = record.get("time")
        if stall is not None and now is not None:
            prev = self._prev_record_time
            self._prev_record_time = now
            if (self.stall_frac_max > 0 and prev is not None
                    and now > prev):
                frac = stall / (now - prev)
                if frac > self.stall_frac_max:
                    self._trigger(
                        "stall_breach", step,
                        {"stall_frac": frac,
                         "budget": self.stall_frac_max},
                    )

        mfu = record.get("perf/mfu")
        # mfu == 0.0 means "peak unknown" (CPU hosts) — not a breach.
        if self.mfu_floor > 0 and mfu and mfu < self.mfu_floor:
            self._trigger("mfu_floor", step,
                          {"mfu": mfu, "floor": self.mfu_floor})

        # Attached upstream by the cross-host aggregator observer (it
        # must be registered BEFORE this engine in the writer's
        # observer list — the trainer guarantees that order).
        ratio = record.get("host/straggler_ratio")
        if (self.straggler_factor > 0 and ratio is not None
                and ratio > self.straggler_factor):
            detail: Dict[str, Any] = {"ratio": ratio,
                                      "factor": self.straggler_factor}
            for key in ("host/min/step_time_s", "host/max/step_time_s",
                        "host/spread/step_time_s", "host/reporting"):
                if key in record:
                    detail[key] = record[key]
            self._trigger("straggler", step, detail)

        gini = record.get("sampler_dist/gini")
        if self.gini_max > 0 and gini is not None and gini > self.gini_max:
            detail = {"gini": gini, "ceiling": self.gini_max}
            cov = record.get("sampler_dist/frac_never_selected")
            if cov is not None:
                detail["frac_never_selected"] = cov
            detail.update(_sampler_histograms(record))
            self._trigger("selection_collapse", step, detail)

        starved = record.get("sampler_dist/class_starved")
        if (self.starved_classes > 0 and starved is not None
                and starved >= self.starved_classes):
            detail = {"class_starved": starved,
                      "threshold": self.starved_classes}
            for key in ("sampler_dist/class_share_min",
                        "sampler_dist/class_share_max"):
                if key in record:
                    detail[key] = record[key]
            detail.update(_sampler_histograms(record))
            self._trigger("class_starvation", step, detail)

        ratio = record.get("sampler_dist/var_ratio")
        if self.var_ratio_patience > 0 and ratio is not None:
            # ratio < 0 is the off-cadence sentinel: no probe ran this
            # record, so it carries no evidence either way.
            if ratio >= 1.0:
                self._var_ratio_breaches += 1
                if self._var_ratio_breaches >= self.var_ratio_patience:
                    detail = {"var_ratio": ratio,
                              "consecutive_breaches":
                                  self._var_ratio_breaches,
                              "patience": self.var_ratio_patience}
                    detail.update(_sampler_histograms(record))
                    self._var_ratio_breaches = 0
                    self._trigger("is_losing", step, detail)
            elif ratio >= 0.0:
                self._var_ratio_breaches = 0

        with self._lock:
            triggers = self.triggers
        if triggers:
            record["anomaly/triggers"] = float(triggers)

    # ----------------------------------------------------------- triggering
    def _trigger(self, kind: str, step: int,
                 detail: Dict[str, Any]) -> None:
        with self._lock:
            self.triggers += 1
            self.trigger_counts[kind] = self.trigger_counts.get(kind, 0) + 1
            last = self._last_trigger_step
            debounced = (
                last is not None
                and step >= 0
                and step - last < self.cooldown_steps
            ) or len(self.dumps) >= self.max_dumps
            if not debounced:
                self._last_trigger_step = step
                if self.profile_steps > 0:
                    self._profile_pending = self.profile_steps
        _log.warning("anomaly trigger %s at step %d: %s", kind, step, detail)
        if self.tracer is not None:
            self.tracer.instant(f"anomaly/{kind}", cat="anomaly", step=step)
        path = None
        if not debounced:
            path = self.dump_flight_record(kind, step, detail)
            if path:
                _log.warning("flight record written: %s", path)
        if self.journal is not None:
            try:
                # Debounced triggers are journaled too: the journal is
                # the decision audit, and "fired but suppressed" is a
                # decision. The flight-record path (when one was dumped)
                # rides in detail so the DAG links to the full dump.
                self.journal.emit(
                    "anomaly/triggered", step,
                    detail={"trigger": kind, "debounced": bool(debounced),
                            "flight_record": path})
            except Exception:
                pass  # journal failures never take down the engine

    def dump_flight_record(self, kind: str, step: int,
                           detail: Optional[Dict[str, Any]] = None
                           ) -> Optional[str]:
        """Write the self-contained post-mortem JSON; returns its path,
        or None when no dump directory is configured. Never raises —
        a failed dump must not take the run down with it."""
        if not self.dump_dir:
            return None
        try:
            # Trigger tallies are written by _trigger on both the drain
            # and trainer threads — snapshot them under the lock before
            # the (slow, unlocked) serialization below.
            with self._lock:
                trigger_counts = dict(self.trigger_counts)
                triggers_total = self.triggers
            doc: Dict[str, Any] = {
                "schema": FLIGHT_RECORD_SCHEMA,
                "timestamp": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime()),
                "trigger": {"kind": kind, "step": int(step),
                            "detail": detail or {}},
                "trigger_counts": trigger_counts,
                "triggers_total": triggers_total,
                "ring": list(self.ring),
                "spans": (self.tracer.snapshot()
                          if self.tracer is not None else []),
                "step_time_window_s": [round(t, 6)
                                       for t in self._step_times],
                "rolling_median_step_s": self._median_s,
                "device_memory": device_memory_stats(),
            }
            if self.context_fn is not None:
                try:
                    doc.update(self.context_fn())
                except Exception as exc:
                    doc["context_error"] = f"{type(exc).__name__}: {exc}"
            os.makedirs(self.dump_dir, exist_ok=True)
            name = f"flight_record_step{max(step, 0)}_{kind}.json"
            path = os.path.join(self.dump_dir, name)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=2, default=str)
                f.write("\n")
            os.replace(tmp, path)
            with self._lock:
                self.dumps.append(path)
            return path
        except Exception as exc:
            _log.warning("flight-record dump failed: %s: %s",
                         type(exc).__name__, exc)
            return None
